"""Grounding quality with zero egress: synthetic screenshots -> trained
Qwen2-VL-test checkpoint -> point-in-bbox accuracy (round-4 VERDICT next #4).

Until round 5, grounding was the one model family with zero semantic proof:
``benches/bench_grounding.py`` grounded a random-noise image with
random-init weights (latency only), and the executor's VL click fallback
(services/executor/actions.py grounded_click) had never been shown to click
the right thing. This module closes that the same way ``train/distill.py``
did for STT — a deterministic synthetic task at the scale this zero-egress
image permits, trained end to end through the REAL serving stack:

- ``sample_page`` renders a 112x112 "web page" of 3 visually distinct
  widgets (search box, submit button, cart, menu, ...) at random
  non-overlapping positions with known bboxes. Widget identity is carried
  by color/shape (plus a drawn text label): a 2-layer d32 vision tower
  cannot OCR 5-px glyphs, so class-identifiable appearance is the visual
  analog of the acoustic font ``distill.render_speech`` uses for STT.
- ``train_grounding`` teacher-forces the exact serve-time token layout
  (vision prefix + ``serve.grounding.prompt_text`` chat template +
  grammar-shaped ``{"point":[x,y],"label":"..."}`` target) through
  ``models.qwen2vl.forward_embeds``, training vision tower + LM jointly.
- ``score_grounding`` runs the REAL ``GroundingEngine.ground`` (letterbox,
  M-RoPE prefill, constrained whole-decode-in-one-dispatch loop) on
  HELD-OUT page layouts and scores point-in-target-bbox accuracy.
  Chance for a uniform-random point is the mean target-bbox area fraction
  (~4% of the page); picking the center of a random widget scores ~1/3.

Reference parity: this AUGMENTS the reference's DOM-scan-only targeting
(apps/executor/src/dom-analyzer.ts:34-448) — the capability BASELINE
config 5 names; the reference has no vision path at all.
"""

from __future__ import annotations

import json
import time
from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

GROUND_CKPT = "grounding-tiny"

PAGE = 112  # == qwen2vl-test vision img_size: letterbox is the identity

# class name -> (fill RGB, (w, h) base size). Colors are far apart in RGB
# so 28-px vision cells resolve identity; sizes differ so shape helps too.
WIDGETS: dict[str, tuple[tuple[int, int, int], tuple[int, int]]] = {
    "search box": ((66, 133, 244), (52, 14)),
    "submit button": ((52, 168, 83), (34, 16)),
    "cancel button": ((234, 67, 53), (34, 16)),
    "cart button": ((251, 140, 0), (26, 18)),
    "menu button": ((156, 39, 176), (20, 20)),
    "login button": ((0, 172, 193), (30, 14)),
    "upload button": ((121, 85, 72), (30, 18)),
    "home link": ((255, 214, 0), (24, 12)),
}

TRAIN_TEMPLATES = [
    "click the {c}", "press the {c}", "tap the {c}", "open the {c}",
    "find the {c}",
]
# held-out phrasing: score_grounding uses these, so the eval also proves the
# instruction side survives a template never seen in training
EVAL_TEMPLATES = ["click the {c}", "select the {c}"]


def sample_page(rng: np.random.Generator, n_widgets: int = 3):
    """One synthetic page: returns (img uint8 (PAGE, PAGE, 3),
    widgets=[{"cls", "bbox": (x, y, w, h)}]). Placement is rejection-
    sampled to keep bboxes disjoint (8 px margin) so point-in-bbox is
    unambiguous."""
    from PIL import Image, ImageDraw

    im = Image.new("RGB", (PAGE, PAGE), (250, 250, 250))
    draw = ImageDraw.Draw(im)
    classes = rng.choice(list(WIDGETS), size=n_widgets, replace=False)
    placed: list[dict] = []
    for cls in classes:
        color, (bw, bh) = WIDGETS[cls]
        bw = int(bw * rng.uniform(0.85, 1.15))
        bh = int(bh * rng.uniform(0.85, 1.15))
        for _ in range(100):
            x = int(rng.integers(2, PAGE - bw - 2))
            y = int(rng.integers(2, PAGE - bh - 2))
            if all(x + bw + 8 < p["bbox"][0] or p["bbox"][0] + p["bbox"][2] + 8 < x
                   or y + bh + 8 < p["bbox"][1] or p["bbox"][1] + p["bbox"][3] + 8 < y
                   for p in placed):
                break
        else:  # crowded sample: skip this widget rather than overlap
            continue
        draw.rectangle([x, y, x + bw, y + bh], fill=color,
                       outline=(40, 40, 40))
        # tiny label text: auxiliary realism; identity signal is color/shape
        draw.text((x + 2, y + max(0, bh // 2 - 5)), cls.split()[0][:6],
                  fill=(255, 255, 255))
        placed.append({"cls": str(cls), "bbox": (x, y, bw, bh)})
    return np.asarray(im, dtype=np.uint8), placed


def _target_string(bbox: tuple[int, int, int, int], cls: str,
                   snap: bool = False) -> str:
    """``snap=True`` quantizes the point to the center of its 28-px vision
    cell — the curriculum's phase-A/B target (16 possible digit strings
    turn the coordinate readout into a classification; see
    train_grounding)."""
    x, y, w, h = bbox
    cx, cy = x + w / 2, y + h / 2
    if snap:
        gm = PAGE // 28  # merged vision grid
        cx = (min(gm - 1, int(cx // 28)) + 0.5) * 28
        cy = (min(gm - 1, int(cy // 28)) + 0.5) * 28
    xn = min(999, round(cx / PAGE * 1000))
    yn = min(999, round(cy / PAGE * 1000))
    return json.dumps({"point": [xn, yn], "label": cls},
                      separators=(",", ":"))


def build_rows(n_pages: int, seed: int, templates: list[str] | None = None,
               n_widgets: int = 3, snap: bool = False):
    """(images f32 (R, PAGE, PAGE, 3), instructions, targets, widgets-per-
    page). One training row per page: a uniformly chosen widget is the
    target."""
    rng = np.random.default_rng(seed)
    templates = templates or TRAIN_TEMPLATES
    imgs, instrs, targets, pages = [], [], [], []
    for _ in range(n_pages):
        img, widgets = sample_page(rng, n_widgets=n_widgets)
        if not widgets:
            continue
        w = widgets[int(rng.integers(len(widgets)))]
        t = templates[int(rng.integers(len(templates)))]
        imgs.append(img.astype(np.float32) / 255.0)
        instrs.append(t.format(c=w["cls"]))
        targets.append(_target_string(w["bbox"], w["cls"], snap=snap))
        pages.append(widgets)
    return np.stack(imgs), instrs, targets, pages


def train_grounding(
    steps: int = 4000,
    batch: int = 16,
    n_pages: int = 512,
    lr: float = 2e-3,
    seed: int = 0,
    stream: bool = True,
    phases: tuple[tuple[float, int, bool], ...] = (
        (0.3, 1, True), (0.3, 3, True), (0.4, 3, False)),
    init_params_from: dict | None = None,
    log=None,
):
    """Train qwen2vl-test on the synthetic grounding task; returns
    (cfg, params, stats). Serve via ``grounding_engine_from``.

    ``stream=True``: every step renders FRESH pages (never-repeating
    layouts), so predicting a widget's digits requires READING its position
    from the vision tokens. The fixed-page variant plateaued with held-out
    point-in-bbox at chance (0.025 vs 0.036) while label accuracy
    generalized (0.575 vs 0.125 chance): with 448 reusable pages the model
    memorized page->point instead of learning localization.

    ``phases``: (fraction-of-steps, n_widgets, snap-to-cell) curriculum.
    Flat training on the full task NEVER forms the position-readout
    circuit (loss plateaus ~0.65 with point accuracy at chance, measured
    across 4 variants up to 6000 steps): the gradient must discover
    attend-to-widget AND pos-embedding->digit-string decoding jointly.
    Snapping phase-A/B targets to the 16 cell centers turns the readout
    into a small classification — loss dives 0.65 -> 0.004 within 1200
    steps and the circuit then survives the move to exact coordinates in
    phase C. Phase A uses single-widget pages (no class matching), B adds
    distractors, C un-snaps the targets to the serve distribution."""
    import optax

    from ..models.qwen2vl import (
        PRESETS,
        embed_tokens,
        forward_embeds,
        init_kv_cache,
        init_params,
        text_positions3,
        vision_forward,
        vision_token_positions,
    )
    from ..serve.grounding import build_grounding_fsm, prompt_text

    if stream and n_pages != 512:
        import warnings

        warnings.warn(
            "n_pages sizes a FIXED page set and is ignored under "
            "stream=True (fresh pages every step); pass stream=False to "
            "use it", stacklevel=2)
    tok, _ = build_grounding_fsm()
    cfg = replace(PRESETS["qwen2vl-test"], vocab_size=tok.vocab_size)
    nv, gm = cfg.vision.n_tokens, cfg.vision.merged_grid

    # fixed (T, ...) shapes across steps: ONE compiled program. T is sized
    # by the worst case over templates x classes x 3-digit coordinates, so
    # no streaming row can exceed it (a probe-derived T risked silently
    # truncating the target tail of rarer long rows — reviewer finding).
    def _row_len(ins: str, tgt: str) -> int:
        p = [tok.bos_id] + tok.encode(prompt_text(ins), bos=False, eos=False)
        return len(p) + len(tok.encode(tgt, bos=False, eos=False)) + 1

    T = max(
        _row_len(t.format(c=cls),
                 json.dumps({"point": [888, 888], "label": cls},
                            separators=(",", ":")))
        for t in (*TRAIN_TEMPLATES, *EVAL_TEMPLATES) for cls in WIDGETS) + 4

    def encode_rows(instrs, targets, T=T):
        """Returns (toks, mask, keep): rows longer than T are DROPPED (keep
        marks survivors so the caller can drop the matching images) rather
        than truncated — a clipped target would train clipped outputs."""
        rows, loss_lo, keep = [], [], []
        for ins, tgt in zip(instrs, targets):
            p = [tok.bos_id] + tok.encode(prompt_text(ins), bos=False, eos=False)
            t = tok.encode(tgt, bos=False, eos=False) + [tok.eos_id]
            if len(p) + len(t) > T:
                keep.append(False)
                continue
            keep.append(True)
            rows.append(p + t)
            loss_lo.append(len(p))  # predictions at [len(p)-1, len-2] score
        R = len(rows)
        toks = np.full((R, T), tok.pad_id, np.int32)
        mask = np.zeros((R, T), np.float32)
        for i, (r, lo) in enumerate(zip(rows, loss_lo)):
            toks[i, : len(r)] = r
            mask[i, lo: len(r)] = 1.0  # CE on target + eos tokens
        return toks, mask, np.asarray(keep, bool)

    vis_pos = np.asarray(vision_token_positions(cfg.vision))

    if init_params_from is not None:
        # warm start (continue a curriculum from a saved checkpoint)
        params = jax.tree.map(
            lambda a: jnp.asarray(a, jnp.float32), init_params_from)
    else:
        params = jax.jit(partial(init_params, cfg, dtype=jnp.float32))(
            jax.random.PRNGKey(seed))
    sched = optax.cosine_decay_schedule(lr, steps, alpha=0.05)
    optimizer = optax.adamw(sched, weight_decay=0.01)
    opt_state = optimizer.init(params)

    def loss_fn(params, img_j, toks_j, mask_j):
        B = img_j.shape[0]
        vis = vision_forward(params["vision"], cfg.vision, img_j)  # (B, nv, D)
        txt = embed_tokens(params, toks_j)
        embeds = jnp.concatenate([vis, txt], axis=1)
        S = nv + T
        slots = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
        vp = jnp.broadcast_to(jnp.asarray(vis_pos)[:, None, :], (3, B, nv))
        tp = text_positions3(gm, T, batch=B)
        pos3 = jnp.concatenate([vp, tp], axis=2)
        cache = init_kv_cache(cfg, B, S, dtype=jnp.float32)
        logits, _ = forward_embeds(params, cfg, embeds, slots, pos3, cache)
        lt = logits[:, nv - 1: nv + T - 1]  # predicts text token at same idx
        logp = jax.nn.log_softmax(lt.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, toks_j[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * mask_j) / jnp.maximum(jnp.sum(mask_j), 1.0)

    # analyze: ok[jit-sentinel] -- offline training step, not a serving dispatch — the recompile sentinel guards the serving plane
    @jax.jit
    def step_fn(params, opt_state, img_j, toks_j, mask_j):
        loss, grads = jax.value_and_grad(loss_fn)(params, img_j, toks_j, mask_j)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    bounds = []
    acc = 0.0
    for frac, nw, snap in phases:
        acc += frac
        bounds.append((int(round(acc * steps)), nw, snap))
    bounds[-1] = (steps, bounds[-1][1], bounds[-1][2])

    def phase_for(s: int) -> tuple[int, bool]:
        for hi, nw, snap in bounds:
            if s < hi:
                return nw, snap
        return bounds[-1][1], bounds[-1][2]

    if stream:
        def batch_for(s: int):
            # over-request: sample_page drops a widget on crowded layouts,
            # a page with zero widgets is skipped, and encode_rows drops
            # over-length rows — the compiled step shape needs exactly
            # `batch` rows every time
            nw, snap = phase_for(s)
            n_req = batch + 2
            while True:
                imgs, instrs, targets, _ = build_rows(
                    n_req, seed=seed + 4000 + s, n_widgets=nw, snap=snap)
                toks, mask, kept = encode_rows(instrs, targets)
                if toks.shape[0] >= batch:
                    return imgs[kept][:batch], toks[:batch], mask[:batch]
                n_req *= 2
    else:
        imgs_e, instrs_e, targets_e, _ = build_rows(n_pages, seed)
        toks_e, mask_e, kept_e = encode_rows(instrs_e, targets_e)
        imgs_e = imgs_e[kept_e]
        R = imgs_e.shape[0]
        erng = np.random.default_rng(seed + 1)

        def batch_for(s: int):
            pick = erng.choice(R, size=batch, replace=False)
            return imgs_e[pick], toks_e[pick], mask_e[pick]

    t0 = time.perf_counter()
    first = ema = None
    n_seen = 0
    for s in range(steps):
        imgs, toks, mask = batch_for(s)
        n_seen += imgs.shape[0]
        params, opt_state, loss = step_fn(
            params, opt_state, jnp.asarray(imgs),
            jnp.asarray(toks), jnp.asarray(mask))
        lf = float(loss)
        first = lf if first is None else first
        ema = lf if ema is None else 0.98 * ema + 0.02 * lf
        if log and (s % 200 == 0 or s == steps - 1):
            log(f"grounding step {s}/{steps} loss {lf:.4f} (ema {ema:.4f})")
    stats = {"steps": steps, "pages": n_seen, "stream": stream,
             "first_loss": first, "final_loss_ema": round(ema, 4),
             "train_s": round(time.perf_counter() - t0, 1)}
    return cfg, params, stats


def grounding_engine_from(cfg, params, max_len: int = 192):
    """Serve a trained (f32) grounding checkpoint in bf16 — the engine's
    serving dtype (its KV cache is bf16; f32 params would down-cast on
    every cache write). The quality eval runs through exactly this cast,
    so the reported accuracy is the served accuracy."""
    from ..serve.grounding import GroundingEngine

    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16)
        if hasattr(a, "dtype") and a.dtype == jnp.float32 else a, params)
    return GroundingEngine(params=jax.device_put(params), cfg=cfg,
                           max_len=max_len)


def save_ground_ckpt(root: str, cfg, params, stats: dict) -> str:
    """distill.save_ckpt can't round-trip Qwen2VLConfig (its ``vision``
    field is a nested dataclass that json-serializes as a string), so the
    grounding checkpoint flattens it under a "vision" sub-dict."""
    import os

    from ..ckpt.orbax_io import save_params

    path = os.path.join(root, GROUND_CKPT)
    save_params(path, params)
    meta = {"config": {
        **{k: getattr(cfg, k) for k in cfg.__dataclass_fields__
           if k != "vision"},
        "vision": {k: getattr(cfg.vision, k)
                   for k in cfg.vision.__dataclass_fields__},
    }, "stats": stats}
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return path


def load_ground_ckpt(root: str):
    """Returns (cfg, params) or None when absent."""
    import os

    from ..ckpt.orbax_io import restore_params
    from ..models.qwen2vl import Qwen2VLConfig, VisionConfig

    path = os.path.join(root, GROUND_CKPT)
    meta_path = os.path.join(path, "meta.json")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        raw = json.load(f)["config"]
    vision = VisionConfig(**raw.pop("vision"))
    raw = {k: (tuple(v) if isinstance(v, list) else v) for k, v in raw.items()}
    cfg = Qwen2VLConfig(vision=vision, **raw)
    return cfg, restore_params(path)


def score_grounding(engine, n_pages: int = 40, seed: int = 1234) -> dict:
    """Held-out accuracy through the REAL GroundingEngine.ground: fresh
    layouts (disjoint seed) and an eval template bank including a phrasing
    never trained on. Returns {point_in_bbox, label_match, chance, pages}.
    ``chance`` is the mean target-bbox area fraction — what a uniform
    random point would score."""
    from ..serve.grounding import GroundingEngine

    rng = np.random.default_rng(seed)
    hits = labels = total = 0
    chance_area = 0.0
    for i in range(n_pages):
        img, widgets = sample_page(rng)
        if not widgets:
            continue
        w = widgets[int(rng.integers(len(widgets)))]
        t = EVAL_TEMPLATES[i % len(EVAL_TEMPLATES)]
        res = engine.ground(img, t.format(c=w["cls"]), max_new_tokens=32)
        px, py = GroundingEngine.to_page_px(res, PAGE, PAGE)
        x, y, bw, bh = w["bbox"]
        hits += int(x <= px <= x + bw and y <= py <= y + bh)
        labels += int(res.label == w["cls"])
        chance_area += (bw * bh) / (PAGE * PAGE)
        total += 1
    return {"point_in_bbox": round(hits / max(total, 1), 4),
            "label_match": round(labels / max(total, 1), 4),
            "chance": round(chance_area / max(total, 1), 4),
            "pages": total}
