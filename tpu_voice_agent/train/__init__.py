from .step import TrainState, make_train_step, loss_fn

__all__ = ["TrainState", "make_train_step", "loss_fn"]
