"""In-tree tiny-checkpoint training: REAL neural quality numbers, zero egress.

The reference's quality comes free from cloud APIs (gpt-4o-mini behind
apps/brain/src/llm.ts:17-30, Deepgram nova-3 behind
apps/voice/src/deepgram.ts:33-45). This environment has no egress and no
external checkpoints, so quality evidence must be MANUFACTURED in-tree
(round-3 VERDICT missing #1 / next #2):

- ``train_intent_model`` distills the intent-parse task into a test-tiny
  Llama: a synthetic utterance->intent corpus (the rule parser as teacher,
  template banks disjoint from the golden eval set) is trained with a SHORT
  prompt — the few-shot scaffolding lives in the weights, not the context
  (the ``train/step.py`` design note made real). The result scores on
  ``evals.golden`` through the real grammar-constrained engine.
- ``train_whisper_overfit`` overfits whisper-test on synthetic audio: each
  character renders as a fixed-frequency tone chord ("acoustic font"), so
  transcription is learnable by a 2-layer encoder-decoder. WER over the
  pairs drops far below 1.0, proving mel -> encoder -> cross-KV -> decode
  -> text end to end with trained weights.

Both paths save with ``ckpt.orbax_io`` and reload through the serving
stack — the full train -> checkpoint -> constrained-serve loop.
"""

from __future__ import annotations

import json
import time
from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# ------------------------------------------------------------------ corpus

_ADJS = [
    "red", "blue", "cheap", "wireless", "gaming", "ergonomic", "portable",
    "vintage", "compact", "noise cancelling", "leather", "steel", "organic",
    "budget", "premium", "refurbished", "foldable", "waterproof",
]
_NOUNS = [
    "shoes", "laptops", "monitors", "desk lamps", "backpacks", "headsets",
    "coffee makers", "office chairs", "phone cases", "keyboards", "tents",
    "water bottles", "cameras", "speakers", "routers", "microphones",
    "notebooks", "standing desks", "power banks", "webcams", "toasters",
]
_SITES = [
    "news.org", "shop.io", "wiki.net", "blog.dev", "store.net", "docs.io",
    "mail.org", "maps.net", "forum.dev", "photos.io",
]
_BUTTONS = [
    "submit", "login", "sign up", "add to cart", "buy now", "next",
    "accept", "save", "download", "subscribe", "apply", "continue",
]
_DOCS = ["resume", "invoice", "report", "portfolio", "transcript"]
_FIELDS = ["price", "rating", "date", "name", "popularity"]
_ORDINALS = {
    "first": 1, "second": 2, "third": 3, "fourth": 4, "fifth": 5,
    "sixth": 6, "seventh": 7, "eighth": 8, "ninth": 9, "tenth": 10,
}
_CHATTER = [
    "what is the weather like", "tell me a joke", "how are you today",
    "play some music", "what time is it", "remind me tomorrow",
    "who won the game", "turn on the lights",
]

# golden-set texts must NEVER appear in training (held-out means held out).
# Dialog turns count too: a golden dialog's SEARCH phrase showing up as a
# training utterance would hand the copy task its answer.
def _golden_texts() -> set[str]:
    from ..evals.golden import GOLDEN_DIALOGS, GOLDEN_INTENT_CASES

    texts = {c.text for c in GOLDEN_INTENT_CASES}
    for d in GOLDEN_DIALOGS:
        texts.update(d.turns)
    return texts


_SYLLS = ["ka", "lo", "mi", "zu", "ta", "ren", "vor", "bex", "dal", "nix",
          "pra", "sum", "tir", "wob", "gim", "fen", "hul", "jaz", "qui", "yol"]
_CONS = "bcdfghjklmnpqrstvwxz"
_VOWS = "aeiou"


def _pseudo_word(rng) -> str:
    """Novel pronounceable non-word — the model cannot memorize these, so
    search queries / button names built from them force TRUE copying (an
    induction-head behavior) instead of bank-item recall. Two generators:
    syllable-bank compounds (common BPE pieces) and char-level CV strings
    (rare pieces / byte fallbacks — the hardest copy class, covering real
    but bank-unseen English like "mechanical" or "checkout" whose
    tokenizations the syllable bank never produces)."""
    if rng.random() < 0.35:
        n = int(rng.integers(4, 10))
        chars = []
        for i in range(n):
            bank = _CONS if i % 2 == 0 else _VOWS
            chars.append(bank[int(rng.integers(len(bank)))])
        return "".join(chars)
    k = int(rng.integers(2, 4))
    return "".join(_SYLLS[int(rng.integers(len(_SYLLS)))] for _ in range(k))


def synth_intent_corpus(n: int = 4000, seed: int = 0) -> list[tuple[str, dict, str]]:
    """(utterance, context, response_json) triples from template banks.

    Simple families are labeled by RuleBasedParser (single source of truth
    for the output format); compound utterances — which the rule parser
    cannot split — get hand-built labels, teaching the chains the golden
    set probes. Half the open-vocabulary slots are filled with pseudo-words
    so copying generalizes past the banks."""
    from ..schemas import Intent, ParseResponse, Target

    rng = np.random.default_rng(seed)
    golden = _golden_texts()
    out: list[tuple[str, dict, str]] = []

    def pick(seq):
        return seq[int(rng.integers(len(seq)))]

    def dump(resp: ParseResponse) -> str:
        return json.dumps(resp.model_dump(), separators=(",", ":"))

    def noun_phrase() -> str:
        # pseudo-words force copy generalization (they cannot be
        # memorized). Phrase SHAPE varies 1-4 words with bank/pseudo words
        # mixed per-slot: golden misses like "waterproof hiking boots" and
        # "usb c chargers" are 3-word shapes the old 2-word templates never
        # produced — the copy circuit must be shape-general, not just
        # vocab-general (round-5 streaming-v4 lever; v3 hit ~0 loss on its
        # own distribution yet still missed these shapes).
        n = 1 + int(rng.random() < 0.75) + int(rng.random() < 0.35) \
            + int(rng.random() < 0.15)
        words = []
        for i in range(n):
            r = rng.random()
            if r < 0.45:
                words.append(_pseudo_word(rng))
            elif i == 0 and n > 1:
                words.append(pick(_ADJS))
            else:
                words.append(pick(_NOUNS))
        return " ".join(words)

    makers = []

    def fam(weight):
        def reg(fn):
            makers.extend([fn] * weight)
            return fn
        return reg

    @fam(6)
    def _search():
        q = noun_phrase()
        t = pick(["search for {q}", "find {q}", "look for {q}",
                  "search for some {q}", "find {q} please"]).format(q=q)
        return t, {}, None

    @fam(2)
    def _navigate():
        s = pick(_SITES)
        if rng.random() < 0.5:
            s = _pseudo_word(rng) + pick([".com", ".org", ".net", ".io"])
        return pick(["go to {s}", "open {s}", "navigate to {s}",
                     "navigate to {s} please"]).format(s=s), {}, None

    @fam(3)
    def _click_index():
        # hand-labeled: the rule teacher only maps first|second|third —
        # fourth..tenth would teacher-label as UNKNOWN, training the model
        # to refuse exactly the ordinals the golden dialogs probe
        # (round-5 reviewer finding)
        word = pick(list(_ORDINALS))
        idx = _ORDINALS[word]
        t = pick(["open the {w} result", "open the {w} link",
                  "open the {w} item"]).format(w=word)
        ctx = {"last_query": noun_phrase()} if rng.random() < 0.5 else {}
        resp = ParseResponse(
            intents=[Intent(type="click",
                            target=Target(strategy="auto", role="link"),
                            args={"index": idx})],
            confidence=0.9,
            tts_summary=f"Opening result {idx}",
        )
        return t, ctx, dump(resp)

    @fam(3)
    def _click_text():
        b = _pseudo_word(rng) if rng.random() < 0.55 else pick(_BUTTONS)
        return pick(["click the {b} button", "click {b}",
                     "click on the {b} button"]).format(b=b), {}, None

    @fam(3)
    def _sort():
        f = pick(_FIELDS)
        t = pick([
            "sort these by {f} from high to low", "sort by {f} low to high",
            "sort by {f} descending", "sort by {f} ascending",
            "sort these by {f} from low to high", "sort by {f} high to low",
        ]).format(f=f)
        return t, {}, None

    @fam(2)
    def _scroll():
        return pick(["scroll down", "scroll up", "scroll down a bit",
                     "scroll up a little", "scroll down the page",
                     "please scroll down", "scroll down some more"]), {}, None

    @fam(1)
    def _back():
        return pick(["go back", "go back a page", "take me back",
                     "head back", "go back now"]), {}, None

    @fam(1)
    def _screenshot():
        return pick(["take a screenshot", "screenshot this page please",
                     "take a screenshot of this", "grab a screenshot"]), {}, None

    @fam(1)
    def _extract():
        return pick(["extract the table as csv", "extract this table",
                     "extract the table as a csv file",
                     "extract that table as csv"]), {}, None

    @fam(2)
    def _upload():
        d = pick(_DOCS)
        return pick(["upload my {d}", "upload my {d} and submit",
                     "upload the {d} and submit the form",
                     "upload my {d} and submit it"]).format(d=d), {}, None

    @fam(1)
    def _summarize():
        return pick(["summarize this page", "give me a summary of this",
                     "summarize the page for me", "summarize this article"]), {}, None

    @fam(1)
    def _cancel():
        return pick(["cancel", "cancel that please", "never mind cancel",
                     "cancel that"]), {}, None

    @fam(1)
    def _unknown():
        return pick(_CHATTER), {}, None

    @fam(3)
    def _search_then_sort():
        # the rule parser cannot split compound commands (its search regex
        # would swallow the tail) — label by hand, teaching the chain
        q = noun_phrase()
        f = pick(_FIELDS)
        asc = rng.random() < 0.5
        t = (f"search for {q} and sort by {f} "
             + ("low to high" if asc else "high to low"))
        resp = ParseResponse(
            intents=[
                Intent(type="search", args={"query": q}),
                Intent(type="sort", args={"field": f,
                                          "direction": "asc" if asc else "desc"}),
            ],
            context_updates={"last_query": q},
            confidence=0.9,
            tts_summary=f"Searching for {q}",
        )
        return t, {}, dump(resp)

    @fam(2)
    def _search_then_screenshot():
        q = noun_phrase()
        t = f"search for {q} and take a screenshot"
        resp = ParseResponse(
            intents=[Intent(type="search", args={"query": q}),
                     Intent(type="screenshot")],
            context_updates={"last_query": q},
            confidence=0.9,
            tts_summary=f"Searching for {q}",
        )
        return t, {}, dump(resp)

    @fam(2)
    def _open_then_scroll():
        word = pick(list(_ORDINALS))
        d = pick(["down", "up"])
        t = f"open the {word} result and scroll {d}"
        resp = ParseResponse(
            intents=[
                Intent(type="click", target=Target(strategy="auto", role="link"),
                       args={"index": _ORDINALS[word]}),
                Intent(type="scroll", args={"direction": d}),
            ],
            confidence=0.9,
            tts_summary=f"Opening result {_ORDINALS[word]}",
        )
        return t, {}, dump(resp)

    @fam(2)
    def _filter():
        # the reference few-shots cover price filtering (server.ts:52-59);
        # the rule parser has no filter family, so labels are hand-built in
        # the executor's {field, op, value} convention (actions._do_filter)
        v = int(rng.integers(2, 80)) * 5
        under = rng.random() < 0.7
        t = pick([
            "filter by price {w} {v}", "show only items {w} {v} dollars",
            "filter price {w} ${v}", "only show results {w} {v}",
        ]).format(w="under" if under else "over", v=v)
        resp = ParseResponse(
            intents=[Intent(type="filter",
                            args={"field": "price",
                                  "op": "lte" if under else "gte",
                                  "value": v})],
            confidence=0.9,
            tts_summary=f"Filtering by price",
        )
        return t, {}, dump(resp)

    @fam(2)
    def _search_wait_extract():
        # reference few-shot #5's chain (server.ts:70-82):
        # search -> wait_for results -> extract_table
        q = noun_phrase()
        t = pick([
            "search for {q} and extract the table when it loads",
            "search for {q} then wait for the results and extract the table",
            "find {q} and once results load extract the table as csv",
        ]).format(q=q)
        resp = ParseResponse(
            intents=[
                Intent(type="search", args={"query": q}),
                Intent(type="wait_for",
                       target=Target(strategy="css", value=".results")),
                Intent(type="extract_table", args={"format": "csv"}),
            ],
            context_updates={"last_query": q},
            confidence=0.9,
            tts_summary=f"Searching for {q} and extracting the table",
        )
        return t, {}, dump(resp)

    seen = set()
    while len(out) < n:
        text, ctx, resp_json = pick(makers)()
        key = (text, tuple(sorted(ctx.items())))
        if text in golden or key in seen:
            continue
        seen.add(key)
        out.append((text, ctx, resp_json or teacher_response_json(text, ctx)))
    return out


def synth_intent_dialogs(n: int = 900, seed: int = 11) -> list[list[tuple[str, dict, str]]]:
    """Multi-turn training dialogs in the PLANNER's transcript shape: each
    dialog is [(utterance, context, plan_json), ...]; at serve time turn 1
    renders via distilled_prompt and later turns append as
    ``\\n<|user|>\\n{json}\\n<|assistant|>\\n`` with the previous plans'
    raw JSON in between (serve.planner: generated tokens join the
    transcript; EOS does not). Turn-2+ context is {} for most rows — the
    transcript itself carries the history, which is the planner's whole
    point — with a 30% share carrying the voice-service-merged
    ``last_query`` for robustness to both context styles."""
    from ..schemas import Intent, ParseResponse, Target

    rng = np.random.default_rng(seed)
    golden = _golden_texts()
    out: list[list[tuple[str, dict, str]]] = []

    def pick(seq):
        return seq[int(rng.integers(len(seq)))]

    def dump(resp: ParseResponse) -> str:
        return json.dumps(resp.model_dump(), separators=(",", ":"))

    def noun_phrase() -> str:
        if rng.random() < 0.5:
            k = int(rng.integers(1, 3))
            return " ".join(_pseudo_word(rng) for _ in range(k))
        return f"{pick(_ADJS)} {pick(_NOUNS)}"

    def search_turn():
        q = noun_phrase()
        t = pick(["search for {q}", "find {q}", "look for {q}"]).format(q=q)
        return q, (t, {}, teacher_response_json(t, {}))

    def follow_turn(q: str):
        ctx = {"last_query": q} if rng.random() < 0.3 else {}
        r = rng.random()
        if r < 0.35:
            # hand-labeled for ALL ordinals (the rule teacher stops at
            # "third" and would label fourth..tenth as unknown — poisoning
            # the exact capability the golden dialogs test; round-5
            # reviewer finding)
            w = pick(list(_ORDINALS))
            t = pick(["open the {w} result", "open the {w} link"]).format(w=w)
            resp = ParseResponse(
                intents=[Intent(type="click",
                                target=Target(strategy="auto", role="link"),
                                args={"index": _ORDINALS[w]})],
                confidence=0.9, tts_summary=f"Opening result {_ORDINALS[w]}")
            return (t, ctx, dump(resp))
        elif r < 0.55:
            f = pick(_FIELDS)
            t = pick(["sort these by {f} from high to low",
                      "sort by {f} low to high"]).format(f=f)
        elif r < 0.7:
            t = pick(["scroll down", "scroll up", "go back"])
        elif r < 0.8:
            t = pick(["take a screenshot", "screenshot this page please"])
        elif r < 0.9:
            t = pick(["extract the table as csv", "extract this table"])
        else:
            w = pick(list(_ORDINALS))
            d = pick(["down", "up"])
            t = f"open the {w} result and scroll {d}"
            resp = ParseResponse(
                intents=[
                    Intent(type="click",
                           target=Target(strategy="auto", role="link"),
                           args={"index": _ORDINALS[w]}),
                    Intent(type="scroll", args={"direction": d}),
                ],
                confidence=0.9, tts_summary=f"Opening result {_ORDINALS[w]}")
            return (t, ctx, json.dumps(resp.model_dump(), separators=(",", ":")))
        return (t, ctx, teacher_response_json(t, ctx))

    seen = set()
    while len(out) < n:
        q, first = search_turn()
        turns = [first]
        for _ in range(1 if rng.random() < 0.7 else 2):
            turns.append(follow_turn(q))
        key = tuple(t for t, _, _ in turns)
        if key in seen or any(t in golden for t in key):
            continue
        seen.add(key)
        out.append(turns)
    return out


def distilled_prompt(text: str, context: dict) -> str:
    """The SHORT serving prompt for distilled checkpoints: the task lives in
    the weights, so inference skips the ~880-token few-shot prefix that
    render_prompt carries (near-zero prefill — the train/step design goal)."""
    user = json.dumps({"text": text, "context": context}, separators=(",", ":"))
    return f"<|user|>\n{user}\n<|assistant|>\n"


def teacher_response_json(text: str, context: dict) -> str:
    """Rule-parser label in the exact compact-JSON shape the grammar emits."""
    from ..services.brain import RuleBasedParser

    resp = RuleBasedParser().parse(text, context)
    return json.dumps(resp.model_dump(), separators=(",", ":"))


# ------------------------------------------------------------- intent train

def build_intent_batches(corpus, tokenizer, seq_len: int, batch: int,
                         seed: int = 0, dialogs=None):
    """Tokenize single-turn pairs AND multi-turn dialogs into fixed (B, T)
    (tokens, targets, loss_mask) arrays for ``step.loss_fn_targets``.

    ``targets[i]`` labels the prediction AT position i (conventionally
    ids[i+1]). Loss covers every plan span plus one termination position
    per plan: after a MID-dialog plan's last token the target is EOS — at
    serve time that is exactly where the turn's decode stops, while the
    transcript itself continues with the next ``\\n<|user|>`` segment
    (planner transcripts never contain EOS). Segments tokenize
    independently and concatenate, matching serve-time transcript
    construction (planner.extend appends encoded segments; BPE must not
    merge across the plan/prompt boundary differently at train and serve).
    Examples too long for ``seq_len`` are dropped (static shapes)."""
    rng = np.random.default_rng(seed)
    rows = []

    def add_sample(turns):
        # turns: list of (utterance, ctx, plan_json)
        ids: list[int] = []
        tgt_over: dict[int, int] = {}
        mask_spans = []
        for ti, (text, ctx, plan_json) in enumerate(turns):
            if ti == 0:
                seg = tokenizer.encode(distilled_prompt(text, ctx), bos=True)
            else:
                user = json.dumps({"text": text, "context": ctx},
                                  separators=(",", ":"))
                seg = tokenizer.encode(f"\n<|user|>\n{user}\n<|assistant|>\n")
            ids.extend(seg)
            p_ids = tokenizer.encode(plan_json)
            start = len(ids)
            ids.extend(p_ids)
            last = ti == len(turns) - 1
            if last:
                ids.append(tokenizer.eos_id)
                # positions start-1 .. end-1 predict plan tokens + EOS
                mask_spans.append((start - 1, len(ids) - 1))
            else:
                mask_spans.append((start - 1, len(ids) - 1))
                # the position AT the plan's last token predicts EOS (that
                # is how the served turn stops) even though the transcript
                # continues with the next <|user|> segment
                tgt_over[len(ids) - 1] = tokenizer.eos_id
        if len(ids) > seq_len:
            return
        T = len(ids)
        toks = ids + [tokenizer.pad_id] * (seq_len - T)
        tgts = ids[1:] + [tokenizer.pad_id] * (seq_len - T + 1)
        mask = [0.0] * seq_len
        for lo, hi in mask_spans:
            for i in range(lo, hi):
                mask[i] = 1.0
        for pos, t in tgt_over.items():
            tgts[pos] = t
            mask[pos] = 1.0
        rows.append((toks, tgts, mask))

    for item in corpus:
        add_sample([item])
    for dlg in dialogs or []:
        add_sample(dlg)
    rng.shuffle(rows)
    toks = np.asarray([r[0] for r in rows], np.int32)
    tgts = np.asarray([r[1] for r in rows], np.int32)
    masks = np.asarray([r[2] for r in rows], np.float32)
    n = (len(rows) // batch) * batch
    return (toks[:n].reshape(-1, batch, seq_len),
            tgts[:n].reshape(-1, batch, seq_len),
            masks[:n].reshape(-1, batch, seq_len))


def train_intent_model(
    steps: int = 2600,
    batch: int = 16,
    seq_len: int = 320,
    corpus_n: int = 5000,
    dialogs_n: int = 900,
    lr: float = 3e-3,
    seed: int = 0,
    stream: bool = True,
    dim: int | None = None,
    n_layers: int | None = None,
    ffn_dim: int | None = None,
    log=None,
):
    """Train test-tiny on the synthetic corpus + multi-turn planner-shaped
    dialogs; returns (cfg, params, stats). f32 weights (bf16 rounding hurts
    at this scale and the model is tiny). ``dim``/``n_layers``/``ffn_dim``
    optionally widen the student past the test-tiny preset (the checkpoint
    carries its own config, so serving is unchanged) — byte-level copying
    over a long JSON prompt is the task's hard part and benefits from a
    third layer / wider residual stream.

    ``stream=True`` (round-5 fix for the golden args gap): every step draws
    a FRESH corpus/dialog sample with a step-derived seed, so pseudo-word
    copy spans never repeat across the run. The fixed-corpus variant
    collapsed train loss to ~1e-3 by MEMORIZING the ~6k completions —
    scoring worse on golden copying ("search for mechanical keyboards" ->
    query "wireless keyboards", a bank recall) than a shorter run. With
    never-repeating spans, copying the prompt is the only strategy that
    reduces loss. ``stream=False`` keeps the epoch path (corpus_n /
    dialogs_n sized) for comparisons."""
    import optax

    from ..grammar.intent_grammar import build_intent_fsm
    from ..models.llama import PRESETS, init_params
    from .step import loss_fn_targets

    if stream and (corpus_n != 5000 or dialogs_n != 900):
        import warnings

        warnings.warn(
            "corpus_n/dialogs_n size a FIXED corpus and are ignored under "
            "stream=True (fresh data every step); pass stream=False to use "
            "them", stacklevel=2)
    tokenizer, _ = build_intent_fsm()
    cfg = replace(PRESETS["test-tiny"], vocab_size=tokenizer.vocab_size,
                  max_seq_len=seq_len)
    if dim or n_layers or ffn_dim:
        cfg = replace(cfg, dim=dim or cfg.dim,
                      n_layers=n_layers or cfg.n_layers,
                      ffn_dim=ffn_dim or cfg.ffn_dim)
    params = jax.jit(partial(init_params, cfg, dtype=jnp.float32))(
        jax.random.PRNGKey(seed))

    warmup = min(50, max(1, steps // 4))
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, steps, lr * 0.05)
    optimizer = optax.adamw(sched, weight_decay=0.01)
    opt_state = optimizer.init(params)

    # analyze: ok[jit-sentinel] -- offline training step, not a serving dispatch — the recompile sentinel guards the serving plane
    @jax.jit
    def step_fn(params, opt_state, tokens, targets, loss_mask):
        loss, grads = jax.value_and_grad(loss_fn_targets)(
            params, cfg, tokens, targets, loss_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if stream:
        def batch_for(s: int):
            # fresh data every step: ~1/4 dialog rows, the rest single-turn.
            # Over-generate so seq_len drops still leave a full batch (and
            # retry bigger in the pathological all-dropped case). Only the
            # FIRST (batch)-row block trains — slice to it so stats count
            # what was actually consumed, not the surplus.
            extra = 6
            while True:
                c = synth_intent_corpus(batch + extra,
                                        seed=seed + 1000 + s * 2)
                d = synth_intent_dialogs(max(2, batch // 4),
                                         seed=seed + 999_983 + s * 2)
                out = build_intent_batches(c, tokenizer, seq_len, batch,
                                           seed + s, dialogs=d)
                if out[0].shape[0] > 0:
                    return tuple(a[:1] for a in out)
                extra *= 2
    else:
        corpus = synth_intent_corpus(corpus_n, seed=seed)
        dialogs = synth_intent_dialogs(dialogs_n, seed=seed + 11)
        toks_e, tgts_e, masks_e = build_intent_batches(
            corpus, tokenizer, seq_len, batch, seed, dialogs=dialogs)

        def batch_for(s: int):
            b = s % toks_e.shape[0]
            return toks_e[b: b + 1], tgts_e[b: b + 1], masks_e[b: b + 1]

    t0 = time.perf_counter()
    first = last = None
    n_seen = 0
    for s in range(steps):
        toks, tgts, masks = batch_for(s)
        n_seen += int(toks.shape[0] * toks.shape[1])
        params, opt_state, loss = step_fn(
            params, opt_state, jnp.asarray(toks[0]), jnp.asarray(tgts[0]),
            jnp.asarray(masks[0]))
        if s == 0:
            first = float(loss)
        if log and (s % 100 == 0 or s == steps - 1):
            log(f"intent train step {s}/{steps} loss {float(loss):.4f}")
    last = float(loss)
    stats = {"steps": steps, "examples": n_seen, "stream": stream,
             "first_loss": first, "final_loss": last,
             "train_s": round(time.perf_counter() - t0, 1)}
    return cfg, params, stats


def intent_engine_from(cfg, params, max_new_tokens: int = 300, spec=None):
    """Serving engine + parser over trained weights: the REAL constrained
    decode path (grammar FSM, prefix cache machinery) with the distilled
    short prompt instead of the few-shot prefix. ``spec`` (serve.spec
    SpecConfig) turns on speculative decoding for the distilled engine —
    brain plumbs SPEC_ENABLE through here."""
    from ..serve import DecodeEngine
    from ..services.brain import EngineParser

    eng = DecodeEngine(cfg=replace(cfg, max_seq_len=512), max_len=512,
                       prefill_buckets=(64, 128), init_weights=False,
                       spec=spec)
    eng.load_params(jax.device_put(params))
    return EngineParser(eng, max_new_tokens=max_new_tokens,
                        render=distilled_prompt)


# ------------------------------------------------------------ draft traces


def load_spec_trace(path: str) -> list[dict]:
    """Parse a ``SPEC_TRACE_SINK`` JSONL file (serve.spec SpecDecoder
    appends one record per cleanly released speculative request:
    prompt/generated ids + drafted/accepted counts). Malformed or partial
    lines are skipped — the sink appends from a serving process that may
    be killed mid-write, and a torn tail line must not poison retraining."""
    out: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("prompt_ids") and rec.get("generated_ids"):
                out.append(rec)
    return out


def build_draft_batches_from_trace(records, tokenizer, seq_len: int = 256,
                                   batch: int = 8, seed: int = 0):
    """Draft-trace records -> fixed (B, T) (tokens, targets, loss_mask)
    arrays for ``step.loss_fn_targets``, loss on the GENERATED span (plus
    one EOS termination position): the drafter's job is to predict the
    target's accepted stream given the live context — exactly what the
    trace captured in production, including the multi-turn radix-warm
    prompts the synthetic corpus never renders. Contexts longer than
    ``seq_len`` keep their RIGHT-most window (drafting conditions on
    recent context; the deep prompt head is conditioning, not labels) —
    unlike ``build_intent_batches`` nothing is dropped, because production
    prompts routinely exceed any training window."""
    rng = np.random.default_rng(seed)
    rows = []
    for rec in records:
        p = [int(t) for t in rec["prompt_ids"]]
        g = [int(t) for t in rec["generated_ids"]]
        ids = p + g + [tokenizer.eos_id]
        gen_start = len(p)
        if len(ids) > seq_len:
            off = len(ids) - seq_len
            ids = ids[off:]
            gen_start = max(gen_start - off, 1)  # keep >= 1 context position
        T = len(ids)
        toks = ids + [tokenizer.pad_id] * (seq_len - T)
        tgts = ids[1:] + [tokenizer.pad_id] * (seq_len - T + 1)
        mask = [0.0] * seq_len
        for i in range(gen_start - 1, T - 1):
            mask[i] = 1.0  # position i predicts ids[i+1]: gen span + EOS
        rows.append((toks, tgts, mask))
    rng.shuffle(rows)
    toks = np.asarray([r[0] for r in rows], np.int32)
    tgts = np.asarray([r[1] for r in rows], np.int32)
    masks = np.asarray([r[2] for r in rows], np.float32)
    n = (len(rows) // batch) * batch
    return (toks[:n].reshape(-1, batch, seq_len),
            tgts[:n].reshape(-1, batch, seq_len),
            masks[:n].reshape(-1, batch, seq_len))


DRAFT_CKPT = "draft-tiny-trace"


def train_draft_from_trace(path: str, steps: int = 400, batch: int = 8,
                           seq_len: int = 256, lr: float = 3e-3,
                           seed: int = 0, log=None):
    """Retrain the ``draft-tiny`` speculation drafter on production draft
    traces (the ROADMAP's accept-rate flywheel: serve with
    ``SPEC_TRACE_SINK`` set, retrain here, point ``SPEC_DRAFT_MODEL`` at
    ``save_ckpt(root, DRAFT_CKPT, ...)``'s output). The student is the
    draft-tiny preset at the serving tokenizer's vocab — the width
    ``DraftModelDrafter`` pads/validates against the target. Returns
    (cfg, params, stats)."""
    import optax

    from ..grammar.intent_grammar import build_intent_fsm
    from ..models.llama import PRESETS, init_params
    from .step import loss_fn_targets

    tokenizer, _ = build_intent_fsm()
    records = load_spec_trace(path)
    if not records:
        raise ValueError(f"no usable draft-trace records in {path} "
                         "(serve with SPEC_TRACE_SINK=<path> first)")
    toks_e, tgts_e, masks_e = build_draft_batches_from_trace(
        records, tokenizer, seq_len=seq_len, batch=batch, seed=seed)
    if toks_e.shape[0] == 0:
        raise ValueError(
            f"{len(records)} trace records fill no ({batch}, {seq_len}) "
            "batch; lower batch or collect more traffic")
    cfg = replace(PRESETS["draft-tiny"], vocab_size=tokenizer.vocab_size,
                  max_seq_len=seq_len)
    params = jax.jit(partial(init_params, cfg, dtype=jnp.float32))(
        jax.random.PRNGKey(seed))

    warmup = min(50, max(1, steps // 4))
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, steps, lr * 0.05)
    optimizer = optax.adamw(sched, weight_decay=0.01)
    opt_state = optimizer.init(params)

    # analyze: ok[jit-sentinel] -- offline training step, not a serving dispatch — the recompile sentinel guards the serving plane
    @jax.jit
    def step_fn(params, opt_state, tokens, targets, loss_mask):
        loss, grads = jax.value_and_grad(loss_fn_targets)(
            params, cfg, tokens, targets, loss_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    t0 = time.perf_counter()
    first = None
    for s in range(steps):
        b = s % toks_e.shape[0]
        params, opt_state, loss = step_fn(
            params, opt_state, jnp.asarray(toks_e[b]), jnp.asarray(tgts_e[b]),
            jnp.asarray(masks_e[b]))
        if s == 0:
            first = float(loss)
        if log and (s % 100 == 0 or s == steps - 1):
            log(f"draft trace train step {s}/{steps} loss {float(loss):.4f}")
    stats = {"steps": steps, "records": len(records),
             "batches": int(toks_e.shape[0]),
             "first_loss": first, "final_loss": float(loss),
             "train_s": round(time.perf_counter() - t0, 1)}
    return cfg, params, stats


# ------------------------------------------------------------ whisper train

# "acoustic font": each character sounds as a 2-tone chord, 60 ms per char.
# Distinct fundamentals keep chars separable after the mel front-end.
_CHAR_SET = "abcdefghijklmnopqrstuvwxyz '"


def render_speech(text: str, sr: int = 16_000, char_ms: int = 60) -> np.ndarray:
    """Deterministic text -> waveform (the synthetic 'speaker')."""
    n = int(sr * char_ms / 1000)
    t = np.arange(n) / sr
    chunks = []
    for ch in text.lower():
        i = _CHAR_SET.find(ch)
        if i < 0:
            i = _CHAR_SET.find(" ")
        f0 = 200.0 + 55.0 * i
        f1 = 2000.0 + 90.0 * i
        env = np.hanning(n)
        chunks.append((0.45 * np.sin(2 * np.pi * f0 * t)
                       + 0.25 * np.sin(2 * np.pi * f1 * t)) * env)
    return np.concatenate(chunks).astype(np.float32)


WHISPER_EVAL_TEXTS = [
    "search for red shoes",
    "scroll down",
    "go back now",
    "open the second result",
    "sort by price",
    "take a screenshot",
    "upload my resume",
    "cancel that",
    "click the submit button",
    "extract the table",
]


def render_speech_jittered(text: str, rng: np.random.Generator,
                           sr: int = 16_000) -> np.ndarray:
    """Augmented render: tempo (char duration), amplitude, and additive
    noise vary per call — the variation that forces the encoder to learn
    the char->chord mapping instead of memorizing waveforms (round-4's
    held-out attempt failed at WER 0.96 on 10 clean training sentences)."""
    char_ms = int(rng.uniform(48, 72))
    amp = float(rng.uniform(0.55, 1.1))
    audio = render_speech(text, sr=sr, char_ms=char_ms) * amp
    noise = rng.normal(0.0, rng.uniform(0.002, 0.02), len(audio))
    return (audio + noise).astype(np.float32)


def whisper_train_sentences(n: int = 240, seed: int = 7) -> list[str]:
    """Deterministic synthetic command bank, sentence-disjoint from
    WHISPER_EVAL_TEXTS (asserted). Word overlap with the eval set is
    deliberate — the unit being generalized is the acoustic font's
    char->chord code, and held-out SENTENCES prove the decoder is reading
    the audio rather than reciting a memorized training line."""
    verbs = ["search", "look", "find", "open", "click", "press", "scroll",
             "go", "sort", "filter", "upload", "extract", "close", "cancel",
             "take", "submit", "select", "type", "show", "read"]
    nouns = ["shoes", "laptops", "headphones", "cameras", "books", "jackets",
             "phones", "bags", "watches", "chairs", "links", "buttons",
             "forms", "pages", "results", "images", "prices", "tables",
             "resume", "screenshot", "menu", "cart", "reviews", "filters"]
    adjs = ["red", "blue", "green", "black", "white", "cheap", "new", "big",
            "small", "wireless", "leather", "second", "last", "top", "old"]
    templates = [
        "{v} for {a} {n}", "{v} the {n}", "{v} {n}", "{v} the {a} {n}",
        "{a} {n}", "{v} for {n}", "{v} up", "{v} down", "{v} back",
        "{v} that now", "{v} the {n} now", "{v} my {n}",
    ]
    rng = np.random.default_rng(seed)
    eval_set = set(WHISPER_EVAL_TEXTS)
    out: list[str] = []
    seen: set[str] = set()
    while len(out) < n:
        t = templates[int(rng.integers(len(templates)))]
        s = t.format(v=verbs[int(rng.integers(len(verbs)))],
                     n=nouns[int(rng.integers(len(nouns)))],
                     a=adjs[int(rng.integers(len(adjs)))])
        # bucket budget: 200 mel frames = 2 s = 33 chars at 60 ms/char,
        # and the tempo jitter reaches 72 ms/char -> cap at 27
        if s in seen or s in eval_set or len(s) > 27:
            continue
        seen.add(s)
        out.append(s)
    assert not set(out) & eval_set
    return out


def train_whisper_generalize(
    steps: int = 6000,
    batch: int = 24,
    variants: int = 10,
    n_sentences: int = 320,
    lr: float = 2e-3,
    seed: int = 0,
    log=None,
):
    """Train whisper-test to READ the acoustic font: a 240-sentence
    synthetic command bank with tempo/amplitude/noise augmentation
    (render_speech_jittered), with WHISPER_EVAL_TEXTS held out entirely
    (VERDICT round-4 next #3 — the committed overfit checkpoint's 0.0 WER
    is a train-set number and is now labeled as such). Returns
    (cfg, params, stats); score held-out WER via whisper_engine_from.

    Reference parity note: this stands in for Deepgram transcribing speech
    it was never trained on (apps/voice/src/deepgram.ts:33-45), at the
    scale this zero-egress image permits."""
    import optax

    from ..audio.mel import MelConfig, log_mel_spectrogram
    from ..grammar.intent_grammar import default_tokenizer
    from ..models.whisper import (
        PRESETS as WPRESETS,
        compute_cross_kv,
        decoder_forward,
        encoder_forward,
        init_params,
        init_self_cache,
    )

    texts = whisper_train_sentences(n_sentences)
    tokenizer = default_tokenizer()
    base = WPRESETS["whisper-test"]
    cfg = replace(base, vocab_size=tokenizer.vocab_size)
    mel_cfg = MelConfig(n_mels=cfg.n_mels)
    bucket = cfg.max_audio_frames
    rng = np.random.default_rng(seed)

    # ---- precompute augmented mel variants (the mel front-end is fixed;
    # only the waveforms vary). R = n_sentences * variants rows.
    # analyze: ok[jit-sentinel] -- offline training-data mel precompute, not a serving dispatch
    mel_fn = jax.jit(partial(log_mel_spectrogram, cfg=mel_cfg))
    rows_mel, rows_valid, rows_sent = [], [], []
    for si, text in enumerate(texts):
        for vi in range(variants):
            # variant 0 is the CLEAN canonical render: serve-time audio
            # (render_speech defaults) must be inside the training
            # distribution, not only the jittered neighborhood around it
            audio = (render_speech(text) if vi == 0
                     else render_speech_jittered(text, rng))
            n_frames = min(max(1, len(audio) // mel_cfg.hop), bucket)
            padded = np.zeros(bucket * mel_cfg.hop, dtype=np.float32)
            padded[: len(audio)] = audio[: len(padded)]
            rows_mel.append(np.asarray(mel_fn(jnp.asarray(padded)))[:bucket])
            v = np.zeros(bucket // 2, bool)
            v[: max(1, n_frames // 2)] = True
            rows_valid.append(v)
            rows_sent.append(si)
    mel_all = np.stack(rows_mel)
    valid_all = np.stack(rows_valid)
    sent_all = np.asarray(rows_sent)

    ids_rows = [tokenizer.encode(t, bos=True) + [tokenizer.eos_id] for t in texts]
    max_text = max(len(r) for r in ids_rows)
    toks_all = np.full((len(texts), max_text), tokenizer.pad_id, np.int32)
    mask_all = np.zeros((len(texts), max_text), np.float32)
    for i, ids in enumerate(ids_rows):
        toks_all[i, : len(ids)] = ids
        mask_all[i, 1: len(ids)] = 1.0

    params = jax.jit(partial(init_params, cfg, dtype=jnp.float32))(
        jax.random.PRNGKey(seed))
    sched = optax.cosine_decay_schedule(lr, steps, alpha=0.05)
    optimizer = optax.adamw(sched, weight_decay=0.01)
    opt_state = optimizer.init(params)

    def spec_augment(key, mel):
        """SpecAugment-style time/freq masking, applied per minibatch on
        the precomputed mels: the first generalization attempt hit train
        loss 4e-4 while CANONICAL-tempo renders of its own training
        sentences scored 0.5 WER — pure waveform memorization. Masked
        inputs can't be memorized; the model must read the char chords."""
        B, T, M = mel.shape
        kt, kf, kt0, kf0 = jax.random.split(key, 4)
        # two time masks (width <= 10 frames < 2 chars) + one freq mask
        tw = jax.random.randint(kt, (B, 2), 0, 11)
        t0 = jax.random.randint(kt0, (B, 2), 0, T)
        fw = jax.random.randint(kf, (B, 1), 0, 13)
        f0 = jax.random.randint(kf0, (B, 1), 0, M)
        trange = jnp.arange(T)[None, :]
        frange = jnp.arange(M)[None, :]
        tmask = jnp.ones((B, T), bool)
        for i in range(2):
            tmask &= ~((trange >= t0[:, i:i + 1])
                       & (trange < t0[:, i:i + 1] + tw[:, i:i + 1]))
        fmask = ~((frange >= f0[:, :1]) & (frange < f0[:, :1] + fw[:, :1]))
        keep = tmask[:, :, None] & fmask[:, None, :]
        return jnp.where(keep, mel, jnp.mean(mel, axis=(1, 2), keepdims=True))

    def loss_fn(params, mel_j, valid_j, toks_j, mask_j, key):
        B = mel_j.shape[0]
        mel_j = spec_augment(key, mel_j)
        enc = encoder_forward(params, cfg, mel_j)
        ckv = compute_cross_kv(params, cfg, enc)
        cache = init_self_cache(cfg, B, dtype=jnp.float32)
        T = toks_j.shape[1]
        pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        logits, _ = decoder_forward(params, cfg, toks_j, pos, cache, ckv, valid_j)
        logp = jax.nn.log_softmax(logits[:, :-1, :].astype(jnp.float32), axis=-1)
        tgt = toks_j[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        m = mask_j[:, 1:]
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)

    # analyze: ok[jit-sentinel] -- offline training step, not a serving dispatch — the recompile sentinel guards the serving plane
    @jax.jit
    def step_fn(params, opt_state, mel_j, valid_j, toks_j, mask_j, key):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, mel_j, valid_j, toks_j, mask_j, key)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.perf_counter()
    first = ema = None
    R = mel_all.shape[0]
    aug_key = jax.random.PRNGKey(seed + 17)
    for s in range(steps):
        pick = rng.choice(R, size=batch, replace=False)
        si = sent_all[pick]
        aug_key, sk = jax.random.split(aug_key)
        params, opt_state, loss = step_fn(
            params, opt_state,
            jnp.asarray(mel_all[pick]), jnp.asarray(valid_all[pick]),
            jnp.asarray(toks_all[si]), jnp.asarray(mask_all[si]), sk)
        lf = float(loss)
        first = lf if first is None else first
        ema = lf if ema is None else 0.98 * ema + 0.02 * lf
        if log and (s % 200 == 0 or s == steps - 1):
            log(f"whisper-gen step {s}/{steps} loss {lf:.4f} (ema {ema:.4f})")
    stats = {"steps": steps, "sentences": len(texts), "variants": variants,
             "first_loss": first, "final_loss_ema": round(ema, 4),
             "train_s": round(time.perf_counter() - t0, 1)}
    return cfg, params, stats


def train_whisper_overfit(
    texts: list[str] | None = None,
    steps: int = 500,
    lr: float = 2e-3,
    seed: int = 0,
    log=None,
):
    """Overfit whisper-test on (render_speech(text), text) pairs; returns
    (cfg, params, stats). Proves the audio->text path learns end to end."""
    import optax

    from ..audio.mel import MelConfig, log_mel_spectrogram
    from ..grammar.intent_grammar import default_tokenizer
    from ..models.whisper import (
        PRESETS as WPRESETS,
        compute_cross_kv,
        decoder_forward,
        encoder_forward,
        init_params,
        init_self_cache,
    )

    texts = texts or WHISPER_EVAL_TEXTS
    tokenizer = default_tokenizer()
    base = WPRESETS["whisper-test"]
    cfg = replace(base, vocab_size=tokenizer.vocab_size)
    mel_cfg = MelConfig(n_mels=cfg.n_mels)

    # fixed-shape batch prepared EXACTLY like SpeechEngine.transcribe:
    # audio zero-padded to the top bucket, mel over the padded audio (the
    # encoder self-attends over padding frames too, so train-time padding
    # must sound like serve-time padding), valid mask = real frames only
    bucket = cfg.max_audio_frames
    B = len(texts)
    mel_b = np.zeros((B, bucket, cfg.n_mels), np.float32)
    enc_valid = np.zeros((B, bucket // 2), bool)
    token_rows = []
    max_text = 0
    for i, text in enumerate(texts):
        audio = render_speech(text)
        n_frames = min(max(1, len(audio) // mel_cfg.hop), bucket)
        padded = np.zeros(bucket * mel_cfg.hop, dtype=np.float32)
        padded[: len(audio)] = audio[: len(padded)]
        mel_b[i] = np.asarray(
            log_mel_spectrogram(jnp.asarray(padded), mel_cfg))[:bucket]
        enc_valid[i, : max(1, n_frames // 2)] = True
        ids = tokenizer.encode(text, bos=True) + [tokenizer.eos_id]
        token_rows.append(ids)
        max_text = max(max_text, len(ids))
    toks = np.full((B, max_text), tokenizer.pad_id, np.int32)
    mask = np.zeros((B, max_text), np.float32)
    for i, ids in enumerate(token_rows):
        toks[i, : len(ids)] = ids
        mask[i, 1: len(ids)] = 1.0  # predict everything after BOS, incl EOS

    params = jax.jit(partial(init_params, cfg, dtype=jnp.float32))(
        jax.random.PRNGKey(seed))
    optimizer = optax.adamw(lr, weight_decay=0.01)
    opt_state = optimizer.init(params)
    mel_j, valid_j = jnp.asarray(mel_b), jnp.asarray(enc_valid)
    toks_j, mask_j = jnp.asarray(toks), jnp.asarray(mask)

    def loss_fn(params):
        enc = encoder_forward(params, cfg, mel_j)
        ckv = compute_cross_kv(params, cfg, enc)
        cache = init_self_cache(cfg, B, dtype=jnp.float32)
        T = toks_j.shape[1]
        pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        logits, _ = decoder_forward(params, cfg, toks_j, pos, cache, ckv, valid_j)
        logp = jax.nn.log_softmax(logits[:, :-1, :].astype(jnp.float32), axis=-1)
        tgt = toks_j[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        m = mask_j[:, 1:]
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)

    # analyze: ok[jit-sentinel] -- offline training step, not a serving dispatch — the recompile sentinel guards the serving plane
    @jax.jit
    def step_fn(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.perf_counter()
    first = None
    for s in range(steps):
        params, opt_state, loss = step_fn(params, opt_state)
        if s == 0:
            first = float(loss)
        if log and (s % 100 == 0 or s == steps - 1):
            log(f"whisper train step {s}/{steps} loss {float(loss):.4f}")
    stats = {"steps": steps, "pairs": B, "first_loss": first,
             "final_loss": float(loss),
             "train_s": round(time.perf_counter() - t0, 1)}
    return cfg, params, stats


def whisper_engine_from(cfg, params):
    from ..serve.stt import SpeechEngine

    # one bucket == the training frame count: transcribe pads exactly the
    # way the batch above was padded, so serve mels match train mels
    eng = SpeechEngine(cfg=cfg, frame_buckets=(cfg.max_audio_frames,),
                       max_new_tokens=48, init_weights=False)
    eng.load_params(jax.device_put(params))
    return eng


# --------------------------------------------------------------- ckpt glue

INTENT_CKPT = "intent-tiny-distilled"
WHISPER_CKPT = "whisper-tiny-overfit"
WHISPER_GEN_CKPT = "whisper-tiny-heldout"


def save_ckpt(root: str, name: str, cfg, params, stats: dict) -> str:
    import os

    from ..ckpt.orbax_io import save_params

    path = os.path.join(root, name)
    save_params(path, params)
    meta = {"config": {k: getattr(cfg, k) for k in cfg.__dataclass_fields__},
            "stats": stats}
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1, default=str)
    return path


def load_ckpt_path(path: str, cfg_cls):
    """load_ckpt over a single path string (service env specs like
    ``BRAIN_BACKEND=distilled:<dir>``). A bare name resolves against the
    CWD — NOT silently under checkpoints/ — so the error a caller prints
    names a path that was actually checked."""
    import os

    root, name = os.path.split(path.rstrip("/"))
    return load_ckpt(root or ".", name, cfg_cls)


def load_ckpt(root: str, name: str, cfg_cls):
    """Returns (cfg, params) or None when the checkpoint is absent."""
    import os

    from ..ckpt.orbax_io import restore_params

    path = os.path.join(root, name)
    meta_path = os.path.join(path, "meta.json")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    raw = meta["config"]
    fields = {}
    for k, v in raw.items():
        if k in cfg_cls.__dataclass_fields__:
            fields[k] = tuple(v) if isinstance(v, list) else v
    return cfg_cls(**fields), restore_params(path)
