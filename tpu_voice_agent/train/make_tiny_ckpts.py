"""Train + save the in-tree tiny checkpoints (round-3 VERDICT next #2).

Usage: python -m tpu_voice_agent.train.make_tiny_ckpts [out_dir]

Produces three orbax checkpoints under ``out_dir`` (default ``checkpoints/``):
- ``intent-tiny-distilled``  — test-tiny Llama distilled on the synthetic
  utterance->intent corpus (short-prompt serving, evals.golden scores it)
- ``whisper-tiny-overfit``   — whisper-test overfit on the acoustic-font
  pairs (evals.wer scores it; train-set number, labeled as such)
- ``whisper-tiny-heldout``   — whisper-test trained on a DISJOINT augmented
  sentence bank; WHISPER_EVAL_TEXTS is held out, so its WER generalizes.
  This is the script's long pole (~15 min CPU); skip with CKPT_HELDOUT=0.
- ``grounding-tiny``         — qwen2vl-test trained on synthetic widget
  screenshots (train.ground); scored point-in-bbox on held-out layouts.
  Also slow on one CPU core (~1 h; a TPU window trains it in minutes);
  skip with CKPT_GROUND=0.

Both reload through the real serving stack in benches/bench_quality.py.
"""

from __future__ import annotations

import os
import sys


def main(out_dir: str | None = None) -> None:
    out = out_dir or (sys.argv[1] if len(sys.argv) > 1 else "checkpoints")

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # NOT redundant in this image: the axon TPU plugin force-prepends
        # itself to jax_platforms regardless of the env var, so an operator
        # who exported JAX_PLATFORMS=cpu must also pin the config (the same
        # double-pin as tests/conftest.py and bench.py)
        import jax

        jax.config.update("jax_platforms", "cpu")

    def log(msg: str) -> None:
        print(f"[make_tiny_ckpts] {msg}", file=sys.stderr, flush=True)

    from .distill import (
        INTENT_CKPT,
        WHISPER_CKPT,
        WHISPER_GEN_CKPT,
        save_ckpt,
        train_intent_model,
        train_whisper_generalize,
        train_whisper_overfit,
    )

    log("training intent model (test-tiny distillation)...")
    cfg, params, stats = train_intent_model(log=log)
    path = save_ckpt(out, INTENT_CKPT, cfg, params, stats)
    log(f"saved {path} ({stats})")

    log("training whisper overfit (acoustic font)...")
    wcfg, wparams, wstats = train_whisper_overfit(log=log)
    path = save_ckpt(out, WHISPER_CKPT, wcfg, wparams, wstats)
    log(f"saved {path} ({wstats})")

    # the generalization checkpoint (round-4 VERDICT next #3): trained on a
    # disjoint augmented sentence bank, so WHISPER_EVAL_TEXTS is a true
    # held-out set for it — the honest WER number. Skip with CKPT_HELDOUT=0
    # (it is the long pole of this script, ~15 min CPU).
    if os.environ.get("CKPT_HELDOUT") != "0":
        log("training whisper generalization (held-out eval)...")
        gcfg, gparams, gstats = train_whisper_generalize(log=log)
        path = save_ckpt(out, WHISPER_GEN_CKPT, gcfg, gparams, gstats)
        log(f"saved {path} ({gstats})")

    if os.environ.get("CKPT_GROUND") != "0":
        from .ground import save_ground_ckpt, train_grounding

        log("training grounding (synthetic widget screenshots)...")
        qcfg, qparams, qstats = train_grounding(log=log)
        path = save_ground_ckpt(out, qcfg, qparams, qstats)
        log(f"saved {path} ({qstats})")


if __name__ == "__main__":
    main()
