"""Sharded fine-tuning step (dp x tp) for the intent-parse model.

The reference has no training path (its models are cloud APIs); this module
exists so the framework can adapt its in-tree models to the intent domain
(e.g. distill the few-shot prompt into the weights and shrink prefill to
near-zero). Design: pure-functional train step jitted over the same mesh and
param shardings the serving engine uses — batch sharded over dp, weights
column/row-sharded over tp, gradients reduced by XLA collectives over ICI.
Remat (jax.checkpoint) wraps the layer scan body to trade FLOPs for HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..models.llama import LlamaConfig, forward, init_kv_cache


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


def loss_fn(params, cfg: LlamaConfig, tokens, loss_mask, rules=None):
    """Next-token cross-entropy over (B, T) tokens; mask excludes prompt/pad.

    Teacher-forced full forward reuses the serving `forward` (a throwaway KV
    cache of length T keeps shapes static and small).
    """
    B, T = tokens.shape
    cache = init_kv_cache(cfg, B, T, dtype=jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    logits, _ = forward(params, cfg, tokens, positions, cache, rules, remat=True)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1, :].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def loss_fn_targets(params, cfg: LlamaConfig, tokens, targets, loss_mask,
                    rules=None):
    """Cross-entropy with EXPLICIT per-position targets (still teacher-
    forced on ``tokens``). Multi-turn planner transcripts need this: the
    position after a mid-dialog plan's last token must put its mass on EOS
    (that is how a served turn stops) while the transcript itself continues
    with the next ``<|user|>`` segment — a shifted-input loss would train
    that position toward the literal next transcript token and the turn
    would never terminate. ``targets[i]`` is the label for the prediction
    made AT position i (i.e. the conventional ids[i+1], overridden with
    EOS at mid-dialog plan ends)."""
    B, T = tokens.shape
    cache = init_kv_cache(cfg, B, T, dtype=jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    logits, _ = forward(params, cfg, tokens, positions, cache, rules, remat=True)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    mask = loss_mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_train_step(cfg: LlamaConfig, optimizer=None, rules=None):
    """Build (init_state, train_step). train_step is jit-ready; shardings come
    from the params/opt-state placements (jit infers) plus activation rules."""
    optimizer = optimizer or optax.adamw(1e-5, weight_decay=0.01)

    def init_state(params) -> TrainState:
        return TrainState(params=params, opt_state=optimizer.init(params), step=0)

    # analyze: ok[jit-sentinel] -- offline training step, not a serving dispatch — the recompile sentinel guards the serving plane
    @partial(jax.jit, static_argnames=(), donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens, loss_mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, cfg, tokens, loss_mask, rules)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init_state, train_step
