"""Real-checkpoint tokenizers from HF ``tokenizer.json`` — true BPE merges.

Round 1 approximated HF vocabs with greedy longest-match (VERDICT.md weak
#3): prompts fed to a real checkpoint would segment differently from its
training tokenizer and silently degrade quality. This module implements the
actual BPE merge procedure for the two families every target checkpoint uses
(zero network egress; pure-python over the checkpoint's own tokenizer.json):

- **byte-level BPE** (GPT-2 lineage: Whisper, Qwen2, Llama-3): vocab keys
  are byte-to-unicode remapped strings (Ġ = space); encoding pretokenizes
  with a GPT-2-style regex, remaps bytes, then merges lowest-rank pairs.
  The pretokenization regex is an ASCII-faithful approximation of the
  published \\p{L}-class patterns (python ``re`` has no unicode property
  classes); byte content per token — what grammar-constrained decoding
  actually depends on — is exact for every token.
- **sentencepiece-style BPE** (Llama-2 lineage: TinyLlama): pieces use ▁
  for space plus ``<0xNN>`` byte-fallback; the normalizer prepends ▁ and
  replaces spaces, then the same rank-merge loop runs over characters.

Special ids (bos/eos/pad) come from the checkpoint's added_tokens, not from
module constants — the engine reads ``tok.bos_id``/``tok.eos_id``.

Interface matches grammar.tokenizer.Tokenizer: encode/decode/token_bytes/
byte_pieces/vocab_size/pad_id/bos_id/eos_id, so TokenFSM and the engines are
tokenizer-agnostic.
"""

from __future__ import annotations

import json
import re
from functools import lru_cache
from pathlib import Path

_INF = 1 << 30

# GPT-2-style pretokenizer, ASCII approximation of the \p{L}/\p{N} classes.
_PRETOK = re.compile(
    r"'(?:[sdmt]|ll|ve|re)"
    r"| ?[^\W\d_]+"
    r"| ?\d+"
    r"| ?(?:[^\w\s]|_)+"
    r"|\s+(?!\S)"
    r"|\s+",
    re.UNICODE,
)

_BYTE_RE = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")


@lru_cache(maxsize=1)
def _byte_to_unicode() -> dict[int, str]:
    """GPT-2's invertible byte -> printable-unicode map."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


@lru_cache(maxsize=1)
def _unicode_to_byte() -> dict[str, int]:
    return {c: b for b, c in _byte_to_unicode().items()}


def _apply_merges(word: tuple[str, ...], ranks: dict[tuple[str, str], int]) -> tuple[str, ...]:
    """Classic BPE: repeatedly merge the lowest-rank adjacent pair."""
    while len(word) > 1:
        best_rank = _INF
        for pair in zip(word, word[1:]):
            r = ranks.get(pair, _INF)
            if r < best_rank:
                best_rank = r
                best = pair
        if best_rank == _INF:
            break
        a, b = best
        out: list[str] = []
        j = 0
        n = len(word)
        while j < n:
            if j < n - 1 and word[j] == a and word[j + 1] == b:
                out.append(a + b)
                j += 2
            else:
                out.append(word[j])
                j += 1
        word = tuple(out)
    return word


_BOS_NAMES = ("<s>", "<|begin_of_text|>", "<|startoftext|>")
_EOS_NAMES = ("</s>", "<|end_of_text|>", "<|eot_id|>", "<|endoftext|>", "<|im_end|>")
_PAD_NAMES = ("<pad>", "<|pad|>", "<unk>")


class HFTokenizer:
    """BPE tokenizer reconstructed from an HF tokenizer.json."""

    def __init__(
        self,
        vocab: dict[str, int],
        merges: list[tuple[str, str]],
        kind: str,  # "byte_level" | "sentencepiece"
        added: dict[str, int] | None = None,
        bos: str | None = None,
        eos: str | None = None,
        prepend: str | None = None,  # sentencepiece Prepend normalizer content
    ):
        if kind not in ("byte_level", "sentencepiece"):
            raise ValueError(f"unknown tokenizer kind {kind!r}")
        self.kind = kind
        self.vocab = dict(vocab)
        self.added = dict(added or {})
        for tok, tid in self.added.items():
            self.vocab.setdefault(tok, tid)
        self.vocab_size = max(self.vocab.values()) + 1
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.id_to_tok: dict[int, str] = {}
        for tok, tid in self.vocab.items():
            self.id_to_tok.setdefault(tid, tok)
        self.special_ids = set(self.added.values())
        self.prepend = prepend

        def find(names: tuple[str, ...], override: str | None) -> int | None:
            if override is not None:
                if override not in self.vocab:
                    raise ValueError(f"special token {override!r} not in vocab")
                return self.vocab[override]
            for nm in names:
                if nm in self.vocab:
                    return self.vocab[nm]
            return None

        self.bos_id = find(_BOS_NAMES, bos)
        self.eos_id = find(_EOS_NAMES, eos)
        if self.eos_id is None:
            raise ValueError("tokenizer.json has no recognizable EOS token")
        if self.bos_id is None:
            self.bos_id = self.eos_id
        pad = find(_PAD_NAMES, None)
        self.pad_id = pad if pad is not None else 0
        self.special_ids |= {self.bos_id, self.eos_id}

        # byte content per id (None = non-emitting special)
        self._pieces: list = [None] * self.vocab_size
        u2b = _unicode_to_byte()
        for tok, tid in self.vocab.items():
            if tid in self.special_ids:
                continue
            if self.kind == "byte_level":
                try:
                    self._pieces[tid] = bytes(u2b[c] for c in tok)
                except KeyError:
                    self._pieces[tid] = None  # added non-special marker token
            else:
                m = _BYTE_RE.match(tok)
                if m:
                    self._pieces[tid] = bytes([int(m.group(1), 16)])
                else:
                    self._pieces[tid] = tok.replace("▁", " ").encode()

        # regex that splits input on added-token strings (longest first)
        specials = sorted(self.added, key=len, reverse=True)
        self._special_split = (
            re.compile("(" + "|".join(re.escape(s) for s in specials) + ")")
            if specials
            else None
        )
        self._b2u = _byte_to_unicode()

    # ------------------------------------------------------------ encode

    def _encode_word(self, word: tuple[str, ...]) -> list[int]:
        ids: list[int] = []
        for sym in _apply_merges(word, self.ranks):
            tid = self.vocab.get(sym)
            if tid is not None:
                ids.append(tid)
                continue
            # byte fallback (sentencepiece <0xNN> pieces)
            for b in sym.encode():
                bt = self.vocab.get(f"<0x{b:02X}>")
                if bt is not None:
                    ids.append(bt)
        return ids

    def _encode_segment(self, text: str) -> list[int]:
        if not text:
            return []
        if self.kind == "byte_level":
            ids: list[int] = []
            for m in _PRETOK.finditer(text):
                mapped = "".join(self._b2u[b] for b in m.group(0).encode())
                ids.extend(self._encode_word(tuple(mapped)))
            return ids
        # sentencepiece: the Prepend normalizer applies to EVERY non-special
        # segment (HF runs normalization per split piece, so text following
        # a special token still gets its ▁ prefix), then space -> ▁
        norm = text.replace(" ", "▁")
        if self.prepend:
            norm = self.prepend + norm
        return self._encode_word(tuple(norm))

    def encode(self, text: str, bos: bool = False, eos: bool = False) -> list[int]:
        ids: list[int] = [self.bos_id] if bos else []
        if self._special_split is not None:
            for part in self._special_split.split(text):
                if part in self.added:
                    ids.append(self.added[part])
                else:
                    ids.extend(self._encode_segment(part))
        else:
            ids.extend(self._encode_segment(text))
        if eos:
            ids.append(self.eos_id)
        return ids

    # ------------------------------------------------------------ decode

    def token_bytes(self, token_id: int) -> bytes:
        p = self._pieces[token_id] if 0 <= token_id < self.vocab_size else None
        return p if p is not None else b""

    def byte_pieces(self) -> list:
        return self._pieces

    def decode(self, ids: list[int]) -> str:
        out = b"".join(self.token_bytes(i) for i in ids)
        text = out.decode(errors="replace")
        # sentencepiece decoders strip the prepended space
        if self.kind == "sentencepiece" and self.prepend and text.startswith(" "):
            text = text[1:]
        return text

    def id_of(self, content: str) -> int | None:
        return self.vocab.get(content)


def load_hf_tokenizer(
    path: str | Path,
    bos: str | None = None,
    eos: str | None = None,
) -> HFTokenizer:
    """Build an HFTokenizer from a tokenizer.json file (or its directory)."""
    p = Path(path)
    if p.is_dir():
        p = p / "tokenizer.json"
    obj = json.loads(p.read_text())
    model = obj.get("model", {})
    if model.get("type") not in (None, "BPE"):
        raise ValueError(f"unsupported tokenizer model type {model.get('type')!r}")
    vocab: dict[str, int] = model.get("vocab", {})
    merges_raw = model.get("merges", [])
    merges: list[tuple[str, str]] = []
    for m in merges_raw:
        if isinstance(m, str):
            a, _, b = m.partition(" ")
            merges.append((a, b))
        else:
            merges.append((m[0], m[1]))

    added = {
        t["content"]: t["id"]
        for t in obj.get("added_tokens", [])
        if t.get("special", True) or t["content"] not in vocab
    }

    # family detection: byte-level vocabs contain the Ġ space marker or a
    # ByteLevel pre_tokenizer; sentencepiece vocabs carry ▁ pieces or <0xNN>
    def has_bytelevel(component) -> bool:
        if not isinstance(component, dict):
            return False
        if component.get("type") == "ByteLevel":
            return True
        subs = component.get("pretokenizers") or component.get("normalizers") or []
        return any(has_bytelevel(s) for s in subs)

    if has_bytelevel(obj.get("pre_tokenizer")) or any(
        "Ġ" in t for t in list(vocab)[:2000]
    ):
        kind = "byte_level"
        prepend = None
    else:
        kind = "sentencepiece"
        prepend = "▁"
        norm = obj.get("normalizer") or {}
        subs = norm.get("normalizers", [norm]) if norm else []
        for s in subs:
            if isinstance(s, dict) and s.get("type") == "Prepend":
                prepend = s.get("prepend", "▁")
    return HFTokenizer(
        vocab=vocab, merges=merges, kind=kind, added=added, bos=bos, eos=eos,
        prepend=prepend,
    )
