"""JSON Schema (pydantic subset) -> regex for constrained decoding.

Compiles ``Model.model_json_schema()`` output into a regex accepted by
``regexlang.compile_regex``. The generated language is a *subset* of the
schema's language chosen for small DFAs and unambiguous decoding:

- objects emit ALL properties, in declaration order, compact (no whitespace);
  optional/nullable fields are emitted as ``null`` rather than omitted
- strings are printable-ASCII with JSON escapes, DFA-bounded at
  ``min(maxLength, 160)`` chars: every grammar path therefore terminates
  within a bounded byte count, so even a worst-case (random-weight) model
  under greedy decoding reaches EOS instead of cycling inside a free string
  until the byte budget truncates
- integers bounded by digit count chosen to stay <= the schema's maximum
- free-form objects (additionalProperties) allow up to 4 key/value pairs

Subset property (everything the DFA accepts validates under pydantic) is
enforced by tests that random-walk the DFA and validate samples.
"""

from __future__ import annotations

import math
from typing import Any

# JSON string contents: printable ASCII minus `"` and `\`, or a JSON escape.
STR_CHAR = r'(\\["\\/bfnrt]|[ !#-\[\]-~])'
# DFA-level string length cap (see module docstring). Each bounded string
# costs ~cap DFA states per occurrence; 160 covers every realistic utterance
# fragment, URL, and tts summary while keeping the DFA in the low tens of
# thousands of states.
DEFAULT_MAX_STRING = 160
STRING = '"' + STR_CHAR + "{0,%d}" % DEFAULT_MAX_STRING + '"'
# Non-empty variant (for keys etc.)
STRING_NONEMPTY = '"' + STR_CHAR + "{1,%d}" % DEFAULT_MAX_STRING + '"'


def _string_regex(max_length: int | None) -> str:
    n = DEFAULT_MAX_STRING if max_length is None else min(int(max_length), DEFAULT_MAX_STRING)
    return '"' + STR_CHAR + "{0,%d}" % n + '"'
KEY = r'"[a-zA-Z_][a-zA-Z0-9_\-]{0,30}"'
BOOL = "(true|false)"
NULL = "null"

FRAC = r"(\.\d{1,6})?"
FRAC0 = r"(\.0{1,6})?"
_UNBOUNDED = 999_999_999


def _digits_range(a: str, b: str) -> str:
    """Regex for fixed-length digit strings in [a, b] (same length)."""
    if a == b:
        return a
    if set(a) == {"0"} and set(b) == {"9"}:
        # full span shortcut — prevents O(3^digits) recursion blowup
        return r"\d" if len(a) == 1 else r"\d{%d}" % len(a)
    i = 0
    while a[i] == b[i]:
        i += 1
    pre = a[:i]
    da, db = int(a[i]), int(b[i])
    rest = len(a) - i - 1
    if rest == 0:
        body = f"[{da}-{db}]" if db > da else str(da)
        return pre + body
    parts = [str(da) + _digits_range(a[i + 1 :], "9" * rest)]
    if db - da >= 2:
        mid = f"[{da + 1}-{db - 1}]" if db - da > 2 else str(da + 1)
        mid += r"\d" if rest == 1 else r"\d{%d}" % rest
        parts.append(mid)
    parts.append(str(db) + _digits_range("0" * rest, b[i + 1 :]))
    return pre + "(" + "|".join(parts) + ")"


def int_range_regex(lo: int, hi: int) -> str:
    """Exact regex for decimal integers in [lo, hi], lo >= 0, no leading zeros."""
    if not 0 <= lo <= hi:
        raise ValueError(f"bad integer range [{lo}, {hi}]")
    parts = []
    for d in range(len(str(lo)), len(str(hi)) + 1):
        dlo = 0 if d == 1 else 10 ** (d - 1)
        dhi = 10**d - 1
        a, b = max(lo, dlo), min(hi, dhi)
        if a > b:
            continue
        if a == dlo and b == dhi and d > 1:
            # full width-d span: [1-9]\d{d-1}
            parts.append(r"[1-9]\d" if d == 2 else r"[1-9]\d{%d}" % (d - 1))
        else:
            parts.append(_digits_range(str(a), str(b)))
    return "(" + "|".join(parts) + ")" if len(parts) > 1 else parts[0]


def _int_regex(minimum: float | None, maximum: float | None) -> str:
    lo = -_UNBOUNDED if minimum is None else math.ceil(minimum)
    hi = _UNBOUNDED if maximum is None else math.floor(maximum)
    if lo > hi:
        raise ValueError(f"empty integer range [{minimum}, {maximum}]")
    parts = []
    if hi >= 0:
        parts.append(int_range_regex(max(lo, 0), hi))
    if lo < 0:
        neg_hi = min(hi, -1)
        parts.append("-" + int_range_regex(-neg_hi, -lo))
    return "(" + "|".join(parts) + ")" if len(parts) > 1 else parts[0]


def _nonneg_num_parts(lo: float, hi: float) -> list[str]:
    """Patterns for `intpart(.frac)?` values in [lo, hi] with 0 <= lo.

    Sound subset: values whose integer part falls in a partially-covered
    integer (e.g. [0.5, 1) when lo=0.5) are omitted rather than over-matched.
    """
    plo = int(lo) if float(lo).is_integer() else int(math.floor(lo)) + 1
    plo = max(0, plo)
    fhi = int(math.floor(hi))
    parts = []
    if fhi - 1 >= plo:
        parts.append(int_range_regex(plo, fhi - 1) + FRAC)
    if fhi >= plo:
        # top integer: free fraction would overshoot; allow .0* only when hi
        # is integral, bare integer otherwise
        parts.append(int_range_regex(fhi, fhi) + (FRAC0 if float(hi).is_integer() else ""))
    return parts


def _num_regex(minimum: float | None, maximum: float | None) -> str:
    if minimum is None and maximum is None:
        return r"(-?(0|[1-9]\d{0,8})(\.\d{1,6})?)"
    lo = float(-_UNBOUNDED) if minimum is None else float(minimum)
    hi = float(_UNBOUNDED) if maximum is None else float(maximum)
    if lo > hi:
        raise ValueError(f"empty number range [{minimum}, {maximum}]")
    parts: list[str] = []
    if hi >= 0:
        parts.extend(_nonneg_num_parts(max(lo, 0.0), hi))
    if lo < 0:
        parts.extend("-" + p for p in _nonneg_num_parts(max(0.0, -hi), -lo))
    if not parts:
        raise ValueError(f"unrepresentable number range [{minimum}, {maximum}]")
    return "(" + "|".join(parts) + ")"


def _escape_literal(s: str) -> str:
    out = []
    for ch in s:
        if ch in r"\.[](){}|*+?-":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def schema_to_regex(
    schema: dict[str, Any],
    overrides: dict[str, str] | None = None,
    max_free_pairs: int = 4,
) -> str:
    """Compile a JSON schema dict (with $defs) to a regex string.

    ``overrides`` maps property names to value regexes (applied wherever the
    property appears).
    """
    defs = schema.get("$defs", {})
    overrides = overrides or {}

    def resolve(node: dict[str, Any]) -> dict[str, Any]:
        while "$ref" in node:
            name = node["$ref"].split("/")[-1]
            node = defs[name]
        return node

    def compile_node(node: dict[str, Any]) -> str:
        node = resolve(node)

        if "enum" in node:
            opts = "|".join('"' + _escape_literal(str(v)) + '"' for v in node["enum"])
            return f"({opts})"
        if "const" in node:
            return '"' + _escape_literal(str(node["const"])) + '"'

        if "anyOf" in node:
            parts = [compile_node(opt) for opt in node["anyOf"]]
            # dedupe (e.g. int|float both become number-ish patterns)
            seen: list[str] = []
            for p in parts:
                if p not in seen:
                    seen.append(p)
            return "(" + "|".join(seen) + ")"

        t = node.get("type")
        if t == "null":
            return NULL
        if t == "boolean":
            return BOOL
        if t == "integer":
            lo, hi = node.get("minimum"), node.get("maximum")
            if "exclusiveMinimum" in node:
                lo = node["exclusiveMinimum"] + 1
            if "exclusiveMaximum" in node:
                hi = node["exclusiveMaximum"] - 1
            return _int_regex(lo, hi)
        if t == "number":
            lo, hi = node.get("minimum"), node.get("maximum")
            # exclusive float bounds: nudge by the smallest emittable step
            if "exclusiveMinimum" in node:
                lo = node["exclusiveMinimum"] + 1e-6
            if "exclusiveMaximum" in node:
                hi = node["exclusiveMaximum"] - 1e-6
            return _num_regex(lo, hi)
        if t == "string":
            return _string_regex(node.get("maxLength"))
        if t == "array":
            item = compile_node(node.get("items", {"type": "string"}))
            max_items = int(node.get("maxItems", 8))
            min_items = int(node.get("minItems", 0))
            if max_items <= 0 or max_items < min_items:
                return r"\[\]"
            body = item
            if min_items > 1:
                body += "(," + item + r"){%d}" % (min_items - 1)
            if max_items > max(min_items, 1):
                body += "(," + item + r"){0,%d}" % (max_items - max(min_items, 1))
            if min_items == 0:
                return r"\[(" + body + r")?\]"
            return r"\[" + body + r"\]"
        if t == "object":
            props = node.get("properties")
            if props:
                parts = []
                for name, sub in props.items():
                    if name in overrides:
                        val = overrides[name]
                    else:
                        val = compile_node(sub)
                    parts.append(f'"{_escape_literal(name)}":' + val)
                return r"\{" + ",".join(parts) + r"\}"
            ap = node.get("additionalProperties")
            if isinstance(ap, dict):
                val = compile_node(ap)
                pair = KEY + ":" + val
                body = pair + "(," + pair + r"){0,%d}" % (max_free_pairs - 1)
                return r"\{(" + body + r")?\}"
            return r"\{\}"

        # untyped (pydantic's Any): permit scalar JSON values
        return "(" + "|".join([STRING, BOOL, NULL, r"(-?(0|[1-9]\d{0,8})(\.\d{1,6})?)"]) + ")"

    return compile_node(schema)
