"""Token-level FSM: lift a byte DFA to a (state, token) transition table.

The table is the device-side artifact of grammar-constrained decoding: at each
decode step the engine gathers ``mask[state]`` (a vocab-sized boolean row) and
adds ``-inf`` to disallowed logits — per-sequence FSM state advances with a
second gather. No host round-trip per token (SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

import numpy as np

from .regexlang import DFA
from .tokenizer import Tokenizer, EOS_ID, BOS_ID, PAD_ID


class TokenFSM:
    """Dense (num_states, vocab) transition + mask tables.

    Attributes:
      next_state: int32 (S, V); -1 = dead/disallowed. EOS column loops in place
                  on accepting states.
      mask:       bool (S, V); True = token allowed in this state (EOS allowed
                  exactly on accepting states).
      start:      start state id.
    """

    def __init__(self, dfa: DFA, tokenizer: Tokenizer):
        S = dfa.num_states
        V = tokenizer.vocab_size
        # byte-expanded transitions: (S, 256)
        trans_b = dfa.trans[:, dfa.class_of]
        next_tab = np.full((S, V), -1, dtype=np.int32)

        identity = np.arange(S, dtype=np.int32)
        # Iterative DFS over the vocab trie; vec[s] = DFA state reached from s
        # after consuming the trie prefix (-1 = dead). Vectorized over states.
        stack: list[tuple[dict, np.ndarray]] = [(tokenizer._trie, identity)]
        while stack:
            node, vec = stack.pop()
            alive = vec >= 0
            for key, child in node.items():
                if key == -1:
                    next_tab[:, child] = vec
                else:
                    nvec = np.where(alive, trans_b[np.maximum(vec, 0), key], -1)
                    if (nvec >= 0).any():
                        stack.append((child, nvec))

        next_tab[:, PAD_ID] = -1
        next_tab[:, BOS_ID] = -1
        # EOS: allowed on accepting states; keeps the state (finished seqs are
        # excluded from further grammar stepping by the engine).
        next_tab[:, EOS_ID] = np.where(dfa.accepting, identity, -1)

        self.next_state = next_tab
        self.mask = next_tab >= 0
        self.start = dfa.start
        self.num_states = S
        self.vocab_size = V
        self.accepting = dfa.accepting.copy()

    def allowed(self, state: int) -> np.ndarray:
        return self.mask[state]

    def step(self, state: int, token_id: int) -> int:
        return int(self.next_state[state, token_id])

    def walk(self, token_ids: list[int]) -> int:
        s = self.start
        for t in token_ids:
            s = self.step(s, t)
            if s < 0:
                return s
        return s


def sample_dfa(dfa: DFA, rng: np.random.Generator, max_len: int = 4000) -> bytes:
    """Random-walk the DFA to an accepting state (test/debug helper)."""
    # representative bytes per class
    by_class: dict[int, list[int]] = {}
    for b in range(256):
        by_class.setdefault(int(dfa.class_of[b]), []).append(b)
    out = bytearray()
    s = dfa.start
    for _ in range(max_len):
        if dfa.accepting[s] and rng.random() < 0.3:
            return bytes(out)
        classes = np.nonzero(dfa.trans[s] >= 0)[0]
        if len(classes) == 0:
            if dfa.accepting[s]:
                return bytes(out)
            raise RuntimeError("stuck in non-accepting state with no moves")
        c = int(rng.choice(classes))
        b = int(rng.choice(by_class[c]))
        out.append(b)
        s = int(dfa.trans[s, c])
    # budget exhausted: walk greedily toward accept by preferring structural bytes
    for _ in range(2000):
        if dfa.accepting[s]:
            return bytes(out)
        classes = np.nonzero(dfa.trans[s] >= 0)[0]
        # prefer classes containing closing punctuation to terminate quickly
        pick = None
        for c in classes:
            if any(ch in by_class[int(c)] for ch in (0x22, 0x5D, 0x7D, 0x2C, 0x3A)):
                pick = int(c)
                break
        c = pick if pick is not None else int(classes[0])
        b = by_class[c][0]
        out.append(b)
        s = int(dfa.trans[s, c])
    raise RuntimeError("could not reach accepting state")
