"""Token-level FSM: lift a byte DFA to (state, token) transitions, compressed.

The device-side artifact of grammar-constrained decoding. Round 1 stored the
transition relation dense as ``(S, V)`` int32 + bool tables; at a real
checkpoint vocab (V = 32k for TinyLlama, 128k for Llama-3) and S ≈ 6k DFA
states that is gigabytes of HBM and was called out as a design wall
(VERDICT.md weak #4). The fix is **token-class column compression**: two
tokens are equivalent iff their next-state columns agree across all states,
and in practice almost every token in a large vocab is either dead everywhere
or behaves like one of a few hundred representatives (the intent grammar has
~300 distinct columns at any vocab size). So we store

  - ``col_id``  (V,) int32 — token → equivalence class
  - ``table``   (S, C) int32 — next state per (state, class); -1 = dead

and recover a full vocab row on device with two gathers:
``row = table[state][col_id]`` (one (C,) gather + one (V,) take that XLA
fuses into the logit-mask loop). Memory is S·C + V instead of S·V — the
intent grammar at Llama-3 scale drops from ~3 GB to ~8 MB.

At each decode step the engine masks logits where ``row < 0`` and advances
per-sequence state with ``table[state, col_id[tok]]`` — no host round-trip
per token (SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .regexlang import DFA


class DeviceFSM(NamedTuple):
    """Device-resident FSM tables (a jit-traceable pytree).

    ``dense_mask`` is populated only for small vocabs (the Pallas
    ``masked_argmax`` kernel streams dense (S, V) mask tiles); ``None``
    switches the engine to the compressed XLA path.

    ``ff_tokens``/``ff_len`` (grammar fast-forward, optional): for each
    state, the canonical tokenization of its FORCED byte run — the unique
    byte path the grammar admits (JSON scaffolding between free choices).
    The decode loop appends these without sampling: in the memory-bound
    decode regime a (1+W)-token forward costs the same HBM traffic as a
    1-token forward, so forced tokens are nearly free.
    """

    table: jax.Array  # (S, C) int32; -1 = dead
    col_id: jax.Array  # (V,) int32 token -> class
    dense_mask: Optional[jax.Array]  # (S, V) bool or None
    ff_tokens: Optional[jax.Array] = None  # (S, W) int32; -1 pad
    ff_len: Optional[jax.Array] = None  # (S,) int32 0..W


def fsm_row(t: DeviceFSM, state: jax.Array) -> jax.Array:
    """(B,) states -> (B, V) int32 next-state row (-1 = disallowed)."""
    return jnp.take(t.table[state], t.col_id, axis=-1)


def fsm_advance(t: DeviceFSM, state: jax.Array, tok: jax.Array) -> jax.Array:
    """(B,) states, (B,) sampled tokens -> (B,) next states."""
    return t.table[state, t.col_id[tok]]


class TokenFSM:
    """Column-compressed (state, token) transition relation.

    Built by a vectorized DFS over the vocab byte trie: each trie node
    carries the (S,) vector of DFA states reached from every start state by
    the node's byte prefix; a token's column is the vector at its leaf,
    interned into the class table by content hash. Tokens never reached
    (dead from every state) share class 0, the all-dead column.

    ``vocab_size`` may exceed the tokenizer's (checkpoints pad their embed
    table); the extra ids are dead.
    """

    def __init__(self, dfa: DFA, tokenizer, vocab_size: int | None = None):
        S = dfa.num_states
        V = int(vocab_size or tokenizer.vocab_size)
        if V < tokenizer.vocab_size:
            raise ValueError(
                f"vocab_size {V} smaller than tokenizer vocab {tokenizer.vocab_size}"
            )
        trans_b = dfa.trans[:, dfa.class_of]  # (S, 256) byte-expanded
        identity = np.arange(S, dtype=np.int32)

        # trie over token byte pieces; distinct ids may share bytes (real
        # vocabs carry duplicates via added_tokens), so leaves hold id lists
        trie: dict = {}
        for tid, piece in enumerate(tokenizer.byte_pieces()):
            if not piece:  # None or b"": specials / non-emitting tokens
                continue
            node = trie
            for b in piece:
                node = node.setdefault(b, {})
            node.setdefault(-1, []).append(tid)

        dead = np.full((S,), -1, dtype=np.int32)
        columns: list[np.ndarray] = [dead]
        col_of: dict[bytes, int] = {dead.tobytes(): 0}
        col_id = np.zeros((V,), dtype=np.int32)

        def intern(vec: np.ndarray) -> int:
            key = vec.tobytes()
            idx = col_of.get(key)
            if idx is None:
                idx = len(columns)
                col_of[key] = idx
                columns.append(vec)
            return idx

        stack: list[tuple[dict, np.ndarray]] = [(trie, identity)]
        while stack:
            node, vec = stack.pop()
            alive = vec >= 0
            for key, child in node.items():
                if key == -1:
                    c = intern(vec)
                    for tid in child:
                        col_id[tid] = c
                else:
                    nvec = np.where(alive, trans_b[np.maximum(vec, 0), key], -1).astype(
                        np.int32
                    )
                    if (nvec >= 0).any():
                        stack.append((child, nvec))

        # EOS is allowed exactly on accepting states and keeps the state
        # (finished rows are excluded from further stepping by the engine).
        # (pad/bos need no forcing: true specials carry piece=None and are
        # dead already, while a checkpoint whose pad falls back to a content
        # token keeps that token usable inside JSON strings)
        eos_vec = np.where(dfa.accepting, identity, -1).astype(np.int32)
        col_id[tokenizer.eos_id] = intern(eos_vec)

        self.table = np.stack(columns, axis=1)  # (S, C)
        self.col_id = col_id
        self.start = dfa.start
        self.num_states = S
        self.num_classes = len(columns)
        self.vocab_size = V
        self.accepting = dfa.accepting.copy()
        # kept for forced_tables(): byte-expanded transitions + piece trie
        self._trans_b = trans_b
        self._trie = trie
        # lookahead(): canonical forced chain per state, computed on demand
        self._lookahead_cache: dict[int, list[int]] = {}
        self._forced_arr: tuple[np.ndarray, np.ndarray] | None = None

    # ------------------------------------------------------------ dense views

    @property
    def next_state(self) -> np.ndarray:
        """Dense (S, V) int32 view — O(S·V); tests and toy vocabs only."""
        return self.table[:, self.col_id]

    @property
    def mask(self) -> np.ndarray:
        """Dense (S, V) bool view — O(S·V); tests and toy vocabs only."""
        return self.next_state >= 0

    # ------------------------------------------------------------ host stepping

    def allowed(self, state: int) -> np.ndarray:
        return self.table[state][self.col_id] >= 0

    def step(self, state: int, token_id: int) -> int:
        return int(self.table[state, self.col_id[token_id]])

    def walk(self, token_ids: list[int]) -> int:
        s = self.start
        for t in token_ids:
            s = self.step(s, t)
            if s < 0:
                return s
        return s

    # ------------------------------------------------------------ fast-forward

    def _forced_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(forced (S,) bool, fbyte (S,) int): a state is "forced" when the
        byte DFA admits exactly one byte and is not accepting (accepting
        adds the EOS choice); fbyte is that byte. Computed once."""
        if self._forced_arr is None:
            legal = self._trans_b >= 0  # (S, 256)
            forced = (legal.sum(axis=1) == 1) & ~self.accepting
            self._forced_arr = (forced, np.argmax(legal, axis=1))
        return self._forced_arr

    def _forced_run(self, state: int) -> list[int]:
        """The unique forced byte path from ``state`` ([] when the state is
        a free choice point / dead / accepting). Any grammar-legal
        continuation must emit these bytes."""
        forced, fbyte = self._forced_arrays()
        run, st = [], state
        while forced[st] and len(run) < 4096:
            b = int(fbyte[st])
            run.append(b)
            st = int(self._trans_b[st, b])
        return run

    def _tile_run(self, run: list[int], width: int) -> list[int]:
        """Greedy-longest canonical tokenization of a byte run over the
        vocab trie (first id of a piece = canonical). THE one copy of the
        canonical-tiling convention — forced_tables and lookahead must
        stay bit-identical or draft acceptance quietly degrades."""
        toks, i = [], 0
        while i < len(run) and len(toks) < width:
            node, best, j = self._trie, None, i
            while j < len(run) and run[j] in node:
                node = node[run[j]]
                j += 1
                if -1 in node:
                    best = (j, node[-1][0])
            if best is None:
                break  # no piece tiles here; stop fast-forwarding
            i = best[0]
            toks.append(best[1])
        return toks

    def forced_tables(self, width: int) -> tuple[np.ndarray, np.ndarray]:
        """(ff_tokens (S, width) int32, ff_len (S,) int32): per state, the
        canonical tokenization (``_tile_run``) of its forced byte run
        (``_forced_run``). Runs longer than ``width`` tokens continue next
        step because the state after a truncated chain is itself forced.
        Chains never contain EOS (runs stop before accepting states).
        """
        S = self.num_states
        forced, _ = self._forced_arrays()
        ff_tokens = np.full((S, width), -1, dtype=np.int32)
        ff_len = np.zeros((S,), dtype=np.int32)
        for s in range(S):
            if not forced[s]:
                continue
            toks = self._tile_run(self._forced_run(s), width)
            ff_tokens[s, : len(toks)] = toks
            ff_len[s] = len(toks)
        return ff_tokens, ff_len

    def lookahead(self, state: int, width: int) -> list[int]:
        """Draft tokens along the forced byte path from ``state`` (the
        speculative-decoding host API; serve.spec FSMDrafter).

        Unlike ``forced_tables`` — whose chains are *forced* onto the
        stream without sampling — lookahead tokens are only PROPOSALS: the
        verify pass checks them against the target model's greedy choice,
        so the canonical (greedy-longest) tokenization here is a guess the
        model is free to reject in favor of a different tiling of the same
        bytes. Returns up to ``width`` token ids; [] when ``state`` is not
        byte-forced (a free choice point) or is dead/accepting. Chains are
        cached per state (full length) and sliced per call."""
        if state < 0 or state >= self.num_states or width <= 0:
            return []
        chain = self._lookahead_cache.get(state)
        if chain is None:
            # tile the WHOLE forced run (bounded by the 4096-byte run cap),
            # so the cache serves any draft width without silent truncation
            run = self._forced_run(state)
            chain = self._tile_run(run, len(run))
            self._lookahead_cache[state] = chain
        return chain[:width]

    # ------------------------------------------------------------ device tables

    def device_tables(self, dense_limit: int = 1 << 25, ff_width: int = 0) -> DeviceFSM:
        """Ship tables to device. The dense bool mask (Pallas masked_argmax
        fodder) is included only while S·V stays under ``dense_limit``
        entries (32M default = 32 MB of bool); past that the engine's
        compressed XLA path is the only sane layout. ``ff_width > 0``
        attaches the grammar fast-forward chains (forced_tables)."""
        dense = None
        if self.num_states * self.vocab_size <= dense_limit:
            dense = jnp.asarray(self.mask)
        ff_tok = ff_len = None
        if ff_width > 0:
            t, l = self.forced_tables(ff_width)
            ff_tok, ff_len = jnp.asarray(t), jnp.asarray(l)
        return DeviceFSM(
            table=jnp.asarray(self.table),
            col_id=jnp.asarray(self.col_id),
            dense_mask=dense,
            ff_tokens=ff_tok,
            ff_len=ff_len,
        )


def sample_dfa(dfa: DFA, rng: np.random.Generator, max_len: int = 4000) -> bytes:
    """Random-walk the DFA to an accepting state (test/debug helper)."""
    # representative bytes per class
    by_class: dict[int, list[int]] = {}
    for b in range(256):
        by_class.setdefault(int(dfa.class_of[b]), []).append(b)
    out = bytearray()
    s = dfa.start
    for _ in range(max_len):
        if dfa.accepting[s] and rng.random() < 0.3:
            return bytes(out)
        classes = np.nonzero(dfa.trans[s] >= 0)[0]
        if len(classes) == 0:
            if dfa.accepting[s]:
                return bytes(out)
            raise RuntimeError("stuck in non-accepting state with no moves")
        c = int(rng.choice(classes))
        b = int(rng.choice(by_class[c]))
        out.append(b)
        s = int(dfa.trans[s, c])
    # budget exhausted: walk greedily toward accept by preferring structural bytes
    for _ in range(2000):
        if dfa.accepting[s]:
            return bytes(out)
        classes = np.nonzero(dfa.trans[s] >= 0)[0]
        # prefer classes containing closing punctuation to terminate quickly
        pick = None
        for c in classes:
            if any(ch in by_class[int(c)] for ch in (0x22, 0x5D, 0x7D, 0x2C, 0x3A)):
                pick = int(c)
                break
        c = pick if pick is not None else int(classes[0])
        b = by_class[c][0]
        out.append(b)
        s = int(dfa.trans[s, c])
    raise RuntimeError("could not reach accepting state")
