from .regexlang import compile_regex, DFA
from .jsonschema import schema_to_regex
from .tokenizer import Tokenizer, train_bpe
from .fsm import TokenFSM, DeviceFSM, fsm_advance, fsm_row
from .intent_grammar import build_fsm_for, build_intent_fsm, intent_regex, default_tokenizer
from .hf_tokenizer import HFTokenizer, load_hf_tokenizer

__all__ = [
    "compile_regex",
    "DFA",
    "schema_to_regex",
    "Tokenizer",
    "train_bpe",
    "TokenFSM",
    "DeviceFSM",
    "fsm_advance",
    "fsm_row",
    "build_fsm_for",
    "HFTokenizer",
    "load_hf_tokenizer",
    "build_intent_fsm",
    "intent_regex",
    "default_tokenizer",
]
