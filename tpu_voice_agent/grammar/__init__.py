from .regexlang import compile_regex, DFA
from .jsonschema import schema_to_regex
from .tokenizer import Tokenizer, train_bpe
from .fsm import TokenFSM
from .intent_grammar import build_intent_fsm, intent_regex, default_tokenizer

__all__ = [
    "compile_regex",
    "DFA",
    "schema_to_regex",
    "Tokenizer",
    "train_bpe",
    "TokenFSM",
    "build_intent_fsm",
    "intent_regex",
    "default_tokenizer",
]
