"""In-tree byte-fallback tokenizer with a trainable BPE vocab.

No network egress is assumed anywhere in this framework, so instead of
downloading an HF tokenizer we build one: 256 byte pieces guarantee coverage,
a BPE pass over an in-repo corpus (system prompt + few-shots + sample
utterances) adds common English/JSON merges, and schema literals (quoted keys,
intent type names, punctuation runs) are injected verbatim so an entire intent
JSON decodes in tens of steps rather than hundreds of byte steps. Encoding is
greedy longest-match (trie) — any token sequence's bytes walk the grammar DFA
identically regardless of segmentation, which is what constrained decoding
needs.

A loader for external HF ``tokenizer.json`` vocabs is provided for when real
checkpoints are available (gated; uses the ``tokenizers`` wheel if present).
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
SPECIALS = ("<pad>", "<bos>", "<eos>")


def train_bpe(corpus: list[str], num_merges: int) -> list[bytes]:
    """Classic BPE merge learning over pre-tokenized words.

    Pre-tokenization splits at every non-alphanumeric character (each such
    character becomes its own one-byte word), so merges never span a word or
    punctuation boundary; multi-char JSON glue is supplied as injected
    literals instead (intent_grammar.schema_literals). Returns learned merge
    pieces (byte strings), most frequent first.
    """
    words: Counter[tuple[bytes, ...]] = Counter()
    for text in corpus:
        buf = ""
        for ch in text:
            if ch.isalnum():
                buf += ch
            else:
                if buf:
                    words[tuple(bytes([b]) for b in buf.encode())] += 1
                    buf = ""
                words[tuple(bytes([b]) for b in ch.encode())] += 1
        if buf:
            words[tuple(bytes([b]) for b in buf.encode())] += 1

    merges: list[bytes] = []
    work = dict(words)
    for _ in range(num_merges):
        pairs: Counter[tuple[bytes, bytes]] = Counter()
        for word, cnt in work.items():
            for a, b in zip(word, word[1:]):
                pairs[(a, b)] += cnt
        if not pairs:
            break
        (a, b), cnt = pairs.most_common(1)[0]
        if cnt < 2:
            break
        merged = a + b
        merges.append(merged)
        new_work: dict[tuple[bytes, ...], int] = {}
        for word, wcnt in work.items():
            out: list[bytes] = []
            i = 0
            while i < len(word):
                if i + 1 < len(word) and word[i] == a and word[i + 1] == b:
                    out.append(merged)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            key = tuple(out)
            new_work[key] = new_work.get(key, 0) + wcnt
        work = new_work
    return merges


class Tokenizer:
    """Greedy longest-match tokenizer over a byte-complete vocab.

    Exposes the interface every tokenizer in the framework satisfies
    (grammar.hf_tokenizer.HFTokenizer is the real-checkpoint twin):
    ``encode/decode/token_bytes/byte_pieces``, ``vocab_size`` and the
    instance special ids ``pad_id/bos_id/eos_id`` (engines must use these,
    never the module constants — real checkpoints place them elsewhere).
    """

    def __init__(self, pieces: list[bytes]):
        # pieces[i] is the byte string for id i + len(SPECIALS)
        self.pieces = pieces
        self.vocab_size = len(SPECIALS) + len(pieces)
        self.pad_id = PAD_ID
        self.bos_id = BOS_ID
        self.eos_id = EOS_ID
        self.piece_bytes: list[bytes] = [s.encode() for s in SPECIALS] + pieces
        self._trie: dict = {}
        for idx, piece in enumerate(pieces):
            node = self._trie
            for b in piece:
                node = node.setdefault(b, {})
            node[-1] = idx + len(SPECIALS)

    @classmethod
    def build(
        cls,
        corpus: list[str] | None = None,
        literals: list[str] | None = None,
        vocab_size: int = 4096,
    ) -> "Tokenizer":
        pieces: list[bytes] = [bytes([b]) for b in range(256)]
        seen = set(pieces)

        def add(p: bytes) -> None:
            if p and p not in seen and len(pieces) + len(SPECIALS) < vocab_size:
                pieces.append(p)
                seen.add(p)

        for lit in literals or []:
            add(lit.encode())
        budget = vocab_size - len(SPECIALS) - len(pieces)
        if corpus and budget > 0:
            for piece in train_bpe(corpus, num_merges=budget * 2):
                add(piece)
        return cls(pieces)

    def encode(self, text: str, bos: bool = False, eos: bool = False) -> list[int]:
        data = text.encode()
        ids: list[int] = [BOS_ID] if bos else []
        i = 0
        n = len(data)
        while i < n:
            node = self._trie
            best_id = None
            best_len = 0
            j = i
            while j < n and data[j] in node:
                node = node[data[j]]
                j += 1
                if -1 in node:
                    best_id = node[-1]
                    best_len = j - i
            if best_id is None:
                # byte fallback always exists
                best_id = data[i] + len(SPECIALS)
                best_len = 1
            ids.append(best_id)
            i += best_len
        if eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: list[int]) -> str:
        out = b"".join(self.token_bytes(i) for i in ids)
        return out.decode(errors="replace")

    def token_bytes(self, token_id: int) -> bytes:
        """Bytes a token contributes to the stream ('' for specials or
        padded-vocab ids past the table — mesh engines pad the model vocab
        to a tp multiple)."""
        if token_id < len(SPECIALS) or token_id >= len(self.piece_bytes):
            return b""
        return self.piece_bytes[token_id]

    def byte_pieces(self) -> list:
        """Per-id byte content; None/b'' for non-emitting specials (the
        TokenFSM builds its vocab trie from this)."""
        return [None] * len(SPECIALS) + self.pieces

    # -------------------------------------------------- persistence

    def save(self, path: str | Path) -> None:
        Path(path).write_text(
            json.dumps({"pieces": [p.hex() for p in self.pieces]})
        )

    @classmethod
    def load(cls, path: str | Path) -> "Tokenizer":
        obj = json.loads(Path(path).read_text())
        return cls([bytes.fromhex(h) for h in obj["pieces"]])

    @classmethod
    def from_hf_tokenizer_json(cls, path: str | Path):
        """Real-checkpoint import moved to grammar.hf_tokenizer (true BPE
        merges, byte-level + sentencepiece families, checkpoint special ids).
        Kept as a forwarding shim for round-1 callers."""
        from .hf_tokenizer import load_hf_tokenizer

        return load_hf_tokenizer(path)
