"""The intent grammar: ParseResponse schema -> regex -> DFA -> TokenFSM.

Single source of truth: ``schemas.ParseResponse`` (pydantic). Everything here
is derived and cached at process level. The reference instead *hoped* the LLM
emitted valid JSON and re-asked on failure (apps/brain/src/server.ts:110-121).
"""

from __future__ import annotations

from functools import lru_cache

from ..schemas import INTENT_TYPES, TARGET_STRATEGIES, ParseResponse
from .jsonschema import schema_to_regex
from .regexlang import DFA, compile_regex
from .tokenizer import Tokenizer
from .fsm import TokenFSM


def schema_literals() -> list[str]:
    """Vocab pieces that make intent JSON decode in few tokens."""
    lits: list[str] = []
    keys = [
        "version",
        "intents",
        "type",
        "target",
        "strategy",
        "value",
        "role",
        "name",
        "args",
        "priority",
        "requires_confirmation",
        "timeout_ms",
        "retries",
        "context_updates",
        "confidence",
        "tts_summary",
        "follow_up_question",
        "text",
        "context",
        "session_id",
        "query",
        "url",
        "field",
        "direction",
        "index",
        "fileRef",
        "format",
        "last_query",
    ]
    for k in keys:
        lits.append(f'"{k}":')
        lits.append(f',"{k}":')
    for t in INTENT_TYPES:
        lits.append(f'"{t}"')
    for s in TARGET_STRATEGIES:
        lits.append(f'"{s}"')
    lits += [
        '{"version":"1.0","intents":[',
        '{"type":',
        'null,',
        "null}",
        "null",
        "true",
        "false",
        "true,",
        "false,",
        '":null',
        "[]",
        "{}",
        "}]",
        "},{",
        '":{"',
        '"},',
        '"}',
        '{"',
        '":"',
        '","',
        "15000",
        "10000",
        "0.9",
        "0.8",
        ":1,",
        ":0,",
        ":0}",
        "<|system|>\n",
        "<|user|>\n",
        "<|assistant|>\n",
    ]
    return lits


def intent_regex() -> str:
    schema = ParseResponse.model_json_schema()
    return schema_to_regex(schema, overrides={"version": r'"1\.0"'})


@lru_cache(maxsize=1)
def intent_dfa() -> DFA:
    """Compile (or load) the intent DFA.

    With DFA-bounded strings the automaton is ~35k states and ~20 s of
    pure-python subset construction — too slow to pay per process, so the
    compiled tables are cached on disk keyed by the regex hash (the regex is
    derived from the pydantic schema, so schema edits invalidate cleanly).
    """
    import hashlib
    import os
    import tempfile
    from pathlib import Path

    import numpy as np

    rx = intent_regex()
    key = hashlib.sha256(rx.encode()).hexdigest()[:16]
    cache_dir = Path(
        os.environ.get("TPU_VOICE_CACHE_DIR")
        or Path.home() / ".cache" / "tpu_voice_agent"
    )
    path = cache_dir / f"intent_dfa_{key}.npz"
    if path.exists():
        try:
            z = np.load(path)
            return DFA(z["trans"], z["accepting"], z["class_of"], int(z["start"]))
        except Exception:
            # truncated/corrupt cache (crash mid-write, format drift):
            # fall through and recompile — the cache is best-effort
            try:
                path.unlink()
            except OSError:
                pass
    dfa = compile_regex(rx)
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix=".npz")
        os.close(fd)
        np.savez_compressed(
            tmp, trans=dfa.trans, accepting=dfa.accepting,
            class_of=dfa.class_of, start=np.int64(dfa.start),
        )
        os.replace(tmp, path)  # atomic: concurrent processes race safely
    except OSError:
        pass  # cache is best-effort
    return dfa


@lru_cache(maxsize=1)
def default_tokenizer() -> Tokenizer:
    from ..services.prompts import corpus_for_tokenizer

    return Tokenizer.build(
        corpus=corpus_for_tokenizer(),
        literals=schema_literals(),
        vocab_size=4096,
    )


@lru_cache(maxsize=1)
def build_intent_fsm() -> tuple[Tokenizer, TokenFSM]:
    tok = default_tokenizer()
    fsm = TokenFSM(intent_dfa(), tok)
    return tok, fsm


def build_fsm_for(tokenizer, vocab_size: int | None = None) -> TokenFSM:
    """Intent-grammar FSM over an arbitrary tokenizer (HFTokenizer for real
    checkpoints). ``vocab_size`` may exceed the tokenizer's to match a
    checkpoint's padded embedding table.

    The multi-second FSM build is cached ON the tokenizer object (keyed by
    vocab width), so the cache lives and dies with the tokenizer — an id()-
    keyed global here would both leak and risk aliasing a recycled address
    to the wrong tokenizer's tables."""
    cache = tokenizer.__dict__.setdefault("_intent_fsm_cache", {})
    key = int(vocab_size or tokenizer.vocab_size)
    fsm = cache.get(key)
    if fsm is None:
        fsm = TokenFSM(intent_dfa(), tokenizer, vocab_size=vocab_size)
        cache[key] = fsm
    return fsm
