"""The intent grammar: ParseResponse schema -> regex -> DFA -> TokenFSM.

Single source of truth: ``schemas.ParseResponse`` (pydantic). Everything here
is derived and cached at process level. The reference instead *hoped* the LLM
emitted valid JSON and re-asked on failure (apps/brain/src/server.ts:110-121).
"""

from __future__ import annotations

from functools import lru_cache

from ..schemas import INTENT_TYPES, TARGET_STRATEGIES, ParseResponse
from .jsonschema import schema_to_regex
from .regexlang import DFA, compile_regex
from .tokenizer import Tokenizer
from .fsm import TokenFSM


def schema_literals() -> list[str]:
    """Vocab pieces that make intent JSON decode in few tokens."""
    lits: list[str] = []
    keys = [
        "version",
        "intents",
        "type",
        "target",
        "strategy",
        "value",
        "role",
        "name",
        "args",
        "priority",
        "requires_confirmation",
        "timeout_ms",
        "retries",
        "context_updates",
        "confidence",
        "tts_summary",
        "follow_up_question",
        "text",
        "context",
        "session_id",
        "query",
        "url",
        "field",
        "direction",
        "index",
        "fileRef",
        "format",
        "last_query",
    ]
    for k in keys:
        lits.append(f'"{k}":')
        lits.append(f',"{k}":')
    for t in INTENT_TYPES:
        lits.append(f'"{t}"')
    for s in TARGET_STRATEGIES:
        lits.append(f'"{s}"')
    lits += [
        '{"version":"1.0","intents":[',
        '{"type":',
        'null,',
        "null}",
        "null",
        "true",
        "false",
        "true,",
        "false,",
        '":null',
        "[]",
        "{}",
        "}]",
        "},{",
        '":{"',
        '"},',
        '"}',
        '{"',
        '":"',
        '","',
        "15000",
        "10000",
        "0.9",
        "0.8",
        ":1,",
        ":0,",
        ":0}",
        "<|system|>\n",
        "<|user|>\n",
        "<|assistant|>\n",
    ]
    return lits


def intent_regex() -> str:
    schema = ParseResponse.model_json_schema()
    return schema_to_regex(schema, overrides={"version": r'"1\.0"'})


@lru_cache(maxsize=1)
def intent_dfa() -> DFA:
    return compile_regex(intent_regex())


@lru_cache(maxsize=1)
def default_tokenizer() -> Tokenizer:
    from ..services.prompts import corpus_for_tokenizer

    return Tokenizer.build(
        corpus=corpus_for_tokenizer(),
        literals=schema_literals(),
        vocab_size=4096,
    )


@lru_cache(maxsize=1)
def build_intent_fsm() -> tuple[Tokenizer, TokenFSM]:
    tok = default_tokenizer()
    fsm = TokenFSM(intent_dfa(), tok)
    return tok, fsm
