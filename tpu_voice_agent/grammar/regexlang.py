"""Byte-level regex -> NFA -> DFA compiler.

This is the foundation of grammar-constrained decoding: the intent JSON schema
compiles to a regex (jsonschema.py), which compiles here to a dense DFA over
byte-equivalence classes, which fsm.py lifts to a token-level transition table
used as a per-step logit mask on TPU. The reference repo has nothing like
this — it validates *after* sampling and re-asks the LLM on failure
(apps/brain/src/server.ts:110-121); we make invalid JSON unrepresentable.

Supported syntax: literals, escapes (\\d \\w \\s \\n \\t \\r and escaped
metachars), character classes ``[a-z0-9_]`` / ``[^...]``, grouping ``()``,
alternation ``|``, quantifiers ``* + ? {m} {m,} {m,n}``, and ``.`` (printable
ASCII incl. space). Patterns are ASCII; the DFA alphabet is bytes 0..255.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

DEAD = -1

_PRINTABLE = frozenset(range(0x20, 0x7F))
_DIGITS = frozenset(range(0x30, 0x3A))
_WORD = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B)) + list(range(0x61, 0x7B)) + [0x5F]
)
_SPACE = frozenset({0x20, 0x09, 0x0A, 0x0D, 0x0B, 0x0C})
_ALL = frozenset(range(256))


# ---------------------------------------------------------------- AST


@dataclass
class Node:
    pass


@dataclass
class Lit(Node):
    chars: frozenset  # set of byte values


@dataclass
class Seq(Node):
    parts: list


@dataclass
class Alt(Node):
    options: list


@dataclass
class Rep(Node):
    child: Node
    lo: int
    hi: int | None  # None = unbounded


# ---------------------------------------------------------------- parser


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> Node:
        node = self._alt()
        if self.i != len(self.p):
            raise ValueError(f"unexpected {self.p[self.i]!r} at {self.i} in regex")
        return node

    def _alt(self) -> Node:
        options = [self._seq()]
        while self.peek() == "|":
            self.next()
            options.append(self._seq())
        return options[0] if len(options) == 1 else Alt(options)

    def _seq(self) -> Node:
        parts = []
        while self.peek() is not None and self.peek() not in "|)":
            parts.append(self._repeat())
        if len(parts) == 1:
            return parts[0]
        return Seq(parts)

    def _repeat(self) -> Node:
        atom = self._atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.next()
                atom = Rep(atom, 0, None)
            elif ch == "+":
                self.next()
                atom = Rep(atom, 1, None)
            elif ch == "?":
                self.next()
                atom = Rep(atom, 0, 1)
            elif ch == "{":
                self.next()
                lo = self._int()
                hi: int | None = lo
                if self.peek() == ",":
                    self.next()
                    hi = None if self.peek() == "}" else self._int()
                if self.next() != "}":
                    raise ValueError("unterminated {m,n}")
                if hi is not None and hi < lo:
                    raise ValueError(f"inverted quantifier {{{lo},{hi}}}")
                atom = Rep(atom, lo, hi)
            else:
                return atom

    def _int(self) -> int:
        s = ""
        while self.peek() is not None and self.peek().isdigit():
            s += self.next()
        if not s:
            raise ValueError("expected integer in quantifier")
        return int(s)

    def _atom(self) -> Node:
        ch = self.next()
        if ch == "(":
            node = self._alt()
            if self.peek() != ")":
                raise ValueError("unbalanced (")
            self.next()
            return node
        if ch == "[":
            return self._cls()
        if ch == ".":
            return Lit(_PRINTABLE)
        if ch == "\\":
            return Lit(self._escape(self.next()))
        if ch in "*+?{}|)":
            raise ValueError(f"unexpected metachar {ch!r}")
        return Lit(frozenset({ord(ch)}))

    def _escape(self, ch: str) -> frozenset:
        if ch == "d":
            return _DIGITS
        if ch == "w":
            return _WORD
        if ch == "s":
            return _SPACE
        if ch == "n":
            return frozenset({0x0A})
        if ch == "t":
            return frozenset({0x09})
        if ch == "r":
            return frozenset({0x0D})
        return frozenset({ord(ch)})

    def _cls(self) -> Node:
        negate = False
        if self.peek() == "^":
            self.next()
            negate = True
        chars: set[int] = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise ValueError("unterminated [")
            if ch == "]" and not first:
                self.next()
                break
            first = False
            self.next()
            if ch == "\\":
                esc = self.next()
                sub = self._escape(esc)
                if len(sub) != 1:
                    # multi-char class (\d, \w, \s) cannot anchor a range
                    chars |= sub
                    continue
                lo = next(iter(sub))
            else:
                lo = ord(ch)
            if self.peek() == "-" and self.i + 1 < len(self.p) and self.p[self.i + 1] != "]":
                self.next()
                hi_ch = self.next()
                if hi_ch == "\\":
                    hi_set = self._escape(self.next())
                    if len(hi_set) != 1:
                        raise ValueError("class range endpoint cannot be \\d/\\w/\\s")
                    hi = next(iter(hi_set))
                else:
                    hi = ord(hi_ch)
                if hi < lo:
                    raise ValueError(f"inverted class range {chr(lo)}-{chr(hi)}")
                chars.update(range(lo, hi + 1))
            else:
                chars.add(lo)
        return Lit(frozenset(_ALL - chars) if negate else frozenset(chars))


# ---------------------------------------------------------------- NFA


@dataclass
class _NFAState:
    eps: list = field(default_factory=list)
    edges: list = field(default_factory=list)  # (class_id placeholder charset, dst)


class _NFA:
    def __init__(self) -> None:
        self.states: list[_NFAState] = []

    def new(self) -> int:
        self.states.append(_NFAState())
        return len(self.states) - 1

    def compile(self, node: Node) -> tuple[int, int]:
        """Thompson construction: returns (start, accept)."""
        if isinstance(node, Lit):
            s, e = self.new(), self.new()
            self.states[s].edges.append((node.chars, e))
            return s, e
        if isinstance(node, Seq):
            if not node.parts:
                s = self.new()
                return s, s
            s, e = self.compile(node.parts[0])
            for part in node.parts[1:]:
                s2, e2 = self.compile(part)
                self.states[e].eps.append(s2)
                e = e2
            return s, e
        if isinstance(node, Alt):
            s, e = self.new(), self.new()
            for opt in node.options:
                os, oe = self.compile(opt)
                self.states[s].eps.append(os)
                self.states[oe].eps.append(e)
            return s, e
        if isinstance(node, Rep):
            lo, hi = node.lo, node.hi
            if hi is None:
                # child{lo,} = child^lo followed by child*
                s = e = self.new()
                for _ in range(lo):
                    cs, ce = self.compile(node.child)
                    self.states[e].eps.append(cs)
                    e = ce
                ks, ke = self.compile(node.child)
                loop_in = self.new()
                self.states[e].eps.append(loop_in)
                self.states[loop_in].eps.append(ks)
                self.states[ke].eps.append(loop_in)
                return s, loop_in
            # bounded: child^lo then up to (hi-lo) optional copies. Each
            # optional copy eps-exits DIRECTLY to one shared exit state —
            # a skip-CHAIN here makes every epsilon closure drag in all
            # downstream skips, turning subset construction quadratic in
            # the repetition count (fatal for {0,160} string bounds).
            s = e = self.new()
            for _ in range(lo):
                cs, ce = self.compile(node.child)
                self.states[e].eps.append(cs)
                e = ce
            exit_ = self.new()
            self.states[e].eps.append(exit_)
            cur = e
            for _ in range(hi - lo):
                cs, ce = self.compile(node.child)
                self.states[cur].eps.append(cs)
                self.states[ce].eps.append(exit_)
                cur = ce
            return s, exit_
        raise TypeError(node)


# ---------------------------------------------------------------- DFA


class DFA:
    """Dense DFA over byte-equivalence classes.

    Attributes:
      trans:      (num_states, num_classes) int32, DEAD=-1
      accepting:  (num_states,) bool
      class_of:   (256,) int32 byte -> class id
      start:      int
    """

    def __init__(self, trans: np.ndarray, accepting: np.ndarray, class_of: np.ndarray, start: int):
        self.trans = trans
        self.accepting = accepting
        self.class_of = class_of
        self.start = start

    @property
    def num_states(self) -> int:
        return self.trans.shape[0]

    def step_byte(self, state: int, byte: int) -> int:
        if state == DEAD:
            return DEAD
        return int(self.trans[state, self.class_of[byte]])

    def matches(self, data: bytes) -> bool:
        s = self.start
        for b in data:
            s = self.step_byte(s, b)
            if s == DEAD:
                return False
        return bool(self.accepting[s])

    def accepts_prefix(self, data: bytes) -> bool:
        """True if data is a viable prefix of some accepted string."""
        s = self.start
        for b in data:
            s = self.step_byte(s, b)
            if s == DEAD:
                return False
        return True


def _byte_classes(node: Node) -> np.ndarray:
    """Partition 0..255 into equivalence classes over all charsets in the AST."""
    sets: list[frozenset] = []

    def walk(n: Node) -> None:
        if isinstance(n, Lit):
            sets.append(n.chars)
        elif isinstance(n, Seq):
            for p in n.parts:
                walk(p)
        elif isinstance(n, Alt):
            for p in n.options:
                walk(p)
        elif isinstance(n, Rep):
            walk(n.child)

    walk(node)
    # signature of each byte = which charsets contain it
    masks = []
    for s in sets:
        arr = np.zeros(256, dtype=bool)
        arr[list(s)] = True
        masks.append(arr)
    if masks:
        mat = np.stack(masks, axis=1)  # (256, n_sets)
    else:
        mat = np.zeros((256, 0), dtype=bool)
    class_of = np.zeros(256, dtype=np.int32)
    seen: dict[bytes, int] = {}
    for b in range(256):
        key = mat[b].tobytes()
        if key not in seen:
            seen[key] = len(seen)
        class_of[b] = seen[key]
    return class_of


def compile_regex(pattern: str) -> DFA:
    ast = _Parser(pattern).parse()
    class_of = _byte_classes(ast)
    num_classes = int(class_of.max()) + 1
    # representative byte per class
    rep: list[int] = [0] * num_classes
    for b in range(255, -1, -1):
        rep[class_of[b]] = b

    nfa = _NFA()
    start, accept = nfa.compile(ast)

    # epsilon-closure per NFA state (cached, iterative DFS)
    n = len(nfa.states)
    closure_cache: dict[int, frozenset] = {}

    def closure(of: frozenset) -> frozenset:
        out: set[int] = set()
        stack = list(of)
        while stack:
            s = stack.pop()
            if s in out:
                continue
            out.add(s)
            cached = closure_cache.get(s)
            if cached is not None:
                out |= cached
                continue
            stack.extend(nfa.states[s].eps)
        return frozenset(out)

    for s in range(n):
        closure_cache[s] = closure(frozenset({s})) - {s}

    # precompute per-NFA-state: class_id -> set of dsts
    per_state_moves: list[dict[int, list[int]]] = []
    for st in nfa.states:
        moves: dict[int, list[int]] = {}
        for chars, dst in st.edges:
            cls_ids = {int(class_of[b]) for b in chars}
            for c in cls_ids:
                moves.setdefault(c, []).append(dst)
        per_state_moves.append(moves)

    start_set = closure(frozenset({start}))
    dfa_states: dict[frozenset, int] = {start_set: 0}
    worklist = [start_set]
    trans_rows: list[list[int]] = []
    accepting: list[bool] = []

    while worklist:
        cur = worklist.pop()
        idx = dfa_states[cur]
        while len(trans_rows) <= idx:
            trans_rows.append([DEAD] * num_classes)
            accepting.append(False)
        accepting[idx] = accept in cur
        by_class: dict[int, set[int]] = {}
        for s in cur:
            for c, dsts in per_state_moves[s].items():
                by_class.setdefault(c, set()).update(dsts)
        for c, dsts in by_class.items():
            nxt = closure(frozenset(dsts))
            if nxt not in dfa_states:
                dfa_states[nxt] = len(dfa_states)
                worklist.append(nxt)
            trans_rows[idx][c] = dfa_states[nxt]

    # fill rows created after the loop for late-discovered states
    while len(trans_rows) < len(dfa_states):
        trans_rows.append([DEAD] * num_classes)
        accepting.append(False)
    for sset, idx in dfa_states.items():
        accepting[idx] = accept in sset

    trans = np.asarray(trans_rows, dtype=np.int32)
    return DFA(trans, np.asarray(accepting, dtype=bool), class_of, 0)
