"""Hugging Face Llama safetensors -> stacked-layer param tree.

HF checkpoints store one tensor per layer per projection with (out, in)
weight layout; models/llama.py wants layers stacked on a leading axis with
(in, out) matmul layout (einsum "btd,dh->bth"). The converter transposes
and stacks. RoPE conventions agree (both use the split-half rotation), so
no permutation of head dims is needed.

Works from either a loaded state dict (numpy arrays) or a directory of
``*.safetensors`` shards.
"""

from __future__ import annotations

import glob
import os

import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig


def llama_hf_key_map(layer: int) -> dict[str, str]:
    """Our per-layer leaf name -> HF tensor name, for layer ``layer``."""
    p = f"model.layers.{layer}."
    return {
        "attn_norm": p + "input_layernorm.weight",
        "wq": p + "self_attn.q_proj.weight",
        "wk": p + "self_attn.k_proj.weight",
        "wv": p + "self_attn.v_proj.weight",
        "wo": p + "self_attn.o_proj.weight",
        "mlp_norm": p + "post_attention_layernorm.weight",
        "w_gate": p + "mlp.gate_proj.weight",
        "w_up": p + "mlp.up_proj.weight",
        "w_down": p + "mlp.down_proj.weight",
    }


_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def _load_state_dir(path: str) -> dict[str, np.ndarray]:
    from safetensors import safe_open

    state: dict[str, np.ndarray] = {}
    files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for f in files:
        with safe_open(f, framework="np") as sf:
            for k in sf.keys():
                state[k] = sf.get_tensor(k)
    return state


def llama_from_hf_state(
    state: dict[str, np.ndarray] | str,
    cfg: LlamaConfig,
    dtype=jnp.bfloat16,
) -> dict:
    """Convert an HF Llama state dict (or a safetensors directory path) into
    the models/llama.py param tree. Validates every shape against ``cfg``."""
    if isinstance(state, str):
        state = _load_state_dir(state)

    def get(name: str, want: tuple[int, ...], transpose: bool) -> jnp.ndarray:
        if name not in state:
            raise KeyError(f"HF checkpoint missing tensor {name}")
        a = np.asarray(state[name])
        if transpose and a.ndim == 2:
            a = a.T
        if tuple(a.shape) != want:
            raise ValueError(f"{name}: shape {a.shape}, config wants {want}")
        return jnp.asarray(a, dtype=dtype)

    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    nq, nkv = cfg.n_heads, cfg.n_kv_heads
    want = {
        "attn_norm": (d,),
        "wq": (d, nq * hd),
        "wk": (d, nkv * hd),
        "wv": (d, nkv * hd),
        "wo": (nq * hd, d),
        "mlp_norm": (d,),
        "w_gate": (d, f),
        "w_up": (d, f),
        "w_down": (f, d),
    }
    stacked: dict[str, list] = {k: [] for k in want}
    for layer in range(cfg.n_layers):
        for ours, hf_name in llama_hf_key_map(layer).items():
            stacked[ours].append(get(hf_name, want[ours], ours in _TRANSPOSED))

    embed = get("model.embed_tokens.weight", (cfg.vocab_size, d), transpose=False)
    head_name = "lm_head.weight"
    if head_name in state:
        lm_head = get(head_name, (d, cfg.vocab_size), transpose=True)
    else:  # tied embeddings (TinyLlama, Llama-3.2-1B style)
        lm_head = embed.T
    return {
        "embed": embed,
        "layers": {k: jnp.stack(v) for k, v in stacked.items()},
        "final_norm": get("model.norm.weight", (d,), transpose=False),
        "lm_head": lm_head,
    }
