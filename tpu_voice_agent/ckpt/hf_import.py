"""Hugging Face Llama safetensors -> stacked-layer param tree.

HF checkpoints store one tensor per layer per projection with (out, in)
weight layout; models/llama.py wants layers stacked on a leading axis with
(in, out) matmul layout (einsum "btd,dh->bth"). The converter transposes
and stacks. RoPE conventions agree (both use the split-half rotation), so
no permutation of head dims is needed.

Works from either a loaded state dict (numpy arrays) or a directory of
``*.safetensors`` shards.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import struct

import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig


# ---------------------------------------------------------------- config.json


def llama_config_from_hf(path: str) -> LlamaConfig:
    """Build a LlamaConfig from an HF config.json (file or directory).

    This plus load_hf_tokenizer plus llama_from_hf_state is the complete
    real-checkpoint path: nothing about the architecture is hard-coded to a
    preset (reference capability replaced: apps/brain/src/llm.ts:7-9's
    LLM_MODEL env selecting an arbitrary cloud model)."""
    if os.path.isdir(path):
        path = os.path.join(path, "config.json")
    with open(path) as f:
        cfg = json.load(f)
    E = cfg.get("num_local_experts", 0)
    K = cfg.get("num_experts_per_tok", 2)
    return LlamaConfig(
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        ffn_dim=cfg["intermediate_size"],
        max_seq_len=cfg.get("max_position_embeddings", 2048),
        rope_theta=float(cfg.get("rope_theta", 10_000.0)),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        # Mixtral-style MoE configs (MixtralForCausalLM) carry expert counts;
        # capacity_factor = E/K makes routing drop-free so chunked prefill
        # stays exactly consistent with per-token decode (see PRESETS note
        # in models/llama.py) — the HF config has no such field to read
        n_experts=E,
        top_k=K,
        capacity_factor=max(1.25, E / K) if E else 1.25,
    )


def qwen2vl_config_from_hf(path: str):
    """Qwen2VLConfig from an HF config.json (file or directory) — the
    real-checkpoint grounding path (BASELINE config 5): nothing about the
    architecture is preset-bound."""
    from ..models.qwen2vl import Qwen2VLConfig, VisionConfig

    if os.path.isdir(path):
        path = os.path.join(path, "config.json")
    with open(path) as f:
        cfg = json.load(f)
    v = cfg.get("vision_config", {})
    rope = cfg.get("rope_scaling") or {}
    sections = rope.get("mrope_section")
    head_dim = cfg["hidden_size"] // cfg["num_attention_heads"]
    if sections is None:
        # Qwen2-VL's published split (t, h, w) = (hd/8, 3hd/16, 3hd/16),
        # e.g. (16, 24, 24) at head_dim 128; sums to head_dim // 2
        sections = (head_dim // 8, 3 * head_dim // 16, 3 * head_dim // 16)
    if "img_size" not in v:
        # real HF Qwen2-VL configs carry no img_size — upstream is
        # dynamic-resolution. This port letterboxes to a fixed square
        # (models/qwen2vl.py preprocessing), a deliberate static-shape
        # adaptation for XLA; surface it so operators evaluating a real
        # checkpoint know the vision path diverges from upstream.
        logging.getLogger("tpu_voice_agent.ckpt").warning(
            "HF vision_config has no img_size: adapting dynamic-resolution "
            "Qwen2-VL to the fixed 448x448 letterbox pipeline (grounding "
            "boxes are mapped back through the letterbox transform, but "
            "very wide/tall screenshots lose detail vs upstream's native "
            "resolution)")
    vision = VisionConfig(
        img_size=int(v.get("img_size", 448)),
        patch_size=v.get("patch_size", 14),
        merge_size=v.get("spatial_merge_size", 2),
        d_model=v.get("embed_dim", v.get("hidden_size", 1280)),
        n_heads=v.get("num_heads", 16),
        n_layers=v.get("depth", 32),
    )
    return Qwen2VLConfig(
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        ffn_dim=cfg["intermediate_size"],
        max_seq_len=min(cfg.get("max_position_embeddings", 2048), 32768),
        rope_theta=float(cfg.get("rope_theta", 1_000_000.0)),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-6)),
        mrope_sections=tuple(int(x) for x in sections),
        vision=vision,
    )


def whisper_config_from_hf(path: str):
    """WhisperConfig from an HF config.json (file or directory)."""
    from ..models.whisper import WhisperConfig

    if os.path.isdir(path):
        path = os.path.join(path, "config.json")
    with open(path) as f:
        cfg = json.load(f)
    return WhisperConfig(
        vocab_size=cfg["vocab_size"],
        n_mels=cfg.get("num_mel_bins", 80),
        d_model=cfg["d_model"],
        n_heads=cfg["encoder_attention_heads"],
        enc_layers=cfg["encoder_layers"],
        dec_layers=cfg["decoder_layers"],
        max_audio_frames=2 * cfg.get("max_source_positions", 1500),
        max_text_len=cfg.get("max_target_positions", 448),
    )


def safetensors_shapes(path: str) -> dict[str, tuple[int, ...]]:
    """Tensor name -> shape from safetensors headers only (no data read).

    The header is a little-endian u64 length + JSON dict; parsing it keeps
    shape validation of multi-GB checkpoints at zero memory cost."""
    shapes: dict[str, tuple[int, ...]] = {}
    files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for f in files:
        with open(f, "rb") as fh:
            (n,) = struct.unpack("<Q", fh.read(8))
            header = json.loads(fh.read(n))
        for name, meta in header.items():
            if name != "__metadata__":
                shapes[name] = tuple(meta["shape"])
    return shapes


def llama_hf_check(shapes: dict[str, tuple[int, ...]], cfg: LlamaConfig) -> None:
    """Validate an HF Llama checkpoint's tensor names+shapes against ``cfg``
    without loading any data (pairs with safetensors_shapes). Raises with
    the full list of mismatches."""
    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    nq, nkv = cfg.n_heads, cfg.n_kv_heads
    # HF (out, in) layout — the un-transposed twin of llama_from_hf_state's
    want: dict[str, tuple[int, ...]] = {
        "model.embed_tokens.weight": (cfg.vocab_size, d),
        "model.norm.weight": (d,),
    }
    per_layer = {
        "input_layernorm.weight": (d,),
        "self_attn.q_proj.weight": (nq * hd, d),
        "self_attn.k_proj.weight": (nkv * hd, d),
        "self_attn.v_proj.weight": (nkv * hd, d),
        "self_attn.o_proj.weight": (d, nq * hd),
        "post_attention_layernorm.weight": (d,),
    }
    if cfg.n_experts > 0:
        per_layer["block_sparse_moe.gate.weight"] = (cfg.n_experts, d)
        for e in range(cfg.n_experts):
            per_layer[f"block_sparse_moe.experts.{e}.w1.weight"] = (f, d)
            per_layer[f"block_sparse_moe.experts.{e}.w3.weight"] = (f, d)
            per_layer[f"block_sparse_moe.experts.{e}.w2.weight"] = (d, f)
    else:
        per_layer.update({
            "mlp.gate_proj.weight": (f, d),
            "mlp.up_proj.weight": (f, d),
            "mlp.down_proj.weight": (d, f),
        })
    for layer in range(cfg.n_layers):
        for suffix, shape in per_layer.items():
            want[f"model.layers.{layer}.{suffix}"] = shape
    problems = []
    for name, shape in want.items():
        if name not in shapes:
            problems.append(f"missing {name}")
        elif tuple(shapes[name]) != shape:
            problems.append(f"{name}: shape {shapes[name]}, config wants {shape}")
    if "lm_head.weight" in shapes and tuple(shapes["lm_head.weight"]) != (cfg.vocab_size, d):
        problems.append(
            f"lm_head.weight: shape {shapes['lm_head.weight']}, "
            f"config wants {(cfg.vocab_size, d)}"
        )
    if problems:
        raise ValueError("HF checkpoint mismatch:\n" + "\n".join(problems[:20]))


def llama_hf_key_map(layer: int, moe: bool = False) -> dict[str, str]:
    """Our per-layer leaf name -> HF tensor name, for layer ``layer``.
    ``moe=True`` (Mixtral naming): the dense MLP keys are absent — the
    router and per-expert tensors are handled by llama_from_hf_state's
    expert stacking (they map E tensors onto one stacked leaf)."""
    p = f"model.layers.{layer}."
    base = {
        "attn_norm": p + "input_layernorm.weight",
        "wq": p + "self_attn.q_proj.weight",
        "wk": p + "self_attn.k_proj.weight",
        "wv": p + "self_attn.v_proj.weight",
        "wo": p + "self_attn.o_proj.weight",
        "mlp_norm": p + "post_attention_layernorm.weight",
    }
    if not moe:
        base.update({
            "w_gate": p + "mlp.gate_proj.weight",
            "w_up": p + "mlp.up_proj.weight",
            "w_down": p + "mlp.down_proj.weight",
        })
    return base


_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def _load_state_dir(path: str) -> dict[str, np.ndarray]:
    from safetensors import safe_open

    state: dict[str, np.ndarray] = {}
    files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for f in files:
        with safe_open(f, framework="np") as sf:
            for k in sf.keys():
                state[k] = sf.get_tensor(k)
    return state


def llama_from_hf_state(
    state: dict[str, np.ndarray] | str,
    cfg: LlamaConfig,
    dtype=jnp.bfloat16,
) -> dict:
    """Convert an HF Llama state dict (or a safetensors directory path) into
    the models/llama.py param tree. Validates every shape against ``cfg``."""
    if isinstance(state, str):
        state = _load_state_dir(state)

    def get(name: str, want: tuple[int, ...], transpose: bool) -> jnp.ndarray:
        if name not in state:
            raise KeyError(f"HF checkpoint missing tensor {name}")
        a = np.asarray(state[name])
        if transpose and a.ndim == 2:
            a = a.T
        if tuple(a.shape) != want:
            raise ValueError(f"{name}: shape {a.shape}, config wants {want}")
        return jnp.asarray(a, dtype=dtype)

    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    nq, nkv = cfg.n_heads, cfg.n_kv_heads
    moe = cfg.n_experts > 0
    want = {
        "attn_norm": (d,),
        "wq": (d, nq * hd),
        "wk": (d, nkv * hd),
        "wv": (d, nkv * hd),
        "wo": (nq * hd, d),
        "mlp_norm": (d,),
    }
    if not moe:
        want.update({"w_gate": (d, f), "w_up": (d, f), "w_down": (f, d)})
    stacked: dict[str, list] = {k: [] for k in want}
    if moe:
        stacked.update({"router": [], "moe_gate": [], "moe_up": [], "moe_down": []})
    for layer in range(cfg.n_layers):
        for ours, hf_name in llama_hf_key_map(layer, moe=moe).items():
            stacked[ours].append(get(hf_name, want[ours], ours in _TRANSPOSED))
        if moe:
            # Mixtral block_sparse_moe: gate (E, d) -> router (d, E);
            # experts.{e}.w1/w3 (f, d) -> moe_gate/up (E, d, f);
            # experts.{e}.w2 (d, f) -> moe_down (E, f, d)
            p = f"model.layers.{layer}.block_sparse_moe."
            stacked["router"].append(
                get(p + "gate.weight", (d, cfg.n_experts), transpose=True))
            for ours, hf_w, shape in (("moe_gate", "w1", (d, f)),
                                      ("moe_up", "w3", (d, f)),
                                      ("moe_down", "w2", (f, d))):
                stacked[ours].append(jnp.stack([
                    get(f"{p}experts.{e}.{hf_w}.weight", shape, transpose=True)
                    for e in range(cfg.n_experts)
                ]))

    embed = get("model.embed_tokens.weight", (cfg.vocab_size, d), transpose=False)
    head_name = "lm_head.weight"
    if head_name in state:
        lm_head = get(head_name, (d, cfg.vocab_size), transpose=True)
    else:  # tied embeddings (TinyLlama, Llama-3.2-1B style)
        lm_head = embed.T
    return {
        "embed": embed,
        "layers": {k: jnp.stack(v) for k, v in stacked.items()},
        "final_norm": get("model.norm.weight", (d,), transpose=False),
        "lm_head": lm_head,
    }


def _stack_layers(items: list[dict], dtype=None) -> dict:
    """Stack per-layer leaf dicts (possibly nested) on a leading layer axis."""
    out: dict = {}
    for k in items[0]:
        if isinstance(items[0][k], dict):
            out[k] = _stack_layers([it[k] for it in items], dtype)
        else:
            arrs = [jnp.asarray(it[k], dtype=dtype) if dtype is not None else it[k]
                    for it in items]
            out[k] = jnp.stack(arrs)
    return out


# ---------------------------------------------------------------- whisper


def whisper_from_hf_state(
    state: dict[str, np.ndarray] | str,
    cfg,  # models.whisper.WhisperConfig
    dtype=jnp.bfloat16,
) -> dict:
    """Convert an HF Whisper state dict (WhisperForConditionalGeneration
    naming, ``model.encoder/decoder.*``) into the models/whisper.py tree.

    Layout notes: HF linear weights are (out, in) -> transposed to our
    (in, out) einsum layout; conv1d kernels are (out, in, k) -> our (k, in,
    out); k_proj carries no bias in Whisper (our blocks model exactly bq/bv/
    bo). Encoder positions are sinusoidal (computed, not imported); decoder
    positions are learned and imported.
    """
    if isinstance(state, str):
        state = _load_state_dir(state)

    def get(name: str, want: tuple[int, ...], t: str = "") -> jnp.ndarray:
        if name not in state:
            raise KeyError(f"HF checkpoint missing tensor {name}")
        a = np.asarray(state[name])
        if t == "lin" and a.ndim == 2:
            a = a.T
        elif t == "conv":  # (out, in, k) -> (k, in, out)
            a = a.transpose(2, 1, 0)
        if tuple(a.shape) != want:
            raise ValueError(f"{name}: shape {a.shape}, config wants {want}")
        return jnp.asarray(a, dtype=dtype)

    d, f = cfg.d_model, cfg.ffn_dim

    def attn(prefix: str) -> dict:
        p = prefix + "."
        return {
            "wq": get(p + "q_proj.weight", (d, d), "lin"),
            "bq": get(p + "q_proj.bias", (d,)),
            "wk": get(p + "k_proj.weight", (d, d), "lin"),
            "wv": get(p + "v_proj.weight", (d, d), "lin"),
            "bv": get(p + "v_proj.bias", (d,)),
            "wo": get(p + "out_proj.weight", (d, d), "lin"),
            "bo": get(p + "out_proj.bias", (d,)),
        }

    def ln(name: str) -> dict:
        return {"g": get(name + ".weight", (d,)), "b": get(name + ".bias", (d,))}

    enc_layers = []
    for n in range(cfg.enc_layers):
        p = f"model.encoder.layers.{n}"
        enc_layers.append({
            "ln1": ln(p + ".self_attn_layer_norm"),
            "attn": attn(p + ".self_attn"),
            "ln2": ln(p + ".final_layer_norm"),
            "w1": get(p + ".fc1.weight", (d, f), "lin"),
            "b1": get(p + ".fc1.bias", (f,)),
            "w2": get(p + ".fc2.weight", (f, d), "lin"),
            "b2": get(p + ".fc2.bias", (d,)),
        })

    dec_layers = []
    for n in range(cfg.dec_layers):
        p = f"model.decoder.layers.{n}"
        dec_layers.append({
            "ln1": ln(p + ".self_attn_layer_norm"),
            "self_attn": attn(p + ".self_attn"),
            "ln2": ln(p + ".encoder_attn_layer_norm"),
            "cross_attn": attn(p + ".encoder_attn"),
            "ln3": ln(p + ".final_layer_norm"),
            "w1": get(p + ".fc1.weight", (d, f), "lin"),
            "b1": get(p + ".fc1.bias", (f,)),
            "w2": get(p + ".fc2.weight", (f, d), "lin"),
            "b2": get(p + ".fc2.bias", (d,)),
        })

    return {
        "encoder": {
            "conv1": {"w": get("model.encoder.conv1.weight", (3, cfg.n_mels, d), "conv"),
                      "b": get("model.encoder.conv1.bias", (d,))},
            "conv2": {"w": get("model.encoder.conv2.weight", (3, d, d), "conv"),
                      "b": get("model.encoder.conv2.bias", (d,))},
            "layers": _stack_layers(enc_layers),
            "ln_post": {"g": get("model.encoder.layer_norm.weight", (d,)),
                        "b": get("model.encoder.layer_norm.bias", (d,))},
        },
        "decoder": {
            "tok_emb": get("model.decoder.embed_tokens.weight", (cfg.vocab_size, d)),
            "pos_emb": get("model.decoder.embed_positions.weight", (cfg.max_text_len, d)),
            "layers": _stack_layers(dec_layers),
            "ln_final": {"g": get("model.decoder.layer_norm.weight", (d,)),
                         "b": get("model.decoder.layer_norm.bias", (d,))},
        },
    }


# ---------------------------------------------------------------- qwen2-vl


def qwen2vl_from_hf_state(
    state: dict[str, np.ndarray] | str,
    cfg,  # models.qwen2vl.Qwen2VLConfig
    dtype=jnp.bfloat16,
) -> dict:
    """Convert an HF Qwen2-VL state dict (Qwen2VLForConditionalGeneration
    naming: ``visual.*`` + ``model.*``) into the models/qwen2vl.py tree.

    Vision notes: the HF patch embed is a conv3d over 2 temporal frames —
    for still images both frames carry the same patch, so the two temporal
    taps sum into one (p*p*3, d) matmul kernel, permuted channel-last to
    match patchify(); the fused qkv projection splits three ways.
    """
    if isinstance(state, str):
        state = _load_state_dir(state)

    def get(name: str, want: tuple[int, ...] | None = None, lin: bool = False):
        if name not in state:
            raise KeyError(f"HF checkpoint missing tensor {name}")
        a = np.asarray(state[name])
        if lin and a.ndim == 2:
            a = a.T
        if want is not None and tuple(a.shape) != want:
            raise ValueError(f"{name}: shape {a.shape}, config wants {want}")
        return a

    v = cfg.vision
    dv, fv, Lv = v.d_model, v.ffn_dim, v.n_layers
    p_sz = v.patch_size

    # patch embed: (dv, 3, T, p, p) [or (dv, 3, p, p)] -> (p*p*3, dv)
    pe = get("visual.patch_embed.proj.weight")
    if pe.ndim == 5:
        pe = pe.sum(axis=2)
    if pe.shape != (dv, 3, p_sz, p_sz):
        raise ValueError(f"patch_embed: shape {pe.shape}")
    patch_embed = pe.transpose(2, 3, 1, 0).reshape(p_sz * p_sz * 3, dv)

    vis_layers = []
    for n in range(Lv):
        p = f"visual.blocks.{n}."
        qkv_w = get(p + "attn.qkv.weight", (3 * dv, dv))  # (3d, d)
        qkv_b = get(p + "attn.qkv.bias", (3 * dv,))
        wq, wk, wv_ = (qkv_w[i * dv:(i + 1) * dv].T for i in range(3))
        bq, bk, bv = (qkv_b[i * dv:(i + 1) * dv] for i in range(3))
        vis_layers.append({
            "ln1": {"g": get(p + "norm1.weight", (dv,)), "b": get(p + "norm1.bias", (dv,))},
            "wq": wq, "bq": bq, "wk": wk, "bk": bk, "wv": wv_, "bv": bv,
            "wo": get(p + "attn.proj.weight", (dv, dv)).T,
            "bo": get(p + "attn.proj.bias", (dv,)),
            "ln2": {"g": get(p + "norm2.weight", (dv,)), "b": get(p + "norm2.bias", (dv,))},
            "w_up": get(p + "mlp.fc1.weight", (fv, dv)).T,
            "b_up": get(p + "mlp.fc1.bias", (fv,)),
            "w_down": get(p + "mlp.fc2.weight", (dv, fv)).T,
            "b_down": get(p + "mlp.fc2.bias", (dv,)),
        })

    merged_in = v.merge_size * v.merge_size * dv
    vision = {
        "patch_embed": jnp.asarray(patch_embed, dtype=dtype),
        "layers": _stack_layers(vis_layers, dtype),
        "merger": {
            "ln": {"g": jnp.asarray(get("visual.merger.ln_q.weight", (dv,)), dtype=dtype),
                   "b": jnp.asarray(get("visual.merger.ln_q.bias", (dv,)), dtype=dtype)},
            "w1": jnp.asarray(get("visual.merger.mlp.0.weight", (merged_in, merged_in)).T, dtype=dtype),
            "b1": jnp.asarray(get("visual.merger.mlp.0.bias", (merged_in,)), dtype=dtype),
            "w2": jnp.asarray(get("visual.merger.mlp.2.weight", (cfg.dim, merged_in)).T, dtype=dtype),
            "b2": jnp.asarray(get("visual.merger.mlp.2.bias", (cfg.dim,)), dtype=dtype),
        },
    }

    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    nq, nkv = cfg.n_heads, cfg.n_kv_heads
    txt: dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "bq", "wk", "bk", "wv", "bv", "wo",
        "mlp_norm", "w_gate", "w_up", "w_down")}
    for n in range(cfg.n_layers):
        p = f"model.layers.{n}."
        txt["attn_norm"].append(get(p + "input_layernorm.weight", (d,)))
        txt["wq"].append(get(p + "self_attn.q_proj.weight", (nq * hd, d)).T)
        txt["bq"].append(get(p + "self_attn.q_proj.bias", (nq * hd,)))
        txt["wk"].append(get(p + "self_attn.k_proj.weight", (nkv * hd, d)).T)
        txt["bk"].append(get(p + "self_attn.k_proj.bias", (nkv * hd,)))
        txt["wv"].append(get(p + "self_attn.v_proj.weight", (nkv * hd, d)).T)
        txt["bv"].append(get(p + "self_attn.v_proj.bias", (nkv * hd,)))
        txt["wo"].append(get(p + "self_attn.o_proj.weight", (d, nq * hd)).T)
        txt["mlp_norm"].append(get(p + "post_attention_layernorm.weight", (d,)))
        txt["w_gate"].append(get(p + "mlp.gate_proj.weight", (f, d)).T)
        txt["w_up"].append(get(p + "mlp.up_proj.weight", (f, d)).T)
        txt["w_down"].append(get(p + "mlp.down_proj.weight", (d, f)).T)

    embed = jnp.asarray(get("model.embed_tokens.weight", (cfg.vocab_size, d)), dtype=dtype)
    if "lm_head.weight" in state:
        lm_head = jnp.asarray(get("lm_head.weight", (cfg.vocab_size, d)).T, dtype=dtype)
    else:  # tied (Qwen2-VL-2B)
        lm_head = embed.T
    return {
        "vision": vision,
        "embed": embed,
        "layers": {k: jnp.stack([jnp.asarray(a, dtype=dtype) for a in vlist])
                   for k, vlist in txt.items()},
        "final_norm": jnp.asarray(get("model.norm.weight", (d,)), dtype=dtype),
        "lm_head": lm_head,
    }
