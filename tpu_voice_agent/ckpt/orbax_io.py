"""Orbax param checkpointing, sharding-aware.

``save_params`` writes any param pytree; ``restore_params`` restores it,
optionally placing leaves directly onto mesh shardings (so a 70B restore
never materializes unsharded copies on one host).
"""

from __future__ import annotations

import os

import jax
import orbax.checkpoint as ocp


def save_params(path: str | os.PathLike, params) -> None:
    """Write ``params`` to ``path`` (a directory; created if needed). Only
    the ``params`` subtree is replaced — never the whole target directory."""
    import shutil

    root = os.path.abspath(path)
    os.makedirs(root, exist_ok=True)
    target = os.path.join(root, "params")
    if os.path.exists(target):
        shutil.rmtree(target)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(target, params)
        ckptr.wait_until_finished()


def restore_params(path: str | os.PathLike, shardings=None, params_like=None):
    """Restore the pytree written by ``save_params``.

    ``shardings``: optional pytree of ``NamedSharding`` matching the params
    structure — leaves stream from disk straight onto their mesh placement.
    ``params_like``: optional abstract pytree (e.g. from ``jax.eval_shape``)
    declaring dtypes/shapes; required if shardings is given without concrete
    reference arrays.
    """
    path = os.path.join(os.path.abspath(path), "params")
    with ocp.StandardCheckpointer() as ckptr:
        if shardings is None:
            return ckptr.restore(path)
        if params_like is None:
            raise ValueError("restore with shardings requires params_like (abstract pytree)")
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            params_like, shardings,
        )
        return ckptr.restore(path, abstract)
