"""Checkpoint I/O: Orbax save/restore + HF safetensors import.

The reference has no model weights at all (SURVEY.md §5 "Checkpoint /
resume": its persistent state is browser sessions and a context dict). In
this framework "checkpoint" regains its normal meaning: Orbax for
save/restore of param pytrees (sharding-aware restore onto a mesh), and a
converter from Hugging Face Llama safetensors into the stacked-layer layout
models/llama.py uses.
"""

from .orbax_io import restore_params, save_params
from .hf_import import (
    llama_config_from_hf,
    llama_from_hf_state,
    llama_hf_check,
    safetensors_shapes,
    whisper_config_from_hf,
    llama_hf_key_map,
    qwen2vl_from_hf_state,
    whisper_from_hf_state,
)

__all__ = [
    "save_params",
    "restore_params",
    "llama_config_from_hf",
    "llama_from_hf_state",
    "llama_hf_check",
    "safetensors_shapes",
    "whisper_config_from_hf",
    "llama_hf_key_map",
    "whisper_from_hf_state",
    "qwen2vl_from_hf_state",
]
