"""Served MoE decoder (Mixtral-style LlamaConfig.n_experts > 0).

Round-1 VERDICT flagged EP as "standalone MoE FFN; no served MoE model
uses it" — these tests pin the serving path: the MoE layer matches the
standalone EP reference math, prefill/decode stay consistent, the engine
serves grammar-valid output from an MoE preset, and the EP-over-tp mesh
layout matches the single-device forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.llama import (
    LlamaConfig, PRESETS, _moe_ffn, forward, init_kv_cache, init_params,
    param_count, quantize_params,
)
from tpu_voice_agent.parallel.mesh import (
    default_rules, kv_cache_shardings, make_mesh, param_shardings,
)

# capacity_factor = E / K makes routing drop-free (C == n_tokens), so the
# chunked-prefill and per-token-decode paths are exactly consistent
CFG = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                  ffn_dim=96, max_seq_len=128, n_experts=4, top_k=2,
                  capacity_factor=2.0)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_moe_layer_matches_standalone_ep_reference(params):
    """One MoE FFN block == parallel.expert.moe_ffn on the same weights."""
    from tpu_voice_agent.parallel.expert import MoEConfig, moe_ffn

    p = jax.tree.map(lambda a: a[0], params["layers"])  # layer 0 slice
    B, T = 2, 8
    h = jnp.asarray(np.random.default_rng(0).standard_normal((B, T, CFG.dim)),
                    jnp.float32)
    ours = _moe_ffn(p, h, CFG)

    mcfg = MoEConfig(dim=CFG.dim, ffn_dim=CFG.ffn_dim, n_experts=CFG.n_experts,
                     top_k=CFG.top_k, capacity_factor=CFG.capacity_factor)
    mp = {"router": p["router"], "w_gate": p["moe_gate"], "w_up": p["moe_up"],
          "w_down": p["moe_down"]}
    ref = moe_ffn(mp, mcfg, h.reshape(B * T, CFG.dim)).reshape(B, T, CFG.dim)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_moe_prefill_decode_consistency(params):
    """Greedy logits from [prefill T] == [prefill T-1 then one decode step]
    — drop-free capacity makes routing independent of batching."""
    T = 12
    toks = np.random.default_rng(1).integers(0, CFG.vocab_size, (1, T)).astype(np.int32)
    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    full, _ = forward(params, CFG, jnp.asarray(toks),
                      jnp.arange(T, dtype=jnp.int32)[None], cache)

    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    _, cache = forward(params, CFG, jnp.asarray(toks[:, :-1]),
                       jnp.arange(T - 1, dtype=jnp.int32)[None], cache)
    step, _ = forward(params, CFG, jnp.asarray(toks[:, -1:]),
                      jnp.full((1, 1), T - 1, jnp.int32), cache)
    np.testing.assert_allclose(
        np.asarray(full[:, -1]), np.asarray(step[:, 0]), rtol=2e-4, atol=2e-4)


def test_moe_param_count_matches_tree(params):
    n = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(params))
    assert n == param_count(CFG)


def test_moe_quantize_covers_experts(params):
    q = quantize_params(params)
    for k in ("moe_gate", "moe_up", "moe_down"):
        assert "q" in q["layers"][k] and q["layers"][k]["q"].dtype == jnp.int8
    assert not isinstance(q["layers"]["router"], dict)  # router stays raw


def test_moe_engine_generates_grammar_valid():
    from tpu_voice_agent.serve import DecodeEngine

    eng = DecodeEngine(preset="mixtral-test", max_len=512,
                       prefill_buckets=(64, 128, 256))
    res = eng.generate("search for usb hubs", max_new_tokens=48)
    assert res.steps > 0
    assert eng.fsm.walk(res.token_ids) >= 0


def test_moe_ep_mesh_forward_matches_unsharded(params):
    """EP serving layout: expert axis sharded over the mesh tp axis."""
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh(dp=1, tp=2)
    rules = default_rules(mesh, CFG.n_kv_heads, CFG.n_heads)
    sh = param_shardings(mesh, CFG.n_kv_heads, CFG.n_experts)
    assert "moe_gate" in sh["layers"], "MoE shardings must cover expert leaves"
    sharded_params = jax.device_put(params, sh)
    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    sharded_cache = jax.device_put(cache, kv_cache_shardings(mesh, CFG.n_kv_heads))

    T = 8
    tokens = (jnp.arange(T, dtype=jnp.int32)[None, :] * 5) % CFG.vocab_size
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    ref_logits, _ = forward(params, CFG, tokens, positions, cache)
    ep_logits, _ = forward(sharded_params, CFG, tokens, positions, sharded_cache, rules)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(ep_logits), rtol=2e-3, atol=2e-3)


def test_moe_hf_config_gets_dropfree_capacity(tmp_path):
    """Imported Mixtral configs must inherit the drop-free E/K capacity the
    in-tree presets encode (HF config.json has no such field)."""
    import json

    from tpu_voice_agent.ckpt.hf_import import llama_config_from_hf

    cfg_json = {
        "vocab_size": 256, "hidden_size": 64, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "intermediate_size": 96, "num_local_experts": 8,
        "num_experts_per_tok": 2,
    }
    p = tmp_path / "config.json"
    p.write_text(json.dumps(cfg_json))
    cfg = llama_config_from_hf(str(p))
    assert cfg.n_experts == 8 and cfg.top_k == 2
    assert cfg.capacity_factor == 4.0  # E / K — drop-free
    cfg_json.pop("num_local_experts")
    p.write_text(json.dumps(cfg_json))
    assert llama_config_from_hf(str(p)).n_experts == 0


def test_moe_hf_import_roundtrip(tmp_path):
    """A synthetic Mixtral-shaped checkpoint imports exactly."""
    from tpu_voice_agent.ckpt.hf_import import llama_from_hf_state

    rng = np.random.default_rng(3)
    d, f, E = CFG.dim, CFG.ffn_dim, CFG.n_experts
    state = {
        "model.embed_tokens.weight": rng.standard_normal((CFG.vocab_size, d)).astype(np.float32),
        "model.norm.weight": np.ones(d, np.float32),
        "lm_head.weight": rng.standard_normal((CFG.vocab_size, d)).astype(np.float32),
    }
    for i in range(CFG.n_layers):
        p = f"model.layers.{i}."
        hd, nq, nkv = CFG.head_dim, CFG.n_heads, CFG.n_kv_heads
        state[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        state[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
        state[p + "self_attn.q_proj.weight"] = rng.standard_normal((nq * hd, d)).astype(np.float32)
        state[p + "self_attn.k_proj.weight"] = rng.standard_normal((nkv * hd, d)).astype(np.float32)
        state[p + "self_attn.v_proj.weight"] = rng.standard_normal((nkv * hd, d)).astype(np.float32)
        state[p + "self_attn.o_proj.weight"] = rng.standard_normal((d, nq * hd)).astype(np.float32)
        state[p + "block_sparse_moe.gate.weight"] = rng.standard_normal((E, d)).astype(np.float32)
        for e in range(E):
            q = f"{p}block_sparse_moe.experts.{e}."
            state[q + "w1.weight"] = rng.standard_normal((f, d)).astype(np.float32)
            state[q + "w3.weight"] = rng.standard_normal((f, d)).astype(np.float32)
            state[q + "w2.weight"] = rng.standard_normal((d, f)).astype(np.float32)

    tree = llama_from_hf_state(state, CFG, dtype=jnp.float32)
    assert tree["layers"]["router"].shape == (CFG.n_layers, d, E)
    assert tree["layers"]["moe_gate"].shape == (CFG.n_layers, E, d, f)
    assert tree["layers"]["moe_down"].shape == (CFG.n_layers, E, f, d)
    # imported weights actually drive the forward
    cache = init_kv_cache(CFG, 1, 16, dtype=jnp.float32)
    logits, _ = forward(tree, CFG, jnp.zeros((1, 4), jnp.int32),
                        jnp.arange(4, dtype=jnp.int32)[None], cache)
    assert np.isfinite(np.asarray(logits)).all()
    # layer 0, expert 1 w1 row survives the transpose+stack exactly
    np.testing.assert_array_equal(
        np.asarray(tree["layers"]["moe_gate"][0, 1]),
        state["model.layers.0.block_sparse_moe.experts.1.w1.weight"].T)


# ---------------------------------------------------------------- grouped


class TestGroupedMoE:
    """Pallas grouped-matmul dispatch (round-2 VERDICT weak #5): FLOPs ∝ K
    not E, token-exact with the dense-dispatch path."""

    def test_grouped_matmul_matches_reference(self):
        from tpu_voice_agent.ops import grouped_matmul, grouped_matmul_reference

        rng = jax.random.PRNGKey(0)
        M, d, f, E, tm = 64, 32, 64, 4, 8
        x = jax.random.normal(rng, (M, d), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (E, d, f), jnp.float32)
        tile_expert = jnp.asarray([0, 0, 1, 3, 3, 2, 1, 0], jnp.int32)
        out = grouped_matmul(x, w, tile_expert, tm=tm)
        ref = grouped_matmul_reference(x, w, tile_expert, tm)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grouped_ffn_matches_dense_dispatch(self):
        """Same routing, same math, different dispatch: outputs agree."""
        from dataclasses import replace

        from tpu_voice_agent.models.llama import _moe_ffn, init_params

        cfg = replace(PRESETS["mixtral-test"], moe_impl="dense")
        params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
        p = jax.tree.map(lambda a: a[0], params["layers"])  # layer 0 slice
        h = jax.random.normal(jax.random.PRNGKey(4), (2, 24, cfg.dim), jnp.float32)
        dense = _moe_ffn(p, h, cfg)
        grouped = _moe_ffn(p, h, replace(cfg, moe_impl="grouped"))
        np.testing.assert_allclose(np.asarray(dense), np.asarray(grouped),
                                   rtol=2e-4, atol=2e-4)

    def test_grouped_ffn_flops_scale_with_k_not_e(self):
        """The point of the kernel: at prefill shapes the dense dispatch
        pays E/K× the FFN FLOPs the grouped path pays."""
        from dataclasses import replace

        from tpu_voice_agent.models.llama import _moe_ffn, init_params

        cfg = replace(
            PRESETS["mixtral-test"], n_experts=8, top_k=2, capacity_factor=4.0)
        params = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
        p = jax.tree.map(lambda a: a[0], params["layers"])
        h = jnp.zeros((1, 256, cfg.dim), jnp.float32)

        def flops(c):
            fn = jax.jit(lambda p, h: _moe_ffn(p, h, c))
            an = fn.lower(p, h).compile().cost_analysis()
            return float(an["flops"]) if an and "flops" in an else None

        dense_f = flops(cfg)
        grouped_f = flops(replace(cfg, moe_impl="grouped"))
        if dense_f is None or grouped_f is None:
            pytest.skip("backend reports no flops in cost analysis")
        # E/K = 4: expect ~4x; require at least 2x to absorb padding +
        # routing overheads
        assert grouped_f < dense_f / 2, (dense_f, grouped_f)

    def test_grouped_engine_decode_is_grammar_valid(self):
        """A served MoE engine on the grouped path still decodes valid
        intents (decode T=1 exercises the tiny-tile path)."""
        from dataclasses import replace

        from tpu_voice_agent.serve import DecodeEngine

        cfg = replace(PRESETS["mixtral-test"], moe_impl="grouped",
                      max_seq_len=512)
        eng = DecodeEngine(cfg=cfg, max_len=512, prefill_buckets=(64,))
        res = eng.generate("<|user|>\ngo back\n<|assistant|>\n", max_new_tokens=120)
        assert res.error is None
        assert eng.fsm.walk(res.token_ids) >= 0
