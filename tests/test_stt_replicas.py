"""Replicated STT tier (serve.stt_replicas, ISSUE 13) — FAST tier.

The contract: N STTBatcher replicas behind utterance-affine placement;
one crashed/wedged Whisper worker costs a warm restart and a failover,
never a lost final and never the other replicas' utterances. Finals are
token-identical to the single-engine reference wherever they end up
(the same engine weights serve every replica), and the watchdog's
stalled-tick warm restart reuses the loaded engine.
"""

import time
from concurrent.futures import Future

import numpy as np
import pytest

from tpu_voice_agent.serve.stt import SpeechEngine
from tpu_voice_agent.serve.stt_replicas import STTReplicaTier, current_tier
from tpu_voice_agent.services.replicaset import rendezvous_weight
from tpu_voice_agent.utils import chaos as chaos_mod
from tpu_voice_agent.utils import get_metrics


def tone(freq, dur_s, amp=0.3, sr=16_000):
    t = np.arange(int(dur_s * sr)) / sr
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


@pytest.fixture(scope="module")
def engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(50, 100, 200),
                        max_new_tokens=16)


@pytest.fixture(autouse=True)
def _chaos_reset():
    chaos_mod.reset()
    yield
    chaos_mod.reset()


def _counters():
    return get_metrics().snapshot()["counters"]


def _utt_homed_on(tier: STTReplicaTier, idx: int, base: int = 50_000) -> int:
    """An utterance id whose rendezvous home is replica ``idx``."""
    keys = [r.url for r in tier.replicas]
    for u in range(base, base + 10_000):
        if max(range(len(keys)),
               key=lambda j: rendezvous_weight(keys[j], str(u))) == idx:
            return u
    raise AssertionError("no utterance hashed onto the target replica")


def _tick_all(tier, rounds=8):
    for _ in range(rounds):
        for b in tier.batchers.values():
            if b.healthy():
                b.tick()


# ------------------------------------------------------------- placement


def test_tier_affinity_identity_and_release(engine):
    """Finals through the tier match the single-engine reference; an
    utterance's work stays on ONE replica (its slot lives there); release
    forgets the sticky entry."""
    tier = STTReplicaTier(engine, replicas=2, slots=4, autostart=False,
                          register=False)
    try:
        audios = {60_001: tone(300, 0.4), 60_002: tone(440, 0.9)}
        singles = {u: engine.transcribe(a).text for u, a in audios.items()}
        futs = {u: tier.submit("final", u, a) for u, a in audios.items()}
        _tick_all(tier)
        for u, f in futs.items():
            assert f.result(timeout=30).text == singles[u]
        # partial + final for one utterance land on the same replica
        u = 60_003
        tier.submit("partial", u, tone(330, 1.0))
        home = tier._sessions[str(u)]
        tier.submit("final", u, tone(330, 1.0))
        assert tier._sessions[str(u)] == home
        _tick_all(tier)
        tier.release(u)
        assert str(u) not in tier._sessions
        for b in tier.batchers.values():
            assert u not in b.slot_of  # the slot is freed everywhere
    finally:
        tier.stop()


# -------------------------------------------------------------- failover


def test_final_fails_over_off_a_killed_replica(engine):
    """The home replica dies with the final queued: the future fails over
    to the other replica and delivers the reference transcript — zero
    lost finals, counted."""
    tier = STTReplicaTier(engine, replicas=2, slots=4, autostart=False,
                          register=False)
    try:
        u = _utt_homed_on(tier, 0)
        audio = tone(410, 0.7)
        ref = engine.transcribe(audio).text
        fo0 = _counters().get("stt.replica_failovers", 0)
        rh0 = _counters().get("stt.replica_rehomed", 0)
        fut = tier.submit("final", u, audio)
        assert tier._sessions[str(u)] == tier.replicas[0].url
        # the crash: queued work fails abruptly, like a killed process
        tier.batchers[0].kill(RuntimeError("crashed"))
        _tick_all(tier)
        assert fut.result(timeout=30).text == ref
        # the failover itself re-homed the utterance (route with the dead
        # home excluded) — both counted, and residence is now sticky on
        # the survivor
        assert _counters().get("stt.replica_failovers", 0) == fo0 + 1
        assert _counters().get("stt.replica_rehomed", 0) == rh0 + 1
        assert tier._sessions[str(u)] == tier.replicas[1].url
        # the NEXT submit serves straight from the new home, no extra move
        fut2 = tier.submit("final", u, audio)
        _tick_all(tier)
        assert fut2.result(timeout=30).text == ref
        assert _counters().get("stt.replica_rehomed", 0) == rh0 + 1
    finally:
        tier.stop()


def test_all_replicas_down_fails_finals_sheds_partials(engine):
    tier = STTReplicaTier(engine, replicas=2, slots=4, autostart=False,
                          register=False)
    try:
        for b in tier.batchers.values():
            b.kill(RuntimeError("gone"))
        f = tier.submit("final", 61_000, tone(300, 0.4))
        with pytest.raises(RuntimeError):
            f.result(timeout=5)
        p = tier.submit("partial", 61_001, tone(300, 0.4))
        assert p.result(timeout=5) is None  # shed, not raised
    finally:
        tier.stop()


# -------------------------------------------------------------- watchdog


def test_watchdog_warm_restarts_killed_replica_and_ring_recovers(engine):
    """The stt_replica_kill chaos drill end to end on live workers: the
    first tick kills a replica; its final fails over and is delivered
    (zero lost); the watchdog warm-restarts the corpse (same engine,
    fresh batcher) and the ring returns to full health."""
    tier = STTReplicaTier(engine, replicas=2, slots=4, probe_s=0.05,
                          stall_s=3.0, register=False)
    try:
        audios = [tone(300, 0.4), tone(440, 0.9)]
        refs = [engine.transcribe(a).text for a in audios]
        # warm the batched decode path BEFORE arming chaos: the first tick
        # pays the jit compile, and a compile-length tick must not read as
        # a stalled worker in this drill
        tier.submit("final", 61_900, audios[0]).result(timeout=60)
        chaos_mod.configure("stt_replica_kill@1", seed=3)
        r0 = _counters().get("stt.replica_restarts", 0)
        futs = [tier.submit("final", 62_000 + i, a)
                for i, a in enumerate(audios)]
        assert [f.result(timeout=60).text for f in futs] == refs
        deadline = time.monotonic() + 10
        while _counters().get("stt.replica_restarts", 0) < r0 + 1:
            assert time.monotonic() < deadline, "watchdog never restarted"
            time.sleep(0.05)
        deadline = time.monotonic() + 10
        while not all(b.healthy() for b in tier.batchers.values()):
            assert time.monotonic() < deadline
            time.sleep(0.05)
        # the restarted replica serves again
        u = _utt_homed_on(tier, 0, base=63_000)
        deadline = time.monotonic() + 10
        while tier.replicas[0].state != "up":
            assert time.monotonic() < deadline, "ring never recovered"
            time.sleep(0.05)
        assert tier.submit("final", u, audios[0]).result(timeout=60).text \
            == refs[0]
        # the restarted corpse re-admits on its next healthy sweep (either
        # replica may have been the chaos victim — wait, don't race it)
        deadline = time.monotonic() + 10
        while tier.tier_health()["healthy"] < 2:
            assert time.monotonic() < deadline, "ring never refilled"
            time.sleep(0.05)
    finally:
        tier.stop()


def test_stalled_tick_watchdog_restarts_hung_replica(engine, monkeypatch):
    """The stt_replica_hang drill: one tick wedges for CHAOS_HANG_S; the
    stalled-tick watchdog ejects + warm-restarts the replica and the hung
    final fails over — delivered well before the hang would have ended
    badly, with zero lost finals."""
    monkeypatch.setenv("CHAOS_HANG_S", "8")
    tier = STTReplicaTier(engine, replicas=2, slots=4, probe_s=0.05,
                          stall_s=0.6, register=False)
    try:
        audio = tone(520, 0.6)
        ref = engine.transcribe(audio).text
        # compile warm-up first (chaos off), then arm the hang drill
        tier.submit("final", 63_900, audio).result(timeout=60)
        chaos_mod.configure("stt_replica_hang@1", seed=3)
        r0 = _counters().get("stt.replica_restarts", 0)
        fut = tier.submit("final", 64_000, audio)
        assert fut.result(timeout=30).text == ref
        assert _counters().get("stt.replica_restarts", 0) >= r0 + 1
    finally:
        tier.stop()


# -------------------------------------------------------------- pressure


def test_pressure_sheds_new_utterances_off_loaded_replica(engine):
    """A replica whose queue occupancy crosses STT_SHED_PRESSURE stops
    receiving NEW utterances (they redirect, counted) while utterances
    already homed there stay."""
    tier = STTReplicaTier(engine, replicas=2, slots=2, max_pending=4,
                          autostart=False, register=False)
    try:
        sticky = _utt_homed_on(tier, 0, base=65_000)
        tier.submit("final", sticky, tone(300, 0.4))
        # pile finals onto replica 0 until its queue is at the cap
        extra = []
        while len(tier.batchers[0].queue) < tier.batchers[0].max_pending:
            u = _utt_homed_on(tier, 0, base=66_000 + len(extra) * 7)
            if str(u) in tier._sessions:
                u += 1  # avoid reusing an already-placed utterance
            tier.submit("final", u, tone(330, 0.4))
            extra.append(u)
        tier.sweep_once()  # publishes queue occupancy as pressure
        assert tier.replicas[0].pressure >= tier.shed_pressure
        shed0 = _counters().get("stt.replica_shed_pressure", 0)
        fresh = _utt_homed_on(tier, 0, base=70_000)
        tier.submit("partial", fresh, tone(300, 1.0))
        assert tier._sessions[str(fresh)] == tier.replicas[1].url
        assert _counters().get("stt.replica_shed_pressure", 0) == shed0 + 1
        # the sticky utterance never moved
        assert tier._sessions[str(sticky)] == tier.replicas[0].url
        _tick_all(tier, rounds=12)
    finally:
        tier.stop()


# ----------------------------------------------------- voice /health HUD


def test_voice_health_surfaces_stt_replica_ring(engine):
    import json
    import urllib.request

    from tests.http_helper import AppServer
    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.voice import VoiceConfig
    from tpu_voice_agent.services.voice import build_app as build_voice

    tier = STTReplicaTier(engine, replicas=2, slots=2, autostart=False)
    voice = AppServer(build_voice(VoiceConfig(
        brain_url="http://127.0.0.1:1", executor_url="http://127.0.0.1:1",
        stt_factory=lambda: NullSTT()))).__enter__()
    try:
        assert current_tier() is tier
        with urllib.request.urlopen(voice.url + "/health", timeout=10) as r:
            h = json.loads(r.read().decode())
        assert h["stt_replicas"] == {"total": 2, "healthy": 2, "draining": 0}
        # a killed replica leaves the ring after probe_fails_limit sweeps
        # (the same sweep warm-restarts it; it re-admits on the NEXT one —
        # read /health inside that window)
        tier.batchers[0].kill(RuntimeError("x"))
        tier.sweep_once()
        tier.sweep_once()
        assert tier.replicas[0].state == "down"
        with urllib.request.urlopen(voice.url + "/health", timeout=10) as r:
            h = json.loads(r.read().decode())
        assert h["stt_replicas"] == {"total": 2, "healthy": 1, "draining": 0}
        # and the warm restart re-admits it on the following sweep
        tier.sweep_once()
        assert tier.replicas[0].state == "up"
    finally:
        voice.__exit__(None, None, None)
        tier.stop()
