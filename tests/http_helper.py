"""Run an aiohttp app on a real socket in a background thread (test helper).

Mirrors the reference voice tests' style: boot the actual server on an
ephemeral port and talk to it over TCP (apps/voice/test/server.test.ts:8-14).
"""

from __future__ import annotations

import asyncio
import threading

from aiohttp import web


class AppServer:
    def __init__(self, app: web.Application):
        self.app = app
        self.port: int | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()

    def __enter__(self) -> "AppServer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("server failed to start")
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def start():
            # services that propagate client disconnects into in-flight
            # work (brain/voice mid-decode cancellation, ISSUE 7) set this
            # app flag; aiohttp >= 3.9 made handler cancellation opt-in
            from tpu_voice_agent.services import HANDLER_CANCELLATION

            runner = web.AppRunner(
                self.app,
                handler_cancellation=bool(
                    self.app.get(HANDLER_CANCELLATION, False)))
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            self.port = runner.addresses[0][1]
            self._runner = runner
            self._started.set()

        self._loop.run_until_complete(start())
        self._loop.run_forever()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def __exit__(self, *exc) -> None:
        async def stop():
            await self._runner.cleanup()

        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(stop(), self._loop).result(timeout=10)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)
