"""Qwen2-VL grounding head: model correctness + executor bridge.

BASELINE config 5 / SURVEY.md §2 #15: the VL head augments the DOM
analyzer's structured page representation. Everything runs on CPU per the
reference's seam strategy (SURVEY.md §4).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.qwen2vl import (
    PRESETS,
    embed_tokens,
    forward_embeds,
    init_kv_cache,
    init_params,
    mrope_tables,
    text_positions3,
    vision_forward,
    vision_token_positions,
)

CFG = PRESETS["qwen2vl-test"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_vision_forward_shapes(params):
    v = CFG.vision
    img = jnp.asarray(np.random.default_rng(0).random((2, v.img_size, v.img_size, 3)), jnp.float32)
    out = vision_forward(params["vision"], v, img)
    assert out.shape == (2, v.n_tokens, CFG.dim)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_mrope_equal_streams_is_1d_rope():
    """Text tokens carry t==h==w; M-RoPE must then reduce to plain RoPE."""
    from tpu_voice_agent.models.llama import rope_tables

    pos = jnp.arange(6, dtype=jnp.int32)[None, :]
    pos3 = jnp.broadcast_to(pos[None], (3, 1, 6))
    cos3, sin3 = mrope_tables(pos3, CFG.head_dim, CFG.rope_theta, CFG.mrope_sections)
    cos1, sin1 = rope_tables(pos, CFG.head_dim, CFG.rope_theta)
    np.testing.assert_allclose(np.asarray(cos3), np.asarray(cos1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sin3), np.asarray(sin1), rtol=1e-6)


def test_incremental_decode_matches_full_forward(params):
    """Prefill-then-decode through the KV cache must reproduce teacher-forced
    logits — validates cache slots, M-RoPE positions, and causality."""
    T = 10
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(3, CFG.vocab_size, (1, T)), jnp.int32)
    emb = embed_tokens(params, ids)
    slots = jnp.arange(T, dtype=jnp.int32)[None]
    pos3 = text_positions3(0, T)

    cache = init_kv_cache(CFG, 1, 32, dtype=jnp.float32)
    full_logits, _ = forward_embeds(params, CFG, emb, slots, pos3, cache)

    cache = init_kv_cache(CFG, 1, 32, dtype=jnp.float32)
    step_logits = []
    for t in range(T):
        lg, cache = forward_embeds(
            params, CFG, emb[:, t:t + 1], slots[:, t:t + 1], pos3[:, :, t:t + 1], cache
        )
        step_logits.append(lg[:, 0])
    inc = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full_logits), atol=2e-3, rtol=2e-2)


def test_vision_token_positions_grid():
    p = vision_token_positions(CFG.vision)
    gm = CFG.vision.merged_grid
    assert p.shape == (3, gm * gm)
    assert p[0].max() == 0 and p[1].max() == gm - 1 and p[2].max() == gm - 1


# ---------------------------------------------------------------- grounding


def test_grounding_engine_emits_grammar_valid_point():
    from tpu_voice_agent.serve.grounding import GroundingEngine

    eng = GroundingEngine(preset="qwen2vl-test", max_len=192)
    img = (np.random.default_rng(0).random((240, 320, 3)) * 255).astype(np.uint8)
    res = eng.ground(img, "click the search box", max_new_tokens=40)
    if res.raw and res.steps < 40:  # finished inside the budget => must parse
        obj = json.loads(res.raw)
        assert 0 <= obj["point"][0] <= 999 and 0 <= obj["point"][1] <= 999
    assert 0 <= res.x_norm <= 999 and 0 <= res.y_norm <= 999


def test_letterbox_point_roundtrip():
    from tpu_voice_agent.serve.grounding import GroundingEngine, GroundingResult, letterbox

    img = np.zeros((200, 400, 3), np.uint8)
    boxed, scale, pad_x, pad_y = letterbox(img, 112)
    assert boxed.shape == (112, 112, 3)
    # a landscape page centers vertically: pad_y > 0, pad_x == 0
    assert pad_x == 0 and pad_y > 0
    res = GroundingResult(x_norm=500, y_norm=500, label="", raw="", vision_ms=0,
                          prefill_ms=0, decode_ms=0, steps=0)
    x, y = GroundingEngine.to_page_px(res, 400, 200)
    assert abs(x - 200) < 2 and abs(y - 100) < 2  # center maps to center


def test_element_at_point_prefers_smallest_bbox():
    from tpu_voice_agent.services.executor.grounding import element_at_point

    analysis = {
        "buttons": [
            {"selector": "#big", "isVisible": True, "bbox": {"x": 0, "y": 0, "w": 500, "h": 500}},
            {"selector": "#small", "isVisible": True, "bbox": {"x": 90, "y": 90, "w": 40, "h": 20}},
        ],
        "links": [
            {"selector": "#hidden", "isVisible": False, "bbox": {"x": 0, "y": 0, "w": 999, "h": 999}},
        ],
    }
    hit = element_at_point(analysis, 100, 100)
    assert hit is not None and hit["selector"] == "#small"
    assert element_at_point(analysis, 600, 600) is None


def test_grounded_click_through_interpreter(tmp_path):
    """Auto-strategy click with no DOM text match routes through the injected
    grounder and clicks the selector whose bbox encloses the point."""
    from tpu_voice_agent.schemas import Intent
    from tpu_voice_agent.services.executor.actions import run_intents
    from tpu_voice_agent.services.executor.page import FakeElement, FakePage

    page = FakePage(
        elements=[
            FakeElement("#buy", tag="button", text="Buy now", role="button",
                        name="Buy now", bbox=(100, 200, 80, 30)),
        ],
        url="https://demo.local/item",
    )
    calls = []

    def grounder(image, instruction):
        calls.append(instruction)
        return 120.0, 210.0, "buy button"

    intents = [Intent(type="click", args={"text": "purchase this item"})]
    results = run_intents(page, tmp_path, intents, grounder=grounder,
                          screenshot_each_step=False)
    assert results[0].ok, results[0].error
    assert calls == ["purchase this item"]
    assert results[0].data["by"] == "grounded_selector"
    assert results[0].data["selector"] == "#buy"
    assert ("click_selector", "#buy") in page.actions
