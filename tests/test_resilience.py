"""Unit tests for the shared resilience kit (utils.resilience).

Every state machine takes an injectable clock/rng, so these tests drive
deadline expiry, breaker trips/resets, and backoff schedules without
sleeping."""

import asyncio

import httpx
import pytest

from tpu_voice_agent.utils.resilience import (
    DEADLINE_HEADER,
    AdmissionController,
    BreakerOpenError,
    CircuitBreaker,
    Deadline,
    DeadlineExpired,
    RetryPolicy,
    post_with_resilience,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------- deadline


def test_deadline_budget_and_expiry():
    clk = FakeClock()
    d = Deadline.after(2.0, clock=clk)
    assert not d.expired and d.remaining_s() == pytest.approx(2.0)
    clk.advance(1.5)
    assert d.remaining_s() == pytest.approx(0.5)
    clk.advance(1.0)
    assert d.expired and d.remaining_s() == 0.0


def test_deadline_header_roundtrip():
    clk = FakeClock()
    d = Deadline.after(1.5, clock=clk)
    hdr = {DEADLINE_HEADER: d.header_value()}
    assert hdr[DEADLINE_HEADER] == "1500"
    d2 = Deadline.from_headers(hdr, clock=clk)
    assert d2 is not None and d2.remaining_s() == pytest.approx(1.5)
    # downstream sees the budget the wire carried, not the origin's clock
    clk.advance(2.0)
    assert d2.expired


def test_deadline_from_headers_tolerates_absent_and_garbage():
    assert Deadline.from_headers({}) is None
    assert Deadline.from_headers({DEADLINE_HEADER: "not-a-number"}) is None
    d = Deadline.from_headers({DEADLINE_HEADER: "-50"})
    assert d is not None and d.expired  # negative budget: already expired


# ------------------------------------------------------------------ retry


def test_retry_backoff_grows_and_caps():
    p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=0.5, jitter=0.0)
    delays = [p.backoff_s(a) for a in range(5)]
    assert delays == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_retry_jitter_bounds():
    p = RetryPolicy(base_delay_s=0.2, multiplier=1.0, jitter=0.5)
    lo = p.backoff_s(0, rng=lambda: 0.0)
    hi = p.backoff_s(0, rng=lambda: 1.0)
    assert lo == pytest.approx(0.1)   # (1 - jitter) * delay
    assert hi == pytest.approx(0.2)   # full delay


# ---------------------------------------------------------------- breaker


def test_breaker_trips_after_threshold_and_half_open_recovers():
    clk = FakeClock()
    br = CircuitBreaker("dep", failure_threshold=3, reset_after_s=5.0, clock=clk)
    for _ in range(2):
        assert br.allow()
        br.record_failure()
    assert br.state == "closed"
    br.record_failure()  # third consecutive failure trips it
    assert br.state == "open"
    assert not br.allow()  # fail fast, no socket touch
    clk.advance(5.1)
    assert br.state == "half_open"
    assert br.allow()       # the single probe passes
    assert not br.allow()   # ...but only the single probe
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_failed_probe_reopens():
    clk = FakeClock()
    br = CircuitBreaker("dep", failure_threshold=1, reset_after_s=1.0, clock=clk)
    br.record_failure()
    assert br.state == "open"
    clk.advance(1.5)
    assert br.allow()  # probe admitted
    br.record_failure()
    assert br.state == "open" and not br.allow()
    # the reset window restarts from the failed probe
    clk.advance(0.5)
    assert not br.allow()
    clk.advance(0.6)
    assert br.allow()


def test_breaker_state_gated_failure_retrips_after_reset_window():
    """Callers that gate on ``state`` instead of ``allow()`` (the router's
    passive per-replica breakers) never drive open->half_open themselves:
    a failure recorded after the reset window has elapsed IS a failed
    half-open probe and must re-open the breaker — not fall into the
    closed-path failure counting that can never trip from 'open'."""
    clk = FakeClock()
    br = CircuitBreaker("dep", failure_threshold=3, reset_after_s=1.0, clock=clk)
    for _ in range(3):
        br.record_failure()
    assert br.state == "open"
    clk.advance(1.5)
    assert br.state == "half_open"  # state-gated callers admit traffic again
    br.record_failure()             # ...and the trial traffic failed
    assert br.state == "open"       # re-tripped, _opened_at refreshed
    clk.advance(0.6)
    assert br.state == "open"       # window restarts from the re-trip
    clk.advance(0.6)
    assert br.state == "half_open"
    br.record_success()
    assert br.state == "closed"


def test_breaker_abandoned_probe_does_not_wedge_half_open():
    """A half-open probe whose caller vanished (cancelled WS, torn-down
    client) never records success OR failure; after another reset window a
    new probe must be admitted rather than rejecting forever."""
    clk = FakeClock()
    br = CircuitBreaker("dep", failure_threshold=1, reset_after_s=1.0, clock=clk)
    br.record_failure()
    clk.advance(1.1)
    assert br.allow()       # probe admitted... and then abandoned
    assert not br.allow()   # probe slot consumed
    clk.advance(1.1)
    assert br.allow()       # time escape: one fresh probe per reset window
    br.record_success()
    assert br.state == "closed"


def test_breaker_success_resets_consecutive_failures():
    br = CircuitBreaker("dep", failure_threshold=2)
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"  # non-consecutive failures never trip


# -------------------------------------------------------------- admission


def test_admission_caps_inflight():
    adm = AdmissionController("svc", max_inflight=2, retry_after_s=0.5)
    assert adm.try_acquire() and adm.try_acquire()
    assert adm.saturated and not adm.try_acquire()
    adm.release()
    assert not adm.saturated and adm.try_acquire()
    adm.release(), adm.release()
    assert adm.inflight == 0


# ------------------------------------------------------------ budgeted POST


class FakeResponse:
    def __init__(self, status_code: int, headers=None):
        self.status_code = status_code
        self.headers = headers or {}


class FakeHTTP:
    """Scripted transport: each entry is a response to return or an
    exception to raise, in call order."""

    def __init__(self, script):
        self.script = list(script)
        self.calls: list[dict] = []

    async def post(self, url, json=None, headers=None, timeout=None):
        self.calls.append({"headers": headers, "timeout": timeout})
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        return item


async def _no_sleep(_s):
    pass


def test_post_retries_connect_errors_then_succeeds():
    http = FakeHTTP([httpx.ConnectError("down"), httpx.ConnectError("down"),
                     FakeResponse(200)])
    r = asyncio.run(post_with_resilience(
        http, "http://x/parse", json_body={}, deadline=Deadline.after(30),
        policy=RetryPolicy(max_attempts=3, jitter=0.0), sleep=_no_sleep))
    assert r.status_code == 200 and len(http.calls) == 3
    # the propagated budget header rides every attempt
    assert all(DEADLINE_HEADER in c["headers"] for c in http.calls)


def test_post_does_not_retry_read_timeouts():
    """A read timeout means the server may have ACTED on the request —
    neither /parse session turns nor /execute browser actions are
    idempotent, so the kit must not resend."""
    http = FakeHTTP([httpx.ReadTimeout("slow"), FakeResponse(200)])
    with pytest.raises(httpx.ReadTimeout):
        asyncio.run(post_with_resilience(
            http, "http://x/execute", json_body={}, deadline=Deadline.after(30),
            policy=RetryPolicy(max_attempts=3, jitter=0.0), sleep=_no_sleep))
    assert len(http.calls) == 1


def test_post_retries_503_and_returns_final_503():
    http = FakeHTTP([FakeResponse(503, {"Retry-After": "0"}),
                     FakeResponse(503, {"Retry-After": "0"})])
    r = asyncio.run(post_with_resilience(
        http, "http://x/parse", json_body={}, deadline=Deadline.after(30),
        policy=RetryPolicy(max_attempts=2, jitter=0.0), sleep=_no_sleep))
    assert r.status_code == 503 and len(http.calls) == 2  # caller owns policy


def test_post_honors_retry_after_as_backoff_floor():
    """A server-sent Retry-After on 503 floors the backoff: the kit must
    wait at least what the server asked for, not its own (shorter)
    jittered schedule."""
    sleeps: list[float] = []

    async def record_sleep(s):
        sleeps.append(s)

    http = FakeHTTP([FakeResponse(503, {"Retry-After": "2"}),
                     FakeResponse(200)])
    r = asyncio.run(post_with_resilience(
        http, "http://x/parse", json_body={}, deadline=Deadline.after(30),
        policy=RetryPolicy(max_attempts=2, base_delay_s=0.01, jitter=0.0),
        sleep=record_sleep))
    assert r.status_code == 200 and len(http.calls) == 2
    assert sleeps == [pytest.approx(2.0)]


def test_post_retry_after_capped_by_remaining_deadline():
    """A Retry-After LONGER than the remaining budget must not forfeit the
    retry (the old behavior: wait > remaining -> give up without ever
    re-asking). The wait is capped at half the remaining deadline so the
    attempt itself still fits."""
    sleeps: list[float] = []

    async def record_sleep(s):
        sleeps.append(s)

    http = FakeHTTP([FakeResponse(503, {"Retry-After": "60"}),
                     FakeResponse(200)])
    r = asyncio.run(post_with_resilience(
        http, "http://x/parse", json_body={}, deadline=Deadline.after(2.0),
        policy=RetryPolicy(max_attempts=2, base_delay_s=0.01, jitter=0.0),
        sleep=record_sleep))
    # the retry HAPPENED (old code returned the 503 without a second call)
    assert r.status_code == 200 and len(http.calls) == 2
    assert len(sleeps) == 1 and sleeps[0] <= 1.0  # capped at remaining/2


def test_post_fails_fast_on_open_breaker():
    br = CircuitBreaker("dep", failure_threshold=1, reset_after_s=60.0)
    http = FakeHTTP([httpx.ConnectError("down"), FakeResponse(200)])
    with pytest.raises(httpx.ConnectError):
        asyncio.run(post_with_resilience(
            http, "http://x/parse", json_body={}, deadline=Deadline.after(30),
            policy=RetryPolicy(max_attempts=1), breaker=br, sleep=_no_sleep))
    assert br.state == "open"
    with pytest.raises(BreakerOpenError):
        asyncio.run(post_with_resilience(
            http, "http://x/parse", json_body={}, deadline=Deadline.after(30),
            policy=RetryPolicy(max_attempts=1), breaker=br, sleep=_no_sleep))
    assert len(http.calls) == 1  # the open circuit never touched the socket


def test_post_5xx_counts_as_breaker_failure_4xx_as_success():
    """A reachable-but-wedged dependency (500 on every call) must trip the
    circuit; semantic refusals (409/422) must not."""
    br = CircuitBreaker("dep", failure_threshold=2, reset_after_s=60.0)

    def post(status):
        return asyncio.run(post_with_resilience(
            FakeHTTP([FakeResponse(status)]), "http://x/parse", json_body={},
            deadline=Deadline.after(30), policy=RetryPolicy(max_attempts=1),
            breaker=br, sleep=_no_sleep))

    assert post(500).status_code == 500 and br.state == "closed"
    assert post(409).status_code == 409 and br.state == "closed"  # resets
    post(500)
    assert br.state == "closed"
    post(500)  # second consecutive 5xx trips
    assert br.state == "open"


def test_post_raises_when_deadline_already_expired():
    clk = FakeClock()
    d = Deadline.after(0.0, clock=clk)
    http = FakeHTTP([FakeResponse(200)])
    with pytest.raises(DeadlineExpired):
        asyncio.run(post_with_resilience(
            http, "http://x/parse", json_body={}, deadline=d, sleep=_no_sleep))
    assert not http.calls


def test_post_attempt_is_bounded_by_wall_clock():
    """httpx interprets a bare-float timeout per PHASE (connect, read, ...),
    so the kit must bound the whole attempt itself — a stalled transport
    must not overrun the hop budget."""
    import time

    class StallingHTTP:
        async def post(self, url, json=None, headers=None, timeout=None):
            await asyncio.sleep(30)

    t0 = time.monotonic()
    with pytest.raises(DeadlineExpired):
        asyncio.run(post_with_resilience(
            StallingHTTP(), "http://x/parse", json_body={},
            deadline=Deadline.after(0.2),
            policy=RetryPolicy(max_attempts=3, jitter=0.0), sleep=_no_sleep))
    assert time.monotonic() - t0 < 5.0  # budget-bounded, not phase-bounded


def test_post_stops_retrying_when_budget_cannot_cover_backoff():
    clk = FakeClock()
    d = Deadline(0.3, clock=clk)
    # each connect error is instant; backoff of 1s exceeds the 0.3s budget,
    # so the second attempt never happens and the transport error surfaces
    http = FakeHTTP([httpx.ConnectError("down"), FakeResponse(200)])
    with pytest.raises(httpx.ConnectError):
        asyncio.run(post_with_resilience(
            http, "http://x/parse", json_body={}, deadline=d,
            policy=RetryPolicy(max_attempts=3, base_delay_s=1.0, jitter=0.0),
            sleep=_no_sleep))
    assert len(http.calls) == 1
