"""Shared-prefix caching + single-row admission (round-2 VERDICT #2/#3).

The system prompt + few-shots are identical for every /parse request, so the
engine prefills them ONCE and each request prefills only its user suffix.
Correctness bar: prefix-cached decode must be token-identical to full
prefill, both single-request and through the continuous batcher, and the
batched brain service must answer concurrent requests correctly.
"""

import numpy as np
import pytest

from tpu_voice_agent.serve import DecodeEngine
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import BatchedEngineParser, install_prompt_prefix
from tpu_voice_agent.services.prompts import render_prompt


def _mk(slots: int = 1) -> DecodeEngine:
    return DecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=slots,
        prefill_buckets=(128, 256, 512, 1024),
    )


@pytest.fixture(scope="module")
def plain_engine():
    return _mk()


@pytest.fixture(scope="module")
def prefix_engine():
    eng = _mk()
    P = install_prompt_prefix(eng)
    assert P > 0, "shared prompt head must tokenize to a non-empty common prefix"
    return eng


def test_prefix_covers_almost_all_of_the_prompt(prefix_engine):
    """The point of the cache: the per-request suffix is a small fraction of
    the full prompt (prefill cost becomes suffix-proportional)."""
    eng = prefix_engine
    ids = eng.tokenizer.encode(render_prompt("search for usb hubs", {}), bos=True)
    suffix = eng._split_prefix(ids)
    assert suffix is not None
    assert len(suffix) < len(ids) * 0.15, (len(suffix), len(ids))


def test_prefix_decode_token_identical(plain_engine, prefix_engine):
    prompt = render_prompt("search for mechanical keyboards", {})
    ra = plain_engine.generate(prompt, max_new_tokens=200)
    rb = prefix_engine.generate(prompt, max_new_tokens=200)
    assert ra.token_ids == rb.token_ids
    assert ra.finished == rb.finished


def test_prefix_decode_with_context_payload(plain_engine, prefix_engine):
    prompt = render_prompt("open the second result", {"last_query": "gpus"})
    ra = plain_engine.generate(prompt, max_new_tokens=200)
    rb = prefix_engine.generate(prompt, max_new_tokens=200)
    assert ra.token_ids == rb.token_ids


def test_unmatched_prompt_falls_back_to_full_prefill(prefix_engine):
    """A prompt NOT starting with the cached prefix must still decode (the
    exact-token-match gate routes it to the plain path)."""
    res = prefix_engine.generate("just some other prompt entirely", max_new_tokens=64)
    assert res.steps >= 0  # no crash; grammar walk stays live
    state = prefix_engine.fsm.walk(res.token_ids)
    assert state >= 0


def test_batcher_single_row_admission_matches_generate(plain_engine):
    """Single-row admission prefill (prefill_row) must reproduce the
    single-request path token for token at equal batch width (B=1; across
    batch widths bf16 numerics legitimately differ)."""
    eng = _mk(slots=1)
    batcher = ContinuousBatcher(eng, chunk_steps=16, max_new_tokens=200)
    prompts = [
        render_prompt("search for laptops under 1000", {}),
        render_prompt("take a screenshot", {}),
    ]
    solo = [plain_engine.generate(p, max_new_tokens=200) for p in prompts]
    packed = batcher.generate_many(prompts)
    for s, b in zip(solo, packed):
        assert s.token_ids == b.token_ids


def test_batcher_with_prefix_matches_batcher_without(plain_engine):
    """Prefix-cached admission must be token-identical to full-prompt
    admission through the same batcher shape."""
    prompts = [
        render_prompt("sort these by price from low to high", {}),
        render_prompt("upload my resume and submit", {}),
        render_prompt("scroll down", {"last_query": "x"}),
    ]
    eng_a = _mk(slots=3)
    plain = ContinuousBatcher(eng_a, chunk_steps=16, max_new_tokens=200).generate_many(prompts)
    eng_b = _mk(slots=3)
    install_prompt_prefix(eng_b)
    cached = ContinuousBatcher(eng_b, chunk_steps=16, max_new_tokens=200).generate_many(prompts)
    for s, b in zip(plain, cached):
        assert s.token_ids == b.token_ids


def test_batched_parser_concurrent_http():
    """BatchedEngineParser behind the real HTTP app: concurrent /parse
    requests share decode chunks and each gets a self-consistent response
    (200 grammar-valid or 422 truncation under tiny random weights)."""
    import httpx

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import build_app

    eng = _mk(slots=4)
    install_prompt_prefix(eng)
    parser = BatchedEngineParser(eng, chunk_steps=16, max_new_tokens=200)
    try:
        with AppServer(build_app(parser)) as srv:
            from concurrent.futures import ThreadPoolExecutor

            def post(q):
                return httpx.post(
                    srv.url + "/parse",
                    json={"text": f"search for {q}", "context": {}},
                    timeout=300,
                )

            with ThreadPoolExecutor(max_workers=4) as ex:
                results = list(ex.map(post, ["ants", "bees", "cats", "dogs"]))
            for r in results:
                assert r.status_code in (200, 422), r.text
                if r.status_code == 200:
                    assert isinstance(r.json()["intents"], list)
            # the batcher actually interleaved: multiple parse jobs completed
            # through the shared runtime
            assert parser.runtime.stats.parse_jobs == 4
    finally:
        parser.close()


def test_admission_writes_do_not_disturb_running_slots():
    """A request admitted mid-decode must not change an in-flight row's
    output (row-isolated prefill writes)."""
    eng = _mk(slots=2)
    b1 = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=120)
    p1 = render_prompt("search for monitors", {})
    p2 = render_prompt("go back", {})
    rid1 = b1.submit(p1)
    b1.step()  # admit p1, decode a chunk
    rid2 = b1.submit(p2)  # joins at the next chunk boundary
    b1.run_until_done()
    joined = b1.results[rid1]
    assert b1.results[rid2] is not None

    eng2 = _mk(slots=2)
    b2 = ContinuousBatcher(eng2, chunk_steps=8, max_new_tokens=120)
    alone = b2.generate_many([p1])[0]
    assert joined.token_ids == alone.token_ids
