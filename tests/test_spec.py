"""Speculative decoding (serve.spec): draft K, verify in one pass.

The load-bearing property is DIFFERENTIAL: greedy speculative output must be
token-identical to the plain constrained greedy path for EVERY drafter —
accepted tokens are by construction the target's own masked greedy choices,
so draft quality may only change the forward count, never the stream. The
rollback/invalid-draft tests push adversarial proposals through the same
assert.

Runs CPU-only on the tiny preset (fast tier: shared f32 weights, small
buckets, one verify-step compile shared across engines via the jit cache).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.llama import init_params
from tpu_voice_agent.serve import DecodeEngine, GenerationResult, SpecConfig
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.spec import (
    ChainDrafter,
    Drafter,
    DraftModelDrafter,
    PromptLookupDrafter,
    SpecDecoder,
    spec_from_env,
)

PROMPTS = ["search for usb hubs", "scroll down"]
MAXTOK = 64


def _mk_engine(raw, spec=None, batch_slots=1):
    eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                       batch_slots=batch_slots, init_weights=False, spec=spec)
    eng.load_params(raw)
    return eng


@pytest.fixture(scope="module")
def raw_params():
    eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                       init_weights=False)
    return init_params(eng.cfg, jax.random.PRNGKey(7), dtype=jnp.float32)


@pytest.fixture(scope="module")
def baseline(raw_params):
    eng = _mk_engine(raw_params)
    return [eng.generate(p, max_new_tokens=MAXTOK) for p in PROMPTS]


# ---------------------------------------------------------------- identity


@pytest.mark.parametrize("drafter", ["fsm", "prompt", "fsm,prompt", "model"])
def test_spec_greedy_token_identical(raw_params, baseline, drafter):
    eng = _mk_engine(raw_params, spec=SpecConfig(k=4, drafter=drafter))
    for p, ref in zip(PROMPTS, baseline):
        res = eng.generate(p, max_new_tokens=MAXTOK)
        assert res.token_ids == ref.token_ids, (drafter, res.text[:80])
        assert res.finished == ref.finished
        # accounting: steps counts ACCEPTED tokens, forwards verify steps
        assert res.steps == len(res.token_ids)
        assert 0 < res.forwards <= res.steps
    assert eng.spec.stats()["verify_steps"] > 0


def test_self_draft_accepts_everything(raw_params, baseline):
    """Draft model == target model: every draft is the target's own greedy
    choice, so the verify pass must accept all K per step — the strongest
    end-to-end check of the accept logic and KV/pos rollback bookkeeping."""
    eng = _mk_engine(raw_params)
    eng.spec = SpecDecoder(
        eng, SpecConfig(k=4),
        drafter=DraftModelDrafter(eng, cfg=eng.cfg, params=raw_params))
    res = eng.generate(PROMPTS[0], max_new_tokens=MAXTOK)
    assert res.token_ids == baseline[0].token_ids
    s = eng.spec.stats()
    assert s["accept_rate"] == pytest.approx(1.0)
    assert s["tokens_per_step"] > 2.0
    assert res.forwards < res.steps / 2


def test_batched_spec_matches_singles(raw_params, baseline):
    eng = _mk_engine(raw_params, spec=SpecConfig(k=4, drafter="fsm,prompt"),
                     batch_slots=2)
    results = ContinuousBatcher(eng, chunk_steps=8,
                                max_new_tokens=MAXTOK).generate_many(PROMPTS)
    for ref, res in zip(baseline, results):
        assert res.error is None
        assert res.token_ids == ref.token_ids
        assert eng.fsm.walk(res.token_ids) >= 0


def test_spec_byte_budget_parity(raw_params):
    """Truncation boundaries (the subtle part of multi-token accounting)
    must land on the same token under speculation."""
    a = _mk_engine(raw_params)
    b = _mk_engine(raw_params, spec=SpecConfig(k=4, drafter="fsm,prompt"))
    for budget in (16, 40):
        ra = a.generate(PROMPTS[0], max_new_tokens=MAXTOK, byte_budget=budget)
        rb = b.generate(PROMPTS[0], max_new_tokens=MAXTOK, byte_budget=budget)
        assert ra.token_ids == rb.token_ids
        assert ra.finished == rb.finished


# ---------------------------------------------------------------- rollback


class _WrongLegalDrafter(Drafter):
    """Adversarial: proposes grammar-LEGAL tokens chosen to disagree with
    the model (highest legal id) — every step exercises rejection rollback."""

    name = "wrong"

    def __init__(self, fsm):
        self.fsm = fsm

    def draft_one(self, ctx, state, k):
        out, s = [], state
        for _ in range(k):
            if s < 0:
                break
            allowed = np.nonzero(self.fsm.allowed(s))[0]
            if len(allowed) == 0:
                break
            t = int(allowed[-1])
            out.append(t)
            s = self.fsm.step(s, t)
        return out


class _DeadDrafter(Drafter):
    """Adversarial: proposes tokens that are grammar-dead from EVERY state
    (column class 0) — the FSM-invalid-draft case; nothing may be accepted
    and the stream must not move off the plain path."""

    name = "dead"

    def __init__(self, fsm, k):
        dead = np.nonzero(fsm.col_id == 0)[0]
        assert len(dead) > 0, "toy vocab always has dead-everywhere ids"
        self.toks = [int(dead[0])] * k

    def draft_one(self, ctx, state, k):
        return self.toks[:k]


def test_rejection_rollback_keeps_stream(raw_params, baseline):
    eng = _mk_engine(raw_params)
    eng.spec = SpecDecoder(eng, SpecConfig(k=4),
                           drafter=_WrongLegalDrafter(eng.fsm))
    res = eng.generate(PROMPTS[0], max_new_tokens=MAXTOK)
    assert res.token_ids == baseline[0].token_ids
    s = eng.spec.stats()
    assert s["drafted"] > 0
    assert s["accepted"] < s["drafted"]  # rollback actually exercised


def test_fsm_invalid_drafts_never_accepted(raw_params, baseline):
    eng = _mk_engine(raw_params)
    eng.spec = SpecDecoder(eng, SpecConfig(k=4),
                           drafter=_DeadDrafter(eng.fsm, 4))
    res = eng.generate(PROMPTS[0], max_new_tokens=MAXTOK)
    assert res.token_ids == baseline[0].token_ids
    s = eng.spec.stats()
    assert s["drafted"] > 0
    assert s["accepted"] == 0


# ---------------------------------------------------------------- drafters


def test_lookahead_chains_walk_the_fsm(raw_params):
    eng = _mk_engine(raw_params)
    fsm = eng.fsm
    ff_tokens, ff_len = fsm.forced_tables(width=8)
    hits = 0
    for s in np.nonzero(ff_len > 0)[0][:40]:
        chain = fsm.lookahead(int(s), 8)
        assert chain == [int(t) for t in ff_tokens[s, : int(ff_len[s])]]
        st = int(s)
        for t in chain:
            st = fsm.step(st, t)
            assert st >= 0, "lookahead proposal left the grammar"
        hits += 1
    assert hits > 0
    # free-choice / dead states draft nothing
    assert fsm.lookahead(-1, 8) == []
    free = np.nonzero(ff_len == 0)[0]
    assert fsm.lookahead(int(free[0]), 8) == []


def test_prompt_lookup_drafts_continuation():
    d = PromptLookupDrafter(max_ngram=3)
    ctx = [5, 1, 2, 3, 9, 8, 1, 2, 3]
    assert d.draft_one(ctx, 0, 2) == [9, 8]  # trigram [1,2,3] recurs
    assert d.draft_one([1, 2, 3], 0, 2) == []  # no earlier occurrence
    # rightmost (most recent) occurrence wins
    ctx2 = [7, 4, 1, 7, 6, 1, 7, 5, 1, 7]
    assert d.draft_one(ctx2, 0, 1) == [5]


def test_chain_drafter_first_hit_wins(raw_params):
    eng = _mk_engine(raw_params)

    class A(Drafter):
        def draft_one(self, ctx, state, k):
            return []

    class B(Drafter):
        def draft_one(self, ctx, state, k):
            return [1, 2]

    c = ChainDrafter([A(), B()])
    toks, lens = c.draft_batch([[0, 1]], np.zeros(1, np.int32),
                               np.ones(1, bool), 4)
    assert lens[0] == 2 and list(toks[0, :2]) == [1, 2]


# ---------------------------------------------------------------- gating


def test_disabled_path_has_no_decoder(raw_params):
    eng = _mk_engine(raw_params)
    assert eng.spec is None  # decode_chunk/generate never branch


def test_spec_from_env(monkeypatch):
    monkeypatch.delenv("SPEC_ENABLE", raising=False)
    assert spec_from_env() is None
    monkeypatch.setenv("SPEC_ENABLE", "1")
    monkeypatch.setenv("SPEC_K", "6")
    monkeypatch.setenv("SPEC_DRAFTER", "fsm")
    cfg = spec_from_env()
    assert cfg is not None and cfg.k == 6 and cfg.drafter == "fsm"


def test_spec_accepted_on_paged_refused_on_pp(raw_params):
    """ISSUE 8 flips the layout envelope: the paged engine now BUILDS a
    SpecDecoder (block-granular rollback on COW-owned draft blocks; the
    compound-path differentials live in tests/test_spec_paged.py), while
    the pp staged layout keeps a clear typed refusal — pinned here so the
    boot-time error an operator sees never silently regresses to the old
    warn+ignore."""
    from tpu_voice_agent.serve import PagedDecodeEngine, PPDecodeEngine
    from tpu_voice_agent.parallel.pipeline import pp_tp_mesh

    eng = PagedDecodeEngine(preset="test-tiny", max_len=512,
                            prefill_buckets=(64,), init_weights=False,
                            spec=SpecConfig(k=4))
    assert eng.spec is not None and eng.spec.paged

    with pytest.raises(ValueError,
                       match="not supported on the pp layout"):
        PPDecodeEngine(preset="test-tiny", max_len=512,
                       prefill_buckets=(64,), mesh=pp_tp_mesh(1, 1),
                       init_weights=False, spec=SpecConfig(k=4))


def test_unknown_drafter_rejected(raw_params):
    with pytest.raises(ValueError, match="SPEC_DRAFTER"):
        _mk_engine(raw_params, spec=SpecConfig(k=4, drafter="nope"))


# ---------------------------------------------------------------- metrics


def test_generation_result_zero_duration_guard():
    r = GenerationResult(text="", token_ids=[1], prefill_ms=0.0,
                         decode_ms=0.0, steps=1, finished=True)
    assert r.tokens_per_s == 0.0
    r2 = GenerationResult(text="", token_ids=[1], prefill_ms=0.0,
                          decode_ms=-1.0, steps=1, finished=True)
    assert r2.tokens_per_s == 0.0


def test_spec_metrics_exported(raw_params):
    from tpu_voice_agent.utils import get_metrics, prometheus_exposition

    eng = _mk_engine(raw_params, spec=SpecConfig(k=4, drafter="fsm,prompt"))
    eng.generate(PROMPTS[0], max_new_tokens=MAXTOK)
    snap = get_metrics().snapshot()
    for name in ("spec.drafted_tokens", "spec.accepted_tokens",
                 "spec.verify_steps"):
        assert snap["counters"].get(name, 0) > 0, name
    for name in ("spec.accept_rate", "spec.tokens_per_step"):
        assert name in snap["gauges"], name
    assert snap["gauges"]["spec.tokens_per_step"] >= 1.0
    text = prometheus_exposition(get_metrics())
    assert "spec_accept_rate" in text
    assert "spec_drafted_tokens_total" in text
    assert get_metrics().collisions() == []
