"""Brain service contract tests.

Mirrors the reference's apps/brain/test/parse.test.ts:1-101 — valid search
parse, upload+confirmation+tts, follow-up question with low confidence — plus
the error envelopes (400/422/500) against the real HTTP socket.
"""

import httpx
import pytest

from tpu_voice_agent.services.brain import (
    EngineParser,
    ParserError,
    RuleBasedParser,
    build_app,
)
from tests.http_helper import AppServer


@pytest.fixture(scope="module")
def rule_server():
    with AppServer(build_app(RuleBasedParser())) as srv:
        yield srv


def test_health(rule_server):
    r = httpx.get(rule_server.url + "/health")
    assert r.status_code == 200 and r.json()["ok"] is True


def test_parse_search(rule_server):
    r = httpx.post(
        rule_server.url + "/parse",
        json={"text": "search for wireless headphones", "context": {}},
    )
    assert r.status_code == 200
    body = r.json()
    assert body["intents"][0]["type"] == "search"
    assert body["intents"][0]["args"]["query"] == "wireless headphones"
    assert body["context_updates"]["last_query"] == "wireless headphones"
    assert 0 <= body["confidence"] <= 1


def test_parse_upload_requires_confirmation(rule_server):
    r = httpx.post(
        rule_server.url + "/parse",
        json={"text": "upload my resume and submit the form", "context": {}},
    )
    body = r.json()
    assert r.status_code == 200
    assert body["intents"][0]["type"] == "upload"
    assert body["intents"][0]["requires_confirmation"] is True
    assert body["tts_summary"]


def test_parse_gibberish_low_confidence_follow_up(rule_server):
    r = httpx.post(
        rule_server.url + "/parse", json={"text": "florble the wug", "context": {}}
    )
    body = r.json()
    assert body["intents"][0]["type"] == "unknown"
    assert body["confidence"] <= 0.5
    assert body["follow_up_question"]


def test_invalid_request_400(rule_server):
    r = httpx.post(rule_server.url + "/parse", json={"context": {}})
    assert r.status_code == 400
    assert r.json()["error"] == "invalid_request"
    r = httpx.post(
        rule_server.url + "/parse",
        content=b"{not json",
        headers={"content-type": "application/json"},
    )
    assert r.status_code == 400


def test_trace_id_propagates(rule_server):
    r = httpx.post(
        rule_server.url + "/parse",
        json={"text": "go back", "context": {}},
        headers={"x-trace-id": "deadbeef"},
    )
    assert r.headers.get("x-trace-id") == "deadbeef"


class _FailingParser:
    def __init__(self, kind):
        self.kind = kind

    def parse(self, text, context):
        if self.kind == "boom":
            raise RuntimeError("engine fell over")
        raise ParserError(self.kind, "nope")


def test_parser_422_and_500_envelopes():
    with AppServer(build_app(_FailingParser("schema_validation_failed"))) as srv:
        r = httpx.post(srv.url + "/parse", json={"text": "x", "context": {}})
        assert r.status_code == 422 and r.json()["error"] == "schema_validation_failed"
    with AppServer(build_app(_FailingParser("boom"))) as srv:
        r = httpx.post(srv.url + "/parse", json={"text": "x", "context": {}})
        assert r.status_code == 500 and r.json()["error"] == "llm_error"


class _NotingParser:
    """Engine-backend stand-in: deposits the decode split as stage notes
    on the worker thread, like _result_to_response does."""

    def parse(self, text, context):
        from tpu_voice_agent.utils.tracing import note_stage

        note_stage("prefill_ms", 12.5)
        note_stage("decode_ms", 80.25)
        note_stage("cached_tokens", 896)
        return RuleBasedParser().parse(text, context)


def test_decode_split_rides_response_headers():
    """The prefill/decode/cached-tokens split reaches the caller as
    x-* headers (the voice service folds them into the latency HUD's
    stage breakdown); parsers without notes emit none."""
    with AppServer(build_app(_NotingParser())) as srv:
        r = httpx.post(srv.url + "/parse",
                       json={"text": "search for ants", "context": {}})
        assert r.status_code == 200
        assert r.headers["x-prefill-ms"] == "12.5"
        assert r.headers["x-decode-ms"] == "80.25"
        assert r.headers["x-cached-tokens"] == "896"
    with AppServer(build_app(RuleBasedParser())) as srv:
        r = httpx.post(srv.url + "/parse",
                       json={"text": "search for ants", "context": {}})
        assert r.status_code == 200
        assert "x-prefill-ms" not in r.headers


def test_concurrent_parses_do_not_interleave(rule_server):
    """Racing requests share one parser; the serialization lock must keep
    each response self-consistent."""
    from concurrent.futures import ThreadPoolExecutor

    def post(q):
        return httpx.post(
            rule_server.url + "/parse", json={"text": f"search for {q}", "context": {}}
        ).json()

    with ThreadPoolExecutor(max_workers=4) as ex:
        results = list(ex.map(post, ["ants", "bees", "cats", "dogs"]))
    for q, body in zip(["ants", "bees", "cats", "dogs"], results):
        assert body["intents"][0]["args"]["query"] == q


def test_engine_parser_end_to_end_http(tiny_engine):
    """The full TPU-shaped path over a real socket: HTTP -> prompt render ->
    grammar-constrained decode -> schema-validated ParseResponse."""
    with AppServer(build_app(EngineParser(tiny_engine, max_new_tokens=300))) as srv:
        r = httpx.post(
            srv.url + "/parse",
            json={"text": "search for 4k monitors", "context": {}},
            timeout=180,
        )
        assert r.status_code in (200, 422)  # tiny random weights may truncate
        if r.status_code == 200:
            body = r.json()
            assert "intents" in body and isinstance(body["intents"], list)
        else:
            assert r.json()["error"] == "schema_validation_failed"


@pytest.mark.slow  # compiles the pp×tp pipeline on the 8-device mesh
def test_make_parser_env_routes_pp_backend(monkeypatch):
    """BRAIN_BACKEND=pp[:preset] serves through the TP×PP engine with the
    BRAIN_PP/BRAIN_TP mesh axes (the 70B serving layout's env contract)."""
    from tpu_voice_agent.serve import PPDecodeEngine
    from tpu_voice_agent.services.brain import make_parser_from_env

    monkeypatch.setenv("BRAIN_BACKEND", "pp:test-tiny")
    monkeypatch.setenv("BRAIN_PP", "2")
    monkeypatch.setenv("BRAIN_TP", "2")
    monkeypatch.setenv("BRAIN_BATCH", "2")
    for knob in ("BRAIN_MODEL", "BRAIN_QUANT", "BRAIN_MOE", "BRAIN_PAGED",
                 "BRAIN_PREFIX", "BRAIN_CHUNK", "BRAIN_FF"):
        monkeypatch.delenv(knob, raising=False)
    from tpu_voice_agent.services.brain import ParserError

    parser = make_parser_from_env()
    try:
        assert isinstance(parser.engine, PPDecodeEngine)
        assert parser.engine.pp == 2 and parser.engine.tp == 2
        try:
            resp = parser.parse("go back", {})
            assert resp.version == "1.0"
        except ParserError as e:
            # random weights may ramble to the token budget without EOS —
            # the 422-class truncation envelope is the one legal failure
            assert e.kind == "schema_validation_failed"
    finally:
        parser.close()


def test_speculative_parse_stateless_ok_stateful_409(rule_server):
    """speculative=true is a no-op for stateless parsers (parse is pure)
    but must be refused by session-keyed backends, which would otherwise
    commit a provisional turn to the session transcript."""
    r = httpx.post(rule_server.url + "/parse",
                   json={"text": "search for hubs", "context": {},
                         "speculative": True})
    assert r.status_code == 200
    assert r.json()["intents"][0]["type"] == "search"

    class _SessionParser:
        wants_session = True

        def parse(self, text, context, session_id=None):
            raise AssertionError("speculative parse must not reach a "
                                 "session-keyed backend")

    with AppServer(build_app(_SessionParser())) as srv:
        r = httpx.post(srv.url + "/parse",
                       json={"text": "search for hubs", "session_id": "s",
                             "context": {}, "speculative": True})
        assert r.status_code == 409
        assert r.json()["error"] == "speculation_unsupported"
        # the non-speculative retry goes through to the parser
        r2 = httpx.post(srv.url + "/parse",
                        json={"text": "search for hubs", "session_id": "s",
                              "context": {}})
        assert r2.status_code == 500  # our stub raises AssertionError
