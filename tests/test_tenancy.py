"""Multi-tenant QoS plane (ISSUE 18): registry parsing, weighted fair
shares under a concurrent submit hammer, token-bucket throttling with the
retryable ``shed:`` prefix, chunk-boundary preemption that resumes
token-identically, the requeue aging bound, and the feature-off identity
(TENANT_CLASSES unset => the exact pre-tenancy scheduler paths)."""

import threading
import time

import pytest

from tpu_voice_agent.serve import PagedDecodeEngine
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.tenancy import (
    DEFAULT_TENANT,
    FairLanes,
    TenancyPlane,
    parse_tenant_classes,
    tenancy_enabled,
)
from tpu_voice_agent.services.brain import install_prompt_prefix

BUCKETS = (128, 256, 512, 1024, 2048)

PROMPTS = [
    "search for usb hubs", "scroll down", "go back",
    "sort by price", "take a screenshot", "search for keyboards",
]


def _paged(batch_slots=2, radix=True, **kw):
    eng = PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=batch_slots,
        prefill_buckets=BUCKETS, radix_enable=radix, **kw)
    install_prompt_prefix(eng)
    return eng


def _batcher(eng, chunk_steps=8, max_new=32):
    return ContinuousBatcher(eng, chunk_steps=chunk_steps,
                             max_new_tokens=max_new)


# ------------------------------------------------------------- registry


def test_parse_tenant_classes_spec():
    classes = parse_tenant_classes(
        "premium:4:slots=3:blocks=64:rps=20:p50=800, free:1:rps=2")
    assert classes["premium"].weight == 4.0
    assert classes["premium"].slots == 3
    assert classes["premium"].blocks == 64
    assert classes["premium"].rps == 20.0
    assert classes["premium"].p50_ms == 800.0
    assert classes["free"].weight == 1.0 and classes["free"].rps == 2.0
    # the implicit default class always exists: unknown tags degrade to
    # shared best-effort, never to a free ride in someone else's lane
    assert classes[DEFAULT_TENANT].weight == 1.0


@pytest.mark.parametrize("bad", [
    "premium:0",            # zero weight
    "premium:1:turbo=9",    # unknown field
    ":2",                   # empty name
    "premium:1:slots",      # field without =
])
def test_parse_tenant_classes_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_tenant_classes(bad)


def test_tenancy_enabled_follows_knob(monkeypatch):
    monkeypatch.delenv("TENANT_CLASSES", raising=False)
    assert not tenancy_enabled()
    monkeypatch.setenv("TENANT_CLASSES", "premium:4")
    assert tenancy_enabled()


# ----------------------------------------------------- plane unit rules


def test_fair_pick_prefers_poorest_lane_with_headroom():
    plane = TenancyPlane(parse_tenant_classes("a:3:slots=1,b:1"))
    plane.charge("a", 30)   # vtime 10
    plane.charge("b", 30)   # vtime 30
    assert plane.pick(["a", "b"]) == 0       # a is poorer
    plane.on_dequeue("a", admitted=True)     # a now holds its 1-slot cap
    assert plane.pick(["a", "b"]) == 1       # capped lane is skipped
    assert plane.pick(["a"]) is None         # every waiter capped


def test_idle_lane_catchup_no_retroactive_credit():
    plane = TenancyPlane(parse_tenant_classes("busy:1,idle:1"))
    plane.on_queue("busy")
    plane.charge("busy", 1000)
    # idle re-enters: its clock jumps to the busy minimum — no banked
    # credit from the time it submitted nothing
    plane.on_queue("idle")
    assert plane.lane("idle").vtime == pytest.approx(1000.0)


def test_fairlanes_rank_composes_before_priority():
    lanes = FairLanes(parse_tenant_classes("premium:4,free:1"))
    lanes.charge("premium", 4.0)  # vtime 1.0
    lanes.charge("free", 4.0)     # vtime 4.0
    assert lanes.rank("premium") < lanes.rank("free")
    assert lanes.rank("unknown") == lanes.rank(None)  # both -> default


# ------------------------------------------------- scheduler integration


def test_feature_off_identity(monkeypatch):
    """THE differential: with TENANT_CLASSES unset the plane is simply not
    constructed, and outputs match the plane-on run token-for-token (greedy
    decode; fair admission may reorder, results must not change)."""
    monkeypatch.delenv("TENANT_CLASSES", raising=False)
    b_off = _batcher(_paged())
    assert b_off.tenancy is None
    off = b_off.generate_many(PROMPTS[:4])

    monkeypatch.setenv("TENANT_CLASSES", "a:2,b:1")
    b_on = _batcher(_paged())
    assert b_on.tenancy is not None
    rids = [b_on.submit(p, tenant=("a" if i % 2 == 0 else "b"))
            for i, p in enumerate(PROMPTS[:4])]
    b_on.run_until_done()
    for r_off, rid in zip(off, rids):
        assert r_off.error is None
        assert b_on.results[rid].token_ids == r_off.token_ids


def test_rate_limited_tenant_sheds_not_errors(monkeypatch):
    """An over-rps burst is refused at submit with the retryable ``shed:``
    prefix (503 + Retry-After at the brain), and only the bucket's share
    decodes — throttled, never errored or queued."""
    monkeypatch.setenv("TENANT_CLASSES", "slowpoke:1:rps=1")
    b = _batcher(_paged())
    rids = [b.submit(PROMPTS[i % len(PROMPTS)], tenant="slowpoke")
            for i in range(5)]
    shed = [r for r in rids if r in b.results]
    assert len(shed) == 4  # burst = max(1, rps) -> exactly one admitted
    for r in shed:
        assert b.results[r].error.startswith("shed: tenant slowpoke")
    b.run_until_done()
    survivor = [r for r in rids if r not in shed]
    assert len(survivor) == 1 and b.results[survivor[0]].error is None
    assert b.tenancy.snapshot()["lanes"]["slowpoke"]["throttled"] == 4


def test_preemption_resumes_warm_and_token_identical(monkeypatch):
    """Chunk-boundary preemption is preempted-NOT-errored: the victim's
    chain is released warm into its tenant's radix namespace, the original
    prompt requeues, and the resumed decode finishes token-identical to an
    uncontended run."""
    monkeypatch.setenv("TENANT_CLASSES", "premium:4,free:1")
    refs = {p: _batcher(_paged(batch_slots=1), max_new=48)
            .generate_many([p])[0] for p in PROMPTS[:2]}
    b = _batcher(_paged(batch_slots=1), max_new=48)
    r_free = b.submit(PROMPTS[0], tenant="free")
    b.step()  # free holds the only slot, one chunk decoded
    r_prem = b.submit(PROMPTS[1], tenant="premium")
    b.run_until_done()
    lanes = b.tenancy.snapshot()["lanes"]
    assert lanes["free"]["preemptions"] >= 1
    for rid, p in ((r_free, PROMPTS[0]), (r_prem, PROMPTS[1])):
        res = b.results[rid]
        assert res.error is None
        assert res.token_ids == refs[p].token_ids


def test_radix_namespaces_are_tenant_salted(monkeypatch):
    """Two tenants decoding the same prompt get separate (salted) radix
    chains; the shared pinned prompt prefix stays one cross-tenant node."""
    monkeypatch.setenv("TENANT_CLASSES", "a:1,b:1")
    eng = _paged()
    b = _batcher(eng)
    # long enough that prompt+generated fills complete blocks — radix
    # chains only adopt full blocks
    ids = eng.tokenizer.encode(PROMPTS[0], bos=True) * 40
    for t in ("a", "b"):
        rid = b.submit(ids, tenant=t)
        b.run_until_done()
        assert b.results.pop(rid).error is None
    rc = eng.radix[0]
    nodes, stack = [], [rc.root]
    while stack:
        n = stack.pop()
        nodes += list(n.children.values())
        stack += list(n.children.values())
    salted = [n for n in nodes if n.ns is not None]
    assert {n.ns for n in salted} == {"a", "b"}
    # same ids, different namespaces: both tenants own their own copy —
    # while the pinned prompt-prefix chain stays ONE cross-tenant node
    assert len(salted) >= 2
    assert any(n.pinned and n.ns is None for n in nodes)


def test_fairness_race_hammer(monkeypatch):
    """Satellite 3: N submitter threads per tenant against a 2-slot
    batcher with preemption on. Zero lost / double-committed requests,
    zero leaked pool blocks, and the decoded-token split over the
    contended window tracks the 3:1 weights within 10 points."""
    monkeypatch.setenv("TENANT_CLASSES", "premium:3,free:1")
    monkeypatch.setenv("SCHED_POOL_WAIT_S", "60")
    eng = _paged(radix=False)  # radix off => idle pool must return to full
    free0 = eng.allocator.free_blocks(0)
    b = _batcher(eng, chunk_steps=8, max_new=16)

    per_thread, threads_per_tenant = 4, 3
    rids: dict[str, list[int]] = {"premium": [], "free": []}
    lock = threading.Lock()

    def submitter(tenant: str) -> None:
        for i in range(per_thread):
            rid = b.submit(PROMPTS[i % len(PROMPTS)], tenant=tenant)
            with lock:
                rids[tenant].append(rid)

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in ("premium", "free") for _ in range(threads_per_tenant)]
    for th in threads:
        th.start()
    # drive the scheduler concurrently with the submitters (the colocate
    # arrangement: submit from request threads, step from the loop)
    deadline = time.monotonic() + 120
    want = per_thread * threads_per_tenant * 2
    contended_share = None
    while time.monotonic() < deadline:
        b.step()
        lanes = b.tenancy.snapshot()["lanes"]
        total = lanes["premium"]["tokens"] + lanes["free"]["tokens"]
        # sample the share while BOTH lanes still have backlog — after the
        # queues drain, equal finite demand converges every split to 1:1
        if (contended_share is None and total >= 96
                and lanes["premium"]["queued"] > 0
                and lanes["free"]["queued"] > 0):
            contended_share = lanes["premium"]["tokens"] / total
        with lock:
            done = all(r in b.results
                       for rs in rids.values() for r in rs)
        if done and not any(s.request_id >= 0 for s in b.slots):
            break
        time.sleep(0)
    for th in threads:
        th.join()

    all_rids = rids["premium"] + rids["free"]
    assert len(all_rids) == want
    # zero lost, zero double-committed: every rid has exactly one result
    # and every result decoded clean
    assert sorted(b.results) == sorted(all_rids)
    for r in all_rids:
        assert b.results[r].error is None, b.results[r].error
    # zero leaked blocks: with radix off, a drained scheduler returns the
    # pool to exactly its initial free count (preemptions included)
    assert eng.allocator.free_blocks(0) == free0
    assert contended_share is not None, "never observed a contended window"
    assert abs(contended_share - 0.75) <= 0.10, contended_share


def test_requeue_rotation_unsticks_small_requests(monkeypatch):
    """Satellite 2 regression: a pool-starved head requeue must rotate to
    the back after SCHED_REQUEUE_MAX retries so small requests behind it
    admit — not starve behind an oversized prompt for the whole pool wait."""
    monkeypatch.delenv("TENANT_CLASSES", raising=False)  # generic bug, plane off
    monkeypatch.setenv("SCHED_POOL_WAIT_S", "60")
    monkeypatch.setenv("SCHED_REQUEUE_MAX", "2")
    from tpu_voice_agent.utils import get_metrics

    eng = _paged(radix=False, pool_blocks=16)
    b = _batcher(eng, chunk_steps=4, max_new=48)
    base = eng.tokenizer.encode(PROMPTS[3], bos=True)
    bs = eng.block_size
    # prefill allocates whole BUCKETS (power-of-two blocks) and the pinned
    # prompt prefix is resident, so size everything off the live pool:
    # big takes the largest bucket the fully-drained pool can still serve
    # (len stays half a block under the bucket so decode never needs a
    # block past it), and the occupant holds just enough that big's bucket
    # cannot fit while it lives — PoolExhausted until the occupant drains
    pool = eng.allocator.free_blocks(0)
    big_blocks = max(n for n in (1, 2, 4, 8, 16) if n <= pool - 1)
    need = big_blocks * bs - bs // 2
    big_ids = (base * (need // len(base) + 1))[:need]
    occ_need = (pool - big_blocks + 1) * bs - bs // 2
    occ_ids = (base * (occ_need // len(base) + 1))[:occ_need]
    occupant = b.submit(occ_ids)
    b.step()  # occupant holds a slot (and its blocks) for ~12 chunks
    big = b.submit(big_ids)
    small = [b.submit(p) for p in PROMPTS[1:3]]
    rot0 = get_metrics().snapshot()["counters"].get(
        "scheduler.requeue_rotations", 0.0)
    order: list[int] = []
    for _ in range(200):
        b.step()
        for rid in (occupant, big, *small):
            if rid in b.results and rid not in order:
                order.append(rid)
        if len(order) == 4:
            break
    assert len(order) == 4, f"stuck: only {order} finished"
    for rid in (occupant, big, *small):
        assert b.results[rid].error is None, b.results[rid].error
    # the small requests must land BEFORE the oversized head — that is the
    # aging bound working (head yielded after SCHED_REQUEUE_MAX retries)
    assert all(order.index(s) < order.index(big) for s in small)
    rot1 = get_metrics().snapshot()["counters"].get(
        "scheduler.requeue_rotations", 0.0)
    assert rot1 > rot0
