"""Radix KV reuse (serve.radix): session-aware prefix caching over the
paged pool — FAST tier, because the identity contract gates tier-1.

The non-negotiable contract (ISSUE 5, mirroring PR 3/4's differential
style): a radix-hit admission produces TOKEN-IDENTICAL output to a cold
admission; RADIX_ENABLE unset keeps the pre-radix paged path byte-identical;
eviction never frees a block referenced by a live slot or the pinned root
(allocator refcounts are the single source of truth).
"""

import random

import pytest

from tpu_voice_agent.serve import PagedDecodeEngine, RadixCache
from tpu_voice_agent.serve.paged import BlockAllocator, PoolExhausted
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import (
    SessionTranscripts,
    install_prompt_prefix,
)
from tpu_voice_agent.services.prompts import render_prompt


# ---------------------------------------------------------------- allocator


def test_allocator_ref_unknown_block_raises():
    a = BlockAllocator(8)
    x = a.alloc(2)
    with pytest.raises(ValueError, match="untracked block 6"):
        a.ref([x[0], 6])  # 6 was never handed out
    a.free(x)
    with pytest.raises(ValueError, match=f"untracked block {x[0]}"):
        a.ref([x[0]])  # use-after-free


def test_allocator_double_free_raises():
    a = BlockAllocator(8)
    x = a.alloc(1)
    a.free(x)
    with pytest.raises(ValueError, match=f"double free of block {x[0]}"):
        a.free(x)
    with pytest.raises(ValueError, match="double free of block 3"):
        a.free([3])  # never allocated at all


def test_allocator_fuzz_no_leaks_no_double_handouts():
    """Random alloc/ref/free interleavings against a host model: every
    handout is unique among live blocks, refcounts drain to exactly zero,
    and the pool ends fully reclaimed."""
    rng = random.Random(7)
    a = BlockAllocator(32, n_groups=2)
    live: dict[int, int] = {}  # block -> modeled refcount
    for _ in range(3000):
        op = rng.random()
        if op < 0.45:
            g = rng.randrange(2)
            k = rng.randint(1, 4)
            try:
                blocks = a.alloc(k, group=g)
            except PoolExhausted:
                assert a.free_blocks(g) < k
                continue
            assert len(set(blocks)) == k
            for b in blocks:
                assert b not in live, "double handout of a live block"
                assert b % a.blocks_per_group != 0, "reserved trash block leaked"
                assert g * a.blocks_per_group <= b < (g + 1) * a.blocks_per_group
                live[b] = 1
        elif op < 0.7 and live:
            b = rng.choice(list(live))
            a.ref([b])
            live[b] += 1
        elif live:
            b = rng.choice(list(live))
            a.free([b])
            live[b] -= 1
            if live[b] == 0:
                del live[b]
        assert a.blocks_in_use == len(live)
        for b, r in live.items():
            assert a.refcount(b) == r
    for b, r in list(live.items()):
        a.free([b] * r)
    assert a.blocks_in_use == 0
    assert a.blocks_shared == 0


# ---------------------------------------------------------------- tree unit


def _tree(n_blocks=32, bs=4, max_nodes=64):
    a = BlockAllocator(n_blocks)
    return a, RadixCache(a, bs, max_nodes=max_nodes)


def test_radix_match_is_block_granular_and_refs_for_caller():
    a, t = _tree()
    ids = list(range(1, 11))  # 10 tokens, bs=4 -> 2 full blocks
    blocks = a.alloc(3)
    t.insert(ids, blocks)  # adopts blocks[0:2]; blocks[2] is a partial tail
    assert t.nodes == 2
    assert a.refcount(blocks[0]) == 2 and a.refcount(blocks[1]) == 2
    assert a.refcount(blocks[2]) == 1  # partial tail never enters the tree
    chain, matched = t.match(ids)
    assert chain == blocks[:2] and matched == 8
    assert a.refcount(blocks[0]) == 3  # caller's ref taken by match
    # a match alone is not a HIT: the engine accounts the hit only once it
    # commits to the chain (bucket-fallback admissions reuse nothing)
    assert t.hits == 0 and t.lookups == 1
    t.record_hit(matched)
    assert t.hits == 1 and t.matched_tokens == 8
    a.free(chain)
    # an exactly-chain-length prompt must leave >= 1 token to re-prefill
    chain, matched = t.match(ids[:8])
    assert matched == 4 and chain == blocks[:1]
    a.free(chain)
    # diverging ids match only the common block prefix
    chain, matched = t.match(ids[:4] + [99, 98, 97, 96, 95])
    assert matched == 4
    a.free(chain)


def test_radix_eviction_respects_refs_pins_and_lru():
    a, t = _tree()
    pin = a.alloc(1)
    t.pin_root_chain([1, 2, 3, 4], pin)
    b1 = a.alloc(1)
    t.insert([1, 2, 3, 4] + [5, 6, 7, 8], [pin[0], b1[0]])  # chain A
    b2 = a.alloc(1)
    t.insert([1, 2, 3, 4] + [9, 10, 11, 12], [pin[0], b2[0]])  # chain B (newer)
    a.free(b1)  # the tree is now chain A's tail's sole owner
    a.free(b2)
    assert t.nodes == 3
    # a live caller ref protects chain B from eviction
    chain, matched = t.match([1, 2, 3, 4, 9, 10, 11, 12, 0])
    assert matched == 8
    # evict: only chain A's leaf is unreferenced (B's tail is ref'd by the
    # caller, the pinned root may never go)
    assert t.evict(10) == 1
    assert a.refcount(pin[0]) >= 1 and t.nodes == 2
    a.free(chain[1:])  # drop the caller ref on B's tail
    a.free(chain[:1])
    assert t.evict(10) == 1  # now B's tail goes too; the pin stays
    assert t.nodes == 1
    assert t.evict(10) == 0  # nothing evictable left
    assert a.refcount(pin[0]) == 2  # engine ref + tree ref, untouched


def test_radix_lru_evicts_oldest_leaf_first():
    a, t = _tree()
    x = a.alloc(2)
    t.insert([1, 2, 3, 4], x[:1])  # older chain
    t.insert([9, 9, 9, 9], x[1:])  # newer chain
    a.free(x)
    assert t.evict(1) == 1
    # the OLDER leaf went; the newer one still matches
    chain, matched = t.match([9, 9, 9, 9, 0])
    assert matched == 4
    a.free(chain)
    chain, matched = t.match([1, 2, 3, 4, 0])
    assert matched == 0


def test_radix_max_nodes_cap_holds():
    a, t = _tree(n_blocks=64, bs=2, max_nodes=4)
    for i in range(8):
        b = a.alloc(1)
        t.insert([100 + i, 200 + i], b)
        a.free(b)
    assert t.nodes <= 4


def test_radix_clear_frees_tree_refs():
    a, t = _tree()
    b = a.alloc(2)
    t.insert([1, 2, 3, 4, 5, 6, 7, 8], b)
    a.free(b)
    assert a.blocks_in_use == 2  # tree's refs keep them
    t.clear()
    assert a.blocks_in_use == 0 and t.nodes == 0


# ---------------------------------------------------------------- engines

BUCKETS = (128, 256, 512, 1024, 2048)


def _paged(radix: bool, **kw):
    return PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=2,
        prefill_buckets=BUCKETS, radix_enable=radix, **kw)


@pytest.fixture(scope="module")
def eng_off():
    eng = _paged(False)
    install_prompt_prefix(eng)
    return eng


@pytest.fixture(scope="module")
def eng_on():
    eng = _paged(True)
    install_prompt_prefix(eng)
    return eng


def _run(eng, prompts, max_new=48):
    return ContinuousBatcher(eng, chunk_steps=16,
                             max_new_tokens=max_new).generate_many(prompts)


def _frame_ids(tok, text, context):
    user = SessionTranscripts.user_frame(text, context)
    return tok.encode(f"\n<|user|>\n{user}\n<|assistant|>\n", bos=False)


TURNS = [
    ("search for wireless headphones", {}),
    ("open the second result", {"last_query": "wireless headphones"}),
    ("sort these by price from low to high", {"last_query": "wireless headphones"}),
]


def _play_session(eng, max_new=48, turns=TURNS):
    """Drive a multi-turn session exactly like the session-aware brain:
    turn 1 is the stateless render, later turns extend prompt ids +
    generated ids (strict token extension — ragged block boundaries arise
    naturally). Returns (per-turn results, per-turn prompt id lists)."""
    tok = eng.tokenizer
    results, prompts = [], []
    hist = None
    for text, ctx in turns:
        ids = (tok.encode(render_prompt(text, ctx), bos=True) if hist is None
               else hist + _frame_ids(tok, text, ctx))
        r = _run(eng, [ids], max_new=max_new)[0]
        assert r.error is None, r.error
        results.append(r)
        prompts.append(ids)
        hist = ids + r.token_ids
    return results, prompts


def test_radix_disabled_by_default(monkeypatch):
    monkeypatch.delenv("RADIX_ENABLE", raising=False)
    eng = _paged(None)  # env decides
    assert eng.radix is None
    monkeypatch.setenv("RADIX_ENABLE", "1")
    monkeypatch.setenv("RADIX_MAX_NODES", "77")
    eng = _paged(None)
    assert eng.radix is not None and eng.radix[0].max_nodes == 77


def test_radix_multi_turn_token_identity(eng_off, eng_on):
    """THE differential: warm radix admissions (turn 2+ reuse turn N-1's
    decoded chain; a repeat session reuses everything) are token-identical
    to the cold engine, across ragged block boundaries."""
    cold, _ = _play_session(eng_off)
    warm, _ = _play_session(eng_on)
    for c, w in zip(cold, warm):
        assert c.token_ids == w.token_ids
        assert eng_on.fsm.walk(w.token_ids) >= 0
    # turn 2+ matched the session chain past the static prefix
    P = len(eng_on.prefix_ids)
    assert warm[0].cached_tokens == P  # turn 1: static prefix only
    assert warm[1].cached_tokens > P
    assert warm[2].cached_tokens >= warm[1].cached_tokens  # block-rounded
    # replaying the same session is a full-history hit, still identical
    warm2, _ = _play_session(eng_on)
    for c, w in zip(cold, warm2):
        assert c.token_ids == w.token_ids
    assert warm2[1].cached_tokens >= warm[1].cached_tokens


def test_radix_concurrent_batch_admissions_identity(eng_off, eng_on):
    """Two requests batched TOGETHER both match tree chains (the pinned
    prefix at least) and share blocks read-only while decoding
    concurrently — still token-identical to the cold engine."""
    tok = eng_on.tokenizer
    prompts = [
        tok.encode(render_prompt("scroll down two pages then go back", {}),
                   bos=True),
        tok.encode(render_prompt("summarize this page for me please", {}),
                   bos=True),
    ]
    cold = _run(eng_off, prompts)
    warm = _run(eng_on, prompts)   # seeds the tree
    warm2 = _run(eng_on, prompts)  # both admissions hit concurrently
    for c, w, w2 in zip(cold, warm, warm2):
        assert c.error is None and w.error is None and w2.error is None
        assert c.token_ids == w.token_ids == w2.token_ids
    assert all(r.cached_tokens > len(eng_on.prefix_ids) for r in warm2)


def test_radix_insert_on_release_and_block_sharing(eng_on):
    """A released request's chain survives in the tree (its blocks stay
    resident under the tree's ref), and a warm admission physically shares
    them: same pool blocks, refcount > 1."""
    base_nodes = sum(t.nodes for t in eng_on.radix)
    ids = eng_on.tokenizer.encode(
        render_prompt("take a screenshot of this page", {}), bos=True)
    r = _run(eng_on, [ids])[0]
    assert r.error is None
    assert sum(t.nodes for t in eng_on.radix) > base_nodes
    # no live slots, but the chain's full blocks are tree-resident
    full = (len(ids) + len(r.token_ids)) // eng_on.block_size
    assert eng_on.allocator.blocks_in_use >= full
    # warm rerun: during admission the matched blocks are multi-owner
    r2 = _run(eng_on, [ids])[0]
    assert r2.token_ids == r.token_ids
    assert r2.cached_tokens >= full * eng_on.block_size


SESSIONS = [
    TURNS,
    [("navigate to example dot com", {}),
     ("take a screenshot of this page", {"last_url": "example.com"})],
    [("filter results under one hundred dollars", {}),
     ("extract the product table", {"last_query": "deals"})],
]


def test_radix_mid_chain_eviction_between_turns_identity(eng_off):
    """A deliberately undersized pool forces eviction of session chains
    between turns (distinct sessions pile divergent branches into the
    tree); admissions just match shorter (or no) chains and re-prefill —
    output stays token-identical and nothing double-frees."""
    eng = _paged(True, pool_blocks=10)
    install_prompt_prefix(eng)
    for turns in SESSIONS:
        cold, _ = _play_session(eng_off, turns=turns)
        warm, _ = _play_session(eng, turns=turns)
        for c, w in zip(cold, warm):
            assert c.token_ids == w.token_ids
    assert sum(t.evictions for t in eng.radix) > 0, \
        "pool was sized to force eviction churn"
    # refcount hygiene: with no slots live, everything resident is owned
    # by the tree (pinned prefix included)
    assert eng.allocator.blocks_in_use == sum(t.nodes for t in eng.radix)


def test_radix_eviction_never_frees_live_or_pinned(eng_on):
    """Direct contract probe on a live engine tree: evict() with a huge
    demand only reclaims unreferenced leaves — the pinned root chain and
    anything a caller still refs survive."""
    tree = eng_on.radix[0]
    alloc = eng_on.allocator
    pin_blocks = eng_on._prefix_blocks[0]
    ids = eng_on.tokenizer.encode(
        render_prompt("scroll down two pages", {}), bos=True)
    chain, matched = tree.match(ids)
    before = {b: alloc.refcount(b) for b in chain + pin_blocks}
    tree.evict(10_000)
    for b in chain + pin_blocks:
        assert alloc.refcount(b) == before[b] >= 1
    if chain:
        alloc.free(chain)


def test_prefill_split_and_metrics(eng_on):
    """cached_tokens + computed-only prefill_ms ride GenerationResult, and
    the radix/paged gauges + counters are exported."""
    from tpu_voice_agent.serve.paged import record_pool_gauges
    from tpu_voice_agent.serve.radix import record_radix_gauges
    from tpu_voice_agent.utils import get_metrics

    ids = eng_on.tokenizer.encode(
        render_prompt("filter results under one hundred dollars", {}), bos=True)
    r1 = _run(eng_on, [ids])[0]
    r2 = _run(eng_on, [ids])[0]
    assert r1.token_ids == r2.token_ids
    assert r2.cached_tokens >= r1.cached_tokens > 0
    assert r2.prefill_ms > 0.0
    record_pool_gauges(eng_on.allocator)
    record_radix_gauges(eng_on.radix)
    snap = get_metrics().snapshot()
    assert snap["gauges"]["radix.nodes"] > 0
    assert 0.0 < snap["gauges"]["radix.hit_rate"] <= 1.0
    assert snap["gauges"]["paged.kv_blocks_shared"] >= 0.0
    assert snap["counters"]["radix.cached_tokens"] > 0


# ---------------------------------------------------------------- sessions


def test_session_transcripts_strict_token_extension(eng_on):
    tok = eng_on.tokenizer
    t = SessionTranscripts(tok, max_sessions=2)
    p1 = t.prompt_for("s1", "search for cats", {})
    assert p1 == render_prompt("search for cats", {})  # turn 1: stateless
    gen = tok.encode('{"version":"1.0"}', bos=False)
    t.record("s1", p1, gen)
    p2 = t.prompt_for("s1", "open the first result", {"last_query": "cats"})
    base = tok.encode(p1, bos=True) + gen
    assert p2[: len(base)] == base  # strict token extension
    # deterministic frame rendering: context key order must not matter
    p2b = t.prompt_for("s1", "open the first result", {"last_query": "cats"})
    assert p2 == p2b
    assert (SessionTranscripts.user_frame("x", {"b": 1, "a": 2})
            == SessionTranscripts.user_frame("x", {"a": 2, "b": 1}))
    # LRU cap: two newer sessions push s1 out -> cold start again
    t.record("s2", "a", [1])
    t.record("s3", "b", [2])
    assert t.prompt_for("s1", "x", {}) == render_prompt("x", {})


def test_session_parser_radix_reuse_and_two_phase(eng_on):
    """Service integration: the session-aware BatchedEngineParser renders
    strict-extension prompts, warm turns report more cached tokens, and a
    speculative turn commits (cached plan, zero decode) on the matching
    final or is silently superseded."""
    from tpu_voice_agent.services.brain import BatchedEngineParser
    from tpu_voice_agent.utils.tracing import pop_stage_notes

    p = BatchedEngineParser(eng_on, chunk_steps=16, max_new_tokens=48,
                            session_aware=True)
    try:
        pop_stage_notes()
        p.parse("search for cats", {}, session_id="it1")
        n1 = pop_stage_notes()
        p.parse("open the first result", {"last_query": "cats"}, session_id="it1")
        n2 = pop_stage_notes()
        assert n2["cached_tokens"] > n1["cached_tokens"] > 0
        # two-phase: speculative decode, then the matching final commits
        spec = p.parse("sort these by price", {"last_query": "cats"},
                       session_id="it1", speculative=True)
        pop_stage_notes()
        final = p.parse("sort these by price", {"last_query": "cats"},
                        session_id="it1")
        notes = pop_stage_notes()
        assert final.model_dump() == spec.model_dump()
        assert notes.get("cached_tokens", 0) > 0  # replayed from the spec turn
        # a mismatched final supersedes the pending turn instead of
        # delivering it
        spec2 = p.parse("scroll down", {}, session_id="it1", speculative=True)
        other = p.parse("go back", {}, session_id="it1")
        assert "it1" not in p._pending
        assert other is not spec2
    finally:
        p.close()


def test_stateless_parser_contract_unchanged(eng_off):
    """session_aware off: parse(text, context) works positionally (the
    pre-radix contract build_app relies on when wants_session is False)."""
    from tpu_voice_agent.services.brain import BatchedEngineParser

    p = BatchedEngineParser(eng_off, chunk_steps=16, max_new_tokens=48)
    try:
        assert p.wants_session is False
        r = p.parse("take a screenshot", {})
        assert r.confidence >= 0.0
    finally:
        p.close()
