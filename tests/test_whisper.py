"""Whisper model correctness: encoder shapes, decoder cache equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.whisper import (
    PRESETS,
    WhisperConfig,
    compute_cross_kv,
    decoder_forward,
    encoder_forward,
    init_params,
    init_self_cache,
    param_count,
)

CFG = WhisperConfig(
    vocab_size=64, d_model=64, n_heads=4, enc_layers=2, dec_layers=2,
    max_audio_frames=64, max_text_len=32,
)


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    mel = jax.random.normal(jax.random.PRNGKey(1), (1, CFG.max_audio_frames, CFG.n_mels))
    enc = encoder_forward(params, CFG, mel)
    cross = compute_cross_kv(params, CFG, enc)
    mask = jnp.ones((1, enc.shape[1]), dtype=bool)
    return params, enc, cross, mask


def test_encoder_halves_time_axis(setup):
    _, enc, _, _ = setup
    assert enc.shape == (1, CFG.max_audio_frames // 2, CFG.d_model)
    assert np.isfinite(np.asarray(enc)).all()


def test_cross_kv_shape(setup):
    _, enc, cross, _ = setup
    assert cross["k"].shape == (CFG.dec_layers, 1, enc.shape[1], CFG.n_heads, CFG.head_dim)


def test_decoder_incremental_matches_teacher_forced(setup):
    params, _, cross, mask = setup
    T = 10
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (1, T)), jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]

    cache = init_self_cache(CFG, 1, dtype=jnp.float32)
    full, _ = decoder_forward(params, CFG, tokens, positions, cache, cross, mask)

    cache = init_self_cache(CFG, 1, dtype=jnp.float32)
    steps = []
    for t in range(T):
        lg, cache = decoder_forward(
            params, CFG, tokens[:, t : t + 1], positions[:, t : t + 1], cache, cross, mask
        )
        steps.append(lg[:, 0])
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(jnp.stack(steps, 1)), rtol=2e-4, atol=2e-4
    )


def test_encoder_mask_hides_padding(setup):
    """Cross-attention must ignore masked encoder frames entirely."""
    params, enc, cross, _ = setup
    half = enc.shape[1] // 2
    mask_half = jnp.arange(enc.shape[1])[None, :] < half

    # corrupt the masked-out frames of the cross K/V; logits must not change
    corrupted = {
        "k": cross["k"].at[:, :, half:].set(99.0),
        "v": cross["v"].at[:, :, half:].set(-99.0),
    }
    tok = jnp.zeros((1, 1), jnp.int32)
    pos = jnp.zeros((1, 1), jnp.int32)
    a, _ = decoder_forward(params, CFG, tok, pos, init_self_cache(CFG, 1, dtype=jnp.float32),
                           cross, mask_half)
    b, _ = decoder_forward(params, CFG, tok, pos, init_self_cache(CFG, 1, dtype=jnp.float32),
                           corrupted, mask_half)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_large_v3_param_scale():
    from dataclasses import replace

    cfg = replace(PRESETS["whisper-large-v3"], vocab_size=51_866)
    assert 1.3e9 < param_count(cfg) < 1.8e9
