"""Grounding trainer + quality proof (round-4 VERDICT next #4).

Until round 5 grounding was the one model family with zero semantic
evidence: bench_grounding grounded random noise with random-init weights,
and the executor's VL click fallback had never been shown to click the
right thing. These tests prove each link:

- the synthetic page generator yields disjoint, regex/grammar-valid rows
- a scaled-down training run learns through the REAL GroundingEngine, and
  the checkpoint round-trips orbax save/load
- (slow, committed-checkpoint) held-out layouts score point-in-bbox far
  above chance, and the executor service resolves a click the DOM scan
  cannot via the trained grounder over a real rendered screenshot

Reference parity: augments the reference's DOM-scan-only targeting
(apps/executor/src/dom-analyzer.ts:34-448; BASELINE config 5).
"""

import io
import os

import numpy as np
import pytest

from tpu_voice_agent.train import ground


def test_sample_page_disjoint_bboxes_and_bounds():
    rng = np.random.default_rng(7)
    for _ in range(20):
        img, widgets = ground.sample_page(rng)
        assert img.shape == (ground.PAGE, ground.PAGE, 3)
        assert img.dtype == np.uint8
        for i, a in enumerate(widgets):
            ax, ay, aw, ah = a["bbox"]
            assert 0 <= ax and ax + aw <= ground.PAGE
            assert 0 <= ay and ay + ah <= ground.PAGE
            for b in widgets[i + 1:]:
                bx, by, bw, bh = b["bbox"]
                # disjoint with the 8px margin used by the generator
                assert (ax + aw < bx or bx + bw < ax
                        or ay + ah < by or by + bh < ay)


def test_build_rows_targets_are_grammar_reachable():
    """Every teacher target must be emittable by the point-grammar FSM —
    mass trained onto unreachable sequences would never decode."""
    from tpu_voice_agent.serve.grounding import build_grounding_fsm

    tok, fsm = build_grounding_fsm()
    _, instrs, targets, _ = ground.build_rows(12, seed=3)
    for t in targets:
        ids = tok.encode(t, bos=False, eos=False)
        assert tok.decode(ids) == t
        assert fsm.walk(ids) >= 0, f"target left the grammar: {t}"


def test_train_smoke_and_ckpt_roundtrip(tmp_path):
    """Three steps of the real trainer, orbax round trip, and a ground()
    call through the real engine (random-quality output; shape contract)."""
    cfg, params, stats = ground.train_grounding(steps=3, batch=4)
    assert stats["first_loss"] > 0
    path = ground.save_ground_ckpt(str(tmp_path), cfg, params, stats)
    loaded = ground.load_ground_ckpt(str(tmp_path))
    assert loaded is not None
    lcfg, lparams = loaded
    assert lcfg == cfg
    eng = ground.grounding_engine_from(lcfg, lparams)
    rng = np.random.default_rng(0)
    img, widgets = ground.sample_page(rng)
    res = eng.ground(img, "click the " + widgets[0]["cls"], max_new_tokens=32)
    assert 0 <= res.x_norm <= 999 and 0 <= res.y_norm <= 999


COMMITTED = os.path.join(os.path.dirname(__file__), "..", "checkpoints")
# existence probe only — restoring the full checkpoint at collection time
# would tax every pytest run that merely collects this module
HAS_CKPT = os.path.exists(os.path.join(COMMITTED, ground.GROUND_CKPT, "meta.json"))


@pytest.mark.slow
@pytest.mark.skipif(not HAS_CKPT, reason="no committed grounding-tiny ckpt")
def test_committed_grounding_accuracy_beats_chance():
    """The committed checkpoint must ground held-out layouts (and one
    never-trained instruction template) point-in-bbox far above chance
    (~4% for a uniform point; ~33% for center-of-random-widget)."""
    cfg, params = ground.load_ground_ckpt(COMMITTED)
    eng = ground.grounding_engine_from(cfg, params)
    scores = ground.score_grounding(eng, n_pages=30)
    assert scores["pages"] >= 25
    # committed curriculum checkpoint measures ~0.30 point-in-bbox over
    # held-out layouts (chance ~0.036; class-match 0.725; single-widget
    # pages ~0.67) — the bar is set with eval-noise headroom below the
    # measured level so a REGRESSION fails, not a noisy rerun
    assert scores["point_in_bbox"] >= 0.15, scores
    assert scores["point_in_bbox"] > 4 * scores["chance"], scores
    assert scores["label_match"] >= 0.5, scores


@pytest.mark.slow
@pytest.mark.skipif(not HAS_CKPT, reason="no committed grounding-tiny ckpt")
def test_executor_vl_fallback_resolves_click_dom_cannot(tmp_path):
    """End to end through the executor service: a click whose text matches
    NO analyzed element routes through the trained grounder over the real
    rendered screenshot and snaps onto the correct DOM selector — the
    augmentation the reference's DOM-only analyzer cannot do."""
    import httpx
    from PIL import Image

    from tpu_voice_agent.services.executor.grounding import TPUGrounder
    from tpu_voice_agent.services.executor.page import FakeElement, FakePage
    from tpu_voice_agent.services.executor.server import build_app
    from tpu_voice_agent.services.executor.session import SessionManager

    from tests.http_helper import AppServer

    # deterministic page whose render the trained model has never seen
    rng = np.random.default_rng(20260736)
    img, widgets = ground.sample_page(rng)
    target = next(w for w in widgets if "button" in w["cls"])
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")

    elements = []
    for i, w in enumerate(widgets):
        x, y, bw, bh = w["bbox"]
        elements.append(FakeElement(
            f"#w{i}", tag="button", role="button",
            # element text deliberately does NOT contain the instruction
            # text, so the interpreter's analyzed-text click misses
            text=w["cls"].split()[0].capitalize(),
            name=w["cls"], bbox=(float(x), float(y), float(bw), float(bh))))
    page = FakePage(elements=elements, url="https://demo.local/g",
                    screenshot_png=buf.getvalue())
    manager = SessionManager(page_factory=lambda: page,
                             artifacts_root=str(tmp_path / "a"),
                             uploads_dir=str(tmp_path / "u"))
    grounder = TPUGrounder(ckpt_dir=COMMITTED)

    instruction = "click the " + target["cls"]
    with AppServer(build_app(manager, grounder=grounder)) as srv:
        r = httpx.post(srv.url + "/execute", json={
            "intents": [{"type": "click", "args": {"text": instruction}}],
        }, timeout=120)
    assert r.status_code == 200
    step = r.json()["results"][0]
    assert step["ok"], step.get("error")
    sel = "#w" + str(widgets.index(target))
    assert step["data"]["by"] == "grounded_selector", step["data"]
    assert step["data"]["selector"] == sel, (step["data"], target)
