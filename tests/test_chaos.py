"""Fault containment inside the inference plane (ISSUE 7) — FAST tier.

The non-negotiable contract, drilled differentially like PR 3/4/5's
identity tests: for EVERY injected fault (NaN logits, prefill exception,
dead FSM state, mid-decode cancellation, expired deadline) the poisoned
request fails alone with a typed error, its KV blocks return to the pool,
its chain NEVER enters the radix tree, and batch-mates' outputs are
TOKEN-IDENTICAL to an undisturbed run. Plus: the repeat-offender
quarantine, the deterministic chaos layer itself, the stalled-step
watchdog's warm restart, and a 200-request mixed ok/poisoned/cancelled
pool-accounting fuzz that must leak zero blocks.
"""

import random
import time

import pytest

from tpu_voice_agent.serve import DecodeEngine, PagedDecodeEngine
from tpu_voice_agent.serve.colocate import ColocatedServing
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import install_prompt_prefix
from tpu_voice_agent.utils import chaos, get_metrics
from tpu_voice_agent.utils.chaos import Chaos, ChaosError
from tpu_voice_agent.utils.resilience import Deadline

BUCKETS = (128, 256, 512, 1024, 2048)
PROMPTS = [
    "search for laptops under 1000",
    "upload my resume and submit",
    "take a screenshot of this page",
]


@pytest.fixture(autouse=True)
def _chaos_hygiene():
    chaos.reset()
    yield
    chaos.reset()


def _counter(name: str) -> float:
    return get_metrics().snapshot()["counters"].get(name, 0.0)


# ---------------------------------------------------------------- chaos unit


def test_chaos_off_by_default(monkeypatch):
    monkeypatch.delenv("CHAOS_FAULTS", raising=False)
    chaos.reset()
    assert not chaos.get_chaos().enabled
    assert not chaos.chaos_fire("nan_logits")


def test_chaos_deterministic_and_seeded():
    a = Chaos("nan_logits:0.3", seed=5)
    b = Chaos("nan_logits:0.3", seed=5)
    seq_a = [a.fire("nan_logits") for _ in range(64)]
    seq_b = [b.fire("nan_logits") for _ in range(64)]
    assert seq_a == seq_b, "same spec+seed must replay identically"
    assert any(seq_a) and not all(seq_a)
    c = Chaos("nan_logits:0.3", seed=6)
    assert [c.fire("nan_logits") for _ in range(64)] != seq_a


def test_chaos_nth_fires_exactly_once():
    c = Chaos("alloc_fail@3")
    assert [c.fire("alloc_fail") for _ in range(6)] == [
        False, False, True, False, False, False]


def test_chaos_unknown_point_rejected():
    with pytest.raises(ValueError, match="unknown chaos point"):
        Chaos("tyop_fault:0.5")


# ------------------------------------------------------------- shared engine


@pytest.fixture(scope="module")
def eng():
    e = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=3,
                          prefill_buckets=BUCKETS, radix_enable=False)
    install_prompt_prefix(e)
    return e


@pytest.fixture(scope="module")
def clean(eng):
    """The undisturbed reference run every fault drill diffs against."""
    return ContinuousBatcher(eng, chunk_steps=8,
                             max_new_tokens=48).generate_many(PROMPTS)


def _run_with_fault(eng, spec: str):
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=48)
    chaos.configure(spec)
    try:
        return b, b.generate_many(PROMPTS)
    finally:
        chaos.reset()


def _assert_contained(eng, clean, res, victim: int, err_prefix: str):
    """The containment contract: victim fails typed, batch-mates are
    token-identical, no pool blocks leak past the resident prefix."""
    assert res[victim].error is not None and \
        res[victim].error.startswith(err_prefix), res[victim].error
    for i in range(len(clean)):
        if i != victim:
            assert res[i].error is None, res[i].error
            assert res[i].token_ids == clean[i].token_ids, \
                f"batch-mate {i} diverged from the undisturbed run"
    assert eng.allocator.blocks_in_use == len(eng._prefix_blocks[0]), \
        "poisoned/cancelled request leaked pool blocks"


# ------------------------------------------------- differential isolation


def test_nan_logits_quarantines_slot_batch_mates_identical(eng, clean):
    before = _counter("scheduler.slots_quarantined")
    b, res = _run_with_fault(eng, "nan_logits@2")  # 2nd admission poisoned
    _assert_contained(eng, clean, res, victim=1, err_prefix="poisoned: non-finite")
    assert _counter("scheduler.slots_quarantined") == before + 1
    assert b.quarantined() == []  # one offense < QUARANTINE_AFTER


def test_dead_fsm_state_quarantines_slot(eng, clean):
    _, res = _run_with_fault(eng, "dead_fsm@2")
    _assert_contained(eng, clean, res, victim=1,
                      err_prefix="poisoned: grammar dead state")


def test_prefill_exception_fails_alone(eng, clean):
    before = _counter("scheduler.prefill_faults")
    _, res = _run_with_fault(eng, "prefill_exc@2")
    _assert_contained(eng, clean, res, victim=1, err_prefix="chaos: injected")
    assert _counter("scheduler.prefill_faults") == before + 1


def test_mid_decode_cancel_releases_slot_batch_mates_identical(eng, clean):
    before = _counter("scheduler.cancelled")
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=48)
    rids = [b.submit(p) for p in PROMPTS]
    b.step()  # all three admitted and one chunk in
    assert b.cancel(rids[1], "test disconnect")
    b.run_until_done()
    res = [b.results.pop(r) for r in rids]
    _assert_contained(eng, clean, res, victim=1, err_prefix="cancelled:")
    assert _counter("scheduler.cancelled") == before + 1


def test_deadline_sheds_at_dequeue_and_cancels_mid_decode(eng):
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=48)
    before_shed = _counter("scheduler.shed_expired")
    # expired before dequeue: never occupies a slot
    rid_dead = b.submit(PROMPTS[0], deadline=Deadline.after(0.0))
    # expires mid-decode: admitted, then evicted at a chunk boundary
    rid_mid = b.submit(PROMPTS[1], deadline=Deadline.after(0.25))
    rid_ok = b.submit(PROMPTS[2])
    b.step()
    time.sleep(0.3)
    b.run_until_done()
    assert b.results.pop(rid_dead).error.startswith("shed: deadline expired")
    assert _counter("scheduler.shed_expired") == before_shed + 1
    mid = b.results.pop(rid_mid)
    assert mid.error is not None and mid.error.startswith("cancelled: deadline")
    assert b.results.pop(rid_ok).error is None
    assert eng.allocator.blocks_in_use == len(eng._prefix_blocks[0])


def test_repeat_offender_quarantined_and_surfaced(eng):
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=48)
    for _ in range(2):  # QUARANTINE_AFTER default
        chaos.configure("nan_logits@1")
        r = b.generate_many([PROMPTS[0]])[0]
        chaos.reset()
        assert r.error.startswith("poisoned:")
    before = _counter("scheduler.quarantine_rejected")
    r = b.generate_many([PROMPTS[0]])[0]  # no chaos armed — refused at submit
    assert r.error.startswith("quarantined:"), r.error
    assert _counter("scheduler.quarantine_rejected") == before + 1
    q = b.quarantined()
    assert q and q[0]["count"] == 2 and q[0]["rejected"] == 1
    assert PROMPTS[0][:20] in q[0]["preview"]
    # a different prompt still serves (quarantine is per-fingerprint)
    assert b.generate_many([PROMPTS[2]])[0].error is None


# ------------------------------------------------------------ radix hygiene


@pytest.fixture(scope="module")
def eng_radix():
    e = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=2,
                          prefill_buckets=BUCKETS, radix_enable=True)
    install_prompt_prefix(e)
    return e


def test_poisoned_chain_never_enters_radix(eng_radix):
    b = ContinuousBatcher(eng_radix, chunk_steps=8, max_new_tokens=48)
    assert b.generate_many([PROMPTS[0]])[0].error is None
    inserts = sum(t.inserts for t in eng_radix.radix)
    nodes = sum(t.nodes for t in eng_radix.radix)
    chaos.configure("nan_logits@1")
    r = b.generate_many([PROMPTS[1]])[0]
    chaos.reset()
    assert r.error.startswith("poisoned:")
    assert sum(t.inserts for t in eng_radix.radix) == inserts, \
        "a poisoned generation must never become a warm prefix"
    assert sum(t.nodes for t in eng_radix.radix) == nodes


def test_cancelled_chain_never_enters_radix(eng_radix):
    b = ContinuousBatcher(eng_radix, chunk_steps=8, max_new_tokens=48)
    inserts = sum(t.inserts for t in eng_radix.radix)
    rid = b.submit(PROMPTS[2])
    b.step()
    b.cancel(rid, "gone")
    b.run_until_done()
    assert b.results.pop(rid).error.startswith("cancelled:")
    assert sum(t.inserts for t in eng_radix.radix) == inserts


# ----------------------------------------------------------- warm restart


def test_warm_restart_keeps_prefix_and_token_identity():
    from tpu_voice_agent.services.prompts import render_prompt

    e = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=2,
                          prefill_buckets=BUCKETS, radix_enable=True)
    install_prompt_prefix(e)
    prompt = render_prompt(PROMPTS[0], {})  # starts with the cached prefix
    b = ContinuousBatcher(e, chunk_steps=8, max_new_tokens=48)
    r1 = b.generate_many([prompt])[0]
    assert r1.error is None and r1.cached_tokens > 0  # prefix served warm
    b.reset()
    e.warm_restart()
    # only the re-reserved prefix survives; the tree is pinned-root-only
    assert e.allocator.blocks_in_use == len(e._prefix_blocks[0])
    assert all(t.nodes == len(e._prefix_blocks[0]) for t in e.radix)
    r2 = ContinuousBatcher(e, chunk_steps=8,
                           max_new_tokens=48).generate_many([prompt])[0]
    assert r2.error is None
    assert r2.token_ids == r1.token_ids, \
        "post-restart decode diverged: prefix KV was not preserved"
    assert r2.cached_tokens == r1.cached_tokens


def test_stall_watchdog_warm_restarts_and_fails_inflight_fast(monkeypatch):
    monkeypatch.setenv("CHAOS_STALL_S", "2.0")
    e = DecodeEngine(preset="test-tiny", max_len=1024, batch_slots=2,
                     prefill_buckets=(128, 256, 512))
    b = ContinuousBatcher(e, chunk_steps=8, max_new_tokens=24)
    # pre-warm the compiled programs: a first-compile (seconds on CPU) must
    # not read as a stall to the tight drill threshold below
    assert b.generate_many([PROMPTS[1]])[0].token_ids
    co = ColocatedServing(None, b)
    before = _counter("engine.restarts")
    chaos.configure("stall_step@1")
    co.start()
    co.start_watchdog(interval_s=0.05, stall_s=0.5)
    try:
        fut = co.submit_parse(PROMPTS[0])
        with pytest.raises(RuntimeError, match="stalled"):
            fut.result(timeout=10)  # failed FAST, not after the stall ends
        assert _counter("engine.restarts") == before + 1
        chaos.reset()
        # the replacement loop serves on the warm-restarted engine
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not co.healthy():
            time.sleep(0.01)
        assert co.healthy()
        res = co.submit_parse(PROMPTS[1]).result(timeout=30)
        assert res.error is None and res.token_ids
    finally:
        co.stop()


# -------------------------------------------------- disconnect cancellation


def test_client_disconnect_cancels_in_flight_decode():
    """The full chain: TCP client vanishes mid-/parse -> aiohttp cancels
    the handler (opt-in flag) -> RequestContext fires the registered
    canceller -> colocate tombstones -> scheduler evicts the slot at the
    next chunk boundary, releasing blocks instead of decoding the full
    budget for a dead socket."""
    import socket

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import BatchedEngineParser, build_app

    e = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=2,
                          prefill_buckets=BUCKETS, radix_enable=False)
    install_prompt_prefix(e)
    parser = BatchedEngineParser(e, chunk_steps=4, max_new_tokens=512)
    srv = AppServer(build_app(parser)).__enter__()
    try:
        before = _counter("scheduler.cancelled")
        body = json_bytes = (
            b'{"text": "search for mechanical keyboards", "context": {}}')
        req = (b"POST /parse HTTP/1.1\r\nHost: 127.0.0.1\r\n"
               b"Content-Type: application/json\r\n"
               b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n"
               + json_bytes)
        before_chunks = _counter("scheduler.chunks")
        s = socket.create_connection(("127.0.0.1", srv.port))
        s.sendall(req)
        # close the moment decode is demonstrably in flight (first chunk
        # dispatched) — a fixed sleep races a warm-cache decode of the
        # whole 512-token budget
        start_wait = time.monotonic() + 15
        while time.monotonic() < start_wait and \
                _counter("scheduler.chunks") == before_chunks:
            time.sleep(0.01)
        assert _counter("scheduler.chunks") > before_chunks, "decode never started"
        s.close()  # client gone — no response will ever be read
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                _counter("scheduler.cancelled") == before:
            time.sleep(0.05)
        assert _counter("scheduler.cancelled") == before + 1, \
            "disconnect did not cancel the in-flight decode"
        assert e.allocator.blocks_in_use == len(e._prefix_blocks[0])
    finally:
        srv.__exit__(None, None, None)
        parser.close()


# -------------------------------------------------------------- pool fuzz


def test_pool_accounting_fuzz_zero_leaks_after_200_mixed_requests():
    """ISSUE 7 satellite: 200 mixed ok/poisoned/cancelled/expired requests
    under probabilistic chaos — every terminal path must return its blocks;
    the pool ends exactly at the resident prefix."""
    e = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=3,
                          prefill_buckets=BUCKETS, radix_enable=False)
    install_prompt_prefix(e)
    b = ContinuousBatcher(e, chunk_steps=4, max_new_tokens=4)
    rng = random.Random(11)
    chaos.configure("nan_logits:0.15,prefill_exc:0.1,alloc_fail:0.05", seed=11)
    try:
        outcomes = {"ok": 0, "error": 0}
        submitted = 0
        while submitted < 200:
            wave = []
            for _ in range(rng.randint(2, 6)):
                # unique suffix: quarantine is per-fingerprint and must not
                # kick in for distinct prompts
                p = f"{PROMPTS[submitted % 3]} v{submitted}"
                dl = Deadline.after(0.0) if rng.random() < 0.1 else None
                wave.append(b.submit(p, deadline=dl))
                submitted += 1
            b.step()
            if wave and rng.random() < 0.3:
                b.cancel(wave[rng.randrange(len(wave))], "fuzz")
            b.run_until_done()
            for rid in wave:
                r = b.results.pop(rid)
                outcomes["ok" if r.error is None else "error"] += 1
    finally:
        chaos.reset()
    assert sum(outcomes.values()) == 200
    assert outcomes["ok"] > 0 and outcomes["error"] > 0, \
        f"fuzz must exercise both paths, got {outcomes}"
    assert e.allocator.blocks_in_use == len(e._prefix_blocks[0]), \
        f"leaked blocks: {e.allocator.blocks_in_use} in use, " \
        f"prefix is {len(e._prefix_blocks[0])}"
    # refcount hygiene: the resident prefix blocks are exactly once-owned
    for blk in e._prefix_blocks[0]:
        assert e.allocator.refcount(blk) == 1
