"""Fleet telemetry plane (ISSUE 14): time-series rings, peer-relative
gray-failure detection, and the fleet dashboard.

Fast-tier coverage for tpu_voice_agent/utils/timeseries.py,
services/replicaset.py's fleet detector, the router's fleet scrape, and
tools/fleetview.py:

- ring bounds + monotonic seqs + the ``?since=`` delta contract (direct
  and over HTTP against a real brain app)
- counter->rate and histogram->window-mean derivation (deterministic
  clock), counter-reset clamping, gauge-prefix filtering
- a thread-safety hammer: concurrent metric writers + ring readers
  against the live sampler thread
- MAD outlier-score units: direction awareness, deviation floors,
  min-peers gating
- the gray enter/exit drill against fake replicas: sticky sessions never
  move, new sessions avoid the gray member, recovery is symmetric, the
  flight dump carries the peer evidence
- ``replica_degrade`` e2e through the REAL router over real brain apps:
  detection, the frozen dump, and fleetview --file rendering it
- the router's clock-skew estimate + traceview's skew-corrected
  multi-service dump merge
- the swarm sampler reading /debug/timeseries deltas
- fleetview --self-test (tier-1 wiring)
"""

import json
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest
from aiohttp import web

from tests.http_helper import AppServer
from tpu_voice_agent.services.brain import RuleBasedParser
from tpu_voice_agent.services.brain import build_app as build_brain
from tpu_voice_agent.services.replicaset import (
    fleet_outlier_scores,
    reduce_window,
    signal_values,
)
from tpu_voice_agent.services.router import BrainRouter, _weight
from tpu_voice_agent.services.router import build_app as build_router
from tpu_voice_agent.utils import Metrics, TimeSeriesRing, get_metrics
from tpu_voice_agent.utils.tracing import get_flight_recorder

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import fleetview  # noqa: E402
import traceview  # noqa: E402


def _get(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _post(url: str, body: dict, timeout: float = 20.0):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


# ------------------------------------------------------------------- ring


def test_ring_bounds_and_since_contract():
    src = Metrics()
    clock = iter(float(i) for i in range(100))
    ring = TimeSeriesRing("t", sources=(src,), interval_s=60.0,
                          max_samples=8, clock=lambda: next(clock))
    for _ in range(20):
        ring.sample_once()
    state = ring.state()
    assert len(state["samples"]) == 8, "ring must trim to max_samples"
    seqs = [s["seq"] for s in state["samples"]]
    assert seqs == list(range(12, 20)), "seqs survive trimming, monotonic"
    assert state["next_seq"] == 20
    # the delta contract: since=N returns samples with seq >= N; a cursor
    # pointing past the end returns nothing; a trimmed-away cursor
    # returns what is still retained
    assert [s["seq"] for s in ring.since(18)] == [18, 19]
    assert ring.since(20) == []
    assert [s["seq"] for s in ring.since(0)] == seqs
    assert "now_s" in state and state["service"] == "t"


def test_rate_and_hist_derivation():
    src = Metrics()
    t = {"now": 100.0}
    ring = TimeSeriesRing("t", sources=(src,), interval_s=60.0,
                          max_samples=16, clock=lambda: t["now"])
    src.inc("c.total", 10.0)
    src.observe_ms("h.lat", 10.0)
    first = ring.sample_once()
    assert first["rates"] == {} and first["hist"] == {}, \
        "first sample has no baseline"
    # +5 counts and 3 hist events over 2 seconds
    src.inc("c.total", 5.0)
    for ms in (10.0, 20.0, 30.0):
        src.observe_ms("h.lat", ms)
    src.set_gauge("g.x", 0.7)
    t["now"] = 102.0
    s = ring.sample_once()
    assert s["dt_s"] == 2.0
    assert s["rates"]["c.total"] == pytest.approx(2.5)
    assert s["hist"]["h.lat"]["ms_per"] == pytest.approx(20.0)
    assert s["hist"]["h.lat"]["per_s"] == pytest.approx(1.5)
    assert s["gauges"]["g.x"] == 0.7
    # a counter stepping BACKWARDS (restarted registry) reads rate 0,
    # never negative
    src2 = Metrics()
    ring.sources = (src2,)
    src2.inc("c.total", 1.0)
    t["now"] = 103.0
    s2 = ring.sample_once()
    assert s2["rates"]["c.total"] == 0.0


def test_gauge_prefix_filter():
    src = Metrics()
    src.set_gauge("keep.a", 1.0)
    src.set_gauge("keep.b", 2.0)
    src.set_gauge("drop.c", 3.0)
    ring = TimeSeriesRing("t", sources=(src,), interval_s=60.0,
                          max_samples=4, gauge_prefixes=("keep.",))
    s = ring.sample_once()
    assert set(s["gauges"]) == {"keep.a", "keep.b"}


def test_source_precedence_local_wins():
    glob, local = Metrics(), Metrics()
    glob.set_gauge("x", 1.0)
    local.set_gauge("x", 2.0)
    ring = TimeSeriesRing("t", sources=(glob, local), interval_s=60.0)
    assert ring.sample_once()["gauges"]["x"] == 2.0


def test_ring_thread_hammer():
    """4 metric writers + 2 ring readers against the live sampler thread:
    no exception, bounded ring, strictly monotonic seqs."""
    src = Metrics()
    ring = TimeSeriesRing("t", sources=(src,), interval_s=0.005,
                          max_samples=16)
    stop = threading.Event()
    errors: list[BaseException] = []

    def writer(i: int) -> None:
        try:
            n = 0
            while not stop.is_set():
                src.inc(f"w{i}.count")
                src.set_gauge(f"w{i}.gauge", n)
                src.observe_ms(f"w{i}.lat", n % 50)
                n += 1
        except BaseException as e:  # pragma: no cover - diagnostics
            errors.append(e)

    def reader() -> None:
        try:
            while not stop.is_set():
                st = ring.state(since=0)
                assert len(st["samples"]) <= 16
                seqs = [s["seq"] for s in st["samples"]]
                assert seqs == sorted(set(seqs))
        except BaseException as e:  # pragma: no cover - diagnostics
            errors.append(e)

    ring.start()
    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for th in threads:
        th.start()
    time.sleep(0.4)
    stop.set()
    for th in threads:
        th.join(timeout=5)
    ring.stop()
    assert not errors, errors
    assert ring.state()["next_seq"] > 10


def test_since_contract_over_http(monkeypatch):
    monkeypatch.setenv("TS_INTERVAL_S", "0.05")
    with AppServer(build_brain(RuleBasedParser())) as srv:
        _post(srv.url + "/parse", {"text": "scroll down", "context": {}})
        time.sleep(0.3)
        body = _get(srv.url + "/debug/timeseries")
        assert body["service"] == "brain" and body["samples"]
        assert isinstance(body["now_s"], float)
        nxt = body["next_seq"]
        assert body["samples"][-1]["seq"] == nxt - 1
        # the cursor: nothing new yet...
        again = _get(srv.url + f"/debug/timeseries?since={nxt}")
        assert all(s["seq"] >= nxt for s in again["samples"])
        # ...until the sampler ticks again
        time.sleep(0.2)
        later = _get(srv.url + f"/debug/timeseries?since={nxt}")
        assert later["samples"] and later["samples"][0]["seq"] >= nxt


# ---------------------------------------------------------------- MAD math


def _readings(**parse_ms_by_member):
    return {m: {"parse_ms": v} for m, v in parse_ms_by_member.items()}


def test_mad_outlier_scores_units():
    # one member far above a tight fleet: huge score, peers near zero
    scores, agg = fleet_outlier_scores(
        _readings(a=10.0, b=11.0, c=10.5, d=300.0), min_peers=3)
    assert scores["d"]["score"] > 10 and scores["d"]["signal"] == "parse_ms"
    assert scores["a"]["score"] < 1 and scores["b"]["score"] < 1
    assert agg["parse_ms"]["n"] == 4
    assert agg["parse_ms"]["median"] == pytest.approx(10.75)
    # direction: parse_ms is worse HIGH — a member far BELOW the median
    # is fast, not gray
    scores, _ = fleet_outlier_scores(
        _readings(a=100.0, b=101.0, c=99.0, d=1.0), min_peers=3)
    assert scores["d"]["score"] == 0.0
    # tokens_per_forward is worse LOW
    tok = {m: {"tokens_per_forward": v}
           for m, v in dict(a=4.0, b=4.2, c=3.9, d=1.0).items()}
    scores, _ = fleet_outlier_scores(tok, min_peers=3)
    assert scores["d"]["score"] > 3 and scores["d"]["signal"] == "tokens_per_forward"
    high = {m: {"tokens_per_forward": v}
            for m, v in dict(a=4.0, b=4.2, c=3.9, d=9.0).items()}
    scores, _ = fleet_outlier_scores(high, min_peers=3)
    assert scores["d"]["score"] == 0.0, "a FASTER drafter is not gray"
    # the deviation floor: a tightly clustered fleet (MAD ~ 0) must not
    # read μs-scale noise as a catastrophic outlier
    scores, _ = fleet_outlier_scores(
        _readings(a=1.000, b=1.001, c=1.002), min_peers=3)
    assert all(v["score"] < 1 for v in scores.values())
    # min_peers: two members cannot name an outlier
    scores, agg = fleet_outlier_scores(_readings(a=1.0, b=500.0), min_peers=3)
    assert agg == {} and all(v["score"] == 0.0 for v in scores.values())


def test_signal_values_and_reduce_window():
    sample = {"gauges": {"slo.brain.p99_ms": 42.0,
                         "paged.kv_utilization": 0.5,
                         "scheduler.tokens_per_forward": 2.5},
              "rates": {"scheduler.slots_quarantined": 0.25},
              "hist": {"brain.parse": {"ms_per": 12.5, "per_s": 3.0},
                       "engine.step.decode": {"ms_per": 4.0, "per_s": 9.0}}}
    vals = signal_values(sample)
    assert vals == {"parse_ms": 12.5, "parse_p99_ms": 42.0,
                    "decode_ms": 4.0, "tokens_per_forward": 2.5,
                    "kv_utilization": 0.5, "quarantine_rate": 0.25}
    # window reduce: mean per signal over the samples that carry it
    s2 = {"gauges": {}, "rates": {},
          "hist": {"brain.parse": {"ms_per": 37.5, "per_s": 1.0}}}
    red = reduce_window([sample, s2])
    assert red["parse_ms"] == pytest.approx(25.0)
    assert red["parse_p99_ms"] == 42.0
    assert reduce_window([]) == {}


def test_gray_hold_expiry_bounds_evidence_starvation():
    """Demotion starves traffic-borne signals (no new sessions -> no
    fwd_ms): a verdict held without scoreable evidence must expire after
    gray_hold_s so the fleet does not permanently lose the replica —
    while evidence still FLOWS, the verdict holds on merit alone."""
    from tpu_voice_agent.services.replicaset import ReplicaSet

    rs = ReplicaSet(["a", "b", "c"], gray_mad=4.0, gray_windows=2,
                    gray_min_peers=3, gray_hold_s=0.05)
    slow = {"a": {"parse_ms": 300.0}, "b": {"parse_ms": 10.0},
            "c": {"parse_ms": 10.0}}
    rs.apply_fleet_window(slow)
    rs.apply_fleet_window(slow)
    ra = rs.replicas[0]
    assert ra.gray and ra.outlier_signal == "parse_ms"
    # evidence keeps flowing and keeps indicting: verdict holds, no clock
    other = {k: {"kv_utilization": 0.1} for k in ("a", "b", "c")}
    rs.apply_fleet_window(slow)
    assert ra.gray and ra.gray_held_since is None
    # now starve parse_ms fleet-wide: carried values keep it scoreable
    # for gray_windows windows (verdict still holds on merit)...
    rs.apply_fleet_window(other)
    rs.apply_fleet_window(other)
    assert ra.gray
    # ...then scoring is impossible: the hold clock arms...
    rs.apply_fleet_window(other)
    assert ra.gray and ra.gray_held_since is not None
    # ...and past gray_hold_s the verdict expires
    time.sleep(0.08)
    rs.apply_fleet_window(other)
    assert not ra.gray and ra.gray_evidence is None


# ----------------------------------------------------- gray drill (fakes)


def _fake_member(name: str, log: list, controls: dict):
    """Brain-contract stand-in with a controllable time-series surface:
    ``controls["parse_ms"]`` is the hist window mean its /debug/timeseries
    reports; ``controls["now_skew_s"]`` shifts its advertised wall clock."""
    rule = RuleBasedParser()
    seq = {"n": 0}

    async def parse(req: web.Request) -> web.Response:
        body = await req.json()
        log.append((name, body.get("session_id")))
        resp = rule.parse(body["text"], body.get("context") or {})
        return web.json_response(json.loads(resp.model_dump_json()))

    async def health(_req: web.Request) -> web.Response:
        return web.json_response({"ok": True, "service": "brain"})

    async def timeseries(req: web.Request) -> web.Response:
        # one fresh sample per scrape: deterministic windows
        s = {"seq": seq["n"], "t_s": time.time(), "dt_s": 0.1,
             "gauges": {}, "rates": {},
             "hist": {"brain.parse": {"ms_per": controls.get("parse_ms", 10.0),
                                      "per_s": 5.0}}}
        seq["n"] += 1
        return web.json_response({
            "service": "brain", "interval_s": 0.1, "max_samples": 240,
            "now_s": time.time() + controls.get("now_skew_s", 0.0),
            "next_seq": seq["n"], "samples": [s]})

    app = web.Application()
    app.router.add_post("/parse", parse)
    app.router.add_get("/health", health)
    app.router.add_get("/debug/timeseries", timeseries)
    return app


def _fleet_ring(n: int, **router_kw):
    logs = [[] for _ in range(n)]
    controls = [{"parse_ms": 10.0} for _ in range(n)]
    servers = [AppServer(_fake_member(f"r{i}", logs[i], controls[i])).__enter__()
               for i in range(n)]
    router_kw.setdefault("probe_s", 0.1)
    router_kw.setdefault("fleet_windows", 2)
    router_kw.setdefault("fleet_min_peers", 3)
    robj = BrainRouter([s.url for s in servers], **router_kw)
    router = AppServer(build_router(robj)).__enter__()
    return router, servers, logs, controls, robj


def _teardown(router, servers):
    router.__exit__(None, None, None)
    for s in servers:
        try:
            s.__exit__(None, None, None)
        except Exception:
            pass


def _sid_homed_on(robj: BrainRouter, idx: int, prefix: str) -> str:
    urls = [r.url for r in robj.replicas]
    for i in range(10_000):
        sid = f"{prefix}{i}"
        if max(range(len(urls)), key=lambda j: _weight(urls[j], sid)) == idx:
            return sid
    raise AssertionError("no session hashed onto the target replica")


def _wait(pred, timeout_s: float = 10.0, step_s: float = 0.05):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(step_s)
    return False


def test_gray_enter_exit_drill():
    get_flight_recorder().rearm()
    router, servers, logs, controls, robj = _fleet_ring(3)
    try:
        victim = 0
        sticky_sid = _sid_homed_on(robj, victim, "sticky")
        _post(router.url + "/parse", {"text": "scroll down",
                                      "session_id": sticky_sid, "context": {}})
        assert any(e[1] == sticky_sid for e in logs[victim])
        # healthy fleet: no gray
        assert _wait(lambda: _get(router.url + "/health")["fleet"]
                     .get("aggregates"), 5.0)
        assert _get(router.url + "/health")["replicas"]["gray"] == 0
        # the victim drifts: parse wall 30x its peers, sustained
        controls[victim]["parse_ms"] = 300.0
        assert _wait(lambda: _get(router.url + "/health")["replicas"]["gray"] == 1), \
            "victim never marked gray"
        h = _get(router.url + "/health")
        detail = {d["url"]: d for d in h["replica_detail"]}
        vurl = robj.replicas[victim].url
        assert detail[vurl]["gray"] and detail[vurl]["state"] == "up", \
            "gray is a demotion, not an eject"
        assert detail[vurl]["outlier_signal"] == "parse_ms"
        assert detail[vurl]["outlier_score"] >= 4.0
        # sticky sessions NEVER move for gray
        before = len(logs[victim])
        st, _ = _post(router.url + "/parse",
                      {"text": "go back", "session_id": sticky_sid,
                       "context": {}})
        assert st == 200 and len(logs[victim]) == before + 1, \
            "sticky session left its gray home"
        # new sessions homed on the victim are redirected off it
        moved = 0
        for i in range(4):
            sid = _sid_homed_on(robj, victim, f"fresh{i}_")
            _post(router.url + "/parse",
                  {"text": "scroll down", "session_id": sid, "context": {}})
            moved += 1
            assert not any(e[1] == sid for e in logs[victim]), \
                "a NEW session was placed on the gray replica"
        counters = get_metrics().snapshot()["counters"]
        assert counters.get("fleet.shed_gray", 0) >= moved
        assert counters.get("fleet.gray_entered", 0) >= 1
        # the flight dump carries the peer-comparison evidence
        dump = _get(router.url + "/debug/flightrecorder")
        assert dump["frozen"] and dump["reason"] == "fleet.gray"
        ev = dump["extra"]["fleet"]
        assert ev["replica"] == vurl and ev["signal"] == "parse_ms"
        assert len(ev["peers"]) == 3 and ev["score"] >= 4.0
        assert ev["fleet_median"] < ev["value"]
        # symmetric recovery: the drift clears, so does the verdict
        controls[victim]["parse_ms"] = 10.0
        assert _wait(lambda: _get(router.url + "/health")["replicas"]["gray"] == 0), \
            "gray never cleared after recovery"
        sid = _sid_homed_on(robj, victim, "postrecovery")
        _post(router.url + "/parse", {"text": "scroll down",
                                      "session_id": sid, "context": {}})
        assert any(e[1] == sid for e in logs[victim]), \
            "recovered replica still avoided"
    finally:
        _teardown(router, servers)
        get_flight_recorder().rearm()


def test_gray_needs_min_peers():
    """With only two members reporting, nobody can be named the outlier —
    detection must stay quiet instead of guessing."""
    get_flight_recorder().rearm()
    router, servers, logs, controls, robj = _fleet_ring(2)
    try:
        controls[0]["parse_ms"] = 500.0
        time.sleep(1.0)
        assert _get(router.url + "/health")["replicas"]["gray"] == 0
    finally:
        _teardown(router, servers)
        get_flight_recorder().rearm()


def test_clock_skew_estimate_and_flight_fanout():
    get_flight_recorder().rearm()
    router, servers, logs, controls, robj = _fleet_ring(3)
    try:
        controls[1]["now_skew_s"] = 5.0
        assert _wait(lambda: abs(robj.replicas[1].clock_skew_s - 5.0) < 1.0,
                     5.0)
        detail = {d["url"]: d for d in
                  _get(router.url + "/health")["replica_detail"]}
        assert abs(detail[servers[1].url]["clock_skew_s"] - 5.0) < 1.0
        assert abs(detail[servers[0].url]["clock_skew_s"]) < 1.0
        # the fan-out annotates each member dump with the estimate; fake
        # members have no /debug/flightrecorder, so bodies carry errors —
        # but the skew annotation rides regardless
        fan = _get(router.url + "/debug/replicas/flightrecorder")
        assert abs(fan["replicas"][servers[1].url]["clock_skew_s"] - 5.0) < 1.0
    finally:
        _teardown(router, servers)
        get_flight_recorder().rearm()


def test_traceview_merges_skewed_dumps(tmp_path):
    """A saved multi-service dump body merges onto one timeline with each
    member's spans shifted by its recorded skew."""
    t0 = 1_700_000_000.0

    def dump(svc, start, skew):
        return {"frozen": True, "reason": f"slo.{svc}.violated",
                "frozen_at_s": t0 + start + skew, "clock_skew_s": skew,
                "metric_snapshots": [],
                "traces": [{"trace_id": "tr1", "spans": [
                    {"svc": svc, "span": "work", "trace": "tr1", "ms": 100.0,
                     "wall_start_s": t0 + start + skew,
                     "wall_end_s": t0 + start + skew + 0.1}]}]}

    body = {"service": "router",
            "replicas": {"http://a": dump("a", 0.0, 0.0),
                         "http://b": dump("b", 0.2, 7.0)}}
    merged = traceview.merge_flight_dumps(body["replicas"])
    spans = merged["traces"][0]["spans"]
    assert len(spans) == 2
    walls = sorted(s["wall_start_s"] for s in spans)
    assert walls[1] - walls[0] == pytest.approx(0.2, abs=0.01), \
        "skew correction did not land the spans on one clock"
    # the CLI path accepts the saved fan-out shape
    p = tmp_path / "fan.json"
    p.write_text(json.dumps(body))
    assert traceview.main(["--flight", str(p), "--json"]) == 0


# ----------------------------------------------------- e2e (real services)


def test_replica_degrade_e2e_and_fleetview_dump(monkeypatch, tmp_path):
    """The canonical gray failure through the REAL stack: one of three
    real brain replicas latches persistently slow (replica_degrade chaos),
    the router's fleet scrape demotes it, the frozen dump carries the
    evidence, and fleetview renders it."""
    from tpu_voice_agent.utils import chaos as chaos_mod

    monkeypatch.setenv("TS_INTERVAL_S", "0.1")
    monkeypatch.setenv("CHAOS_SLOW_S", "0.4")
    monkeypatch.setenv("SLO_TARGET_P50_MS", "60000")  # only fleet.gray freezes
    monkeypatch.setenv("SLO_TARGET_P99_MS", "120000")
    get_flight_recorder().rearm()
    chaos_mod.configure("replica_degrade@1", seed=3)
    servers = [AppServer(build_brain(RuleBasedParser())).__enter__()
               for _ in range(3)]
    robj = BrainRouter([s.url for s in servers], probe_s=0.1,
                       fleet_windows=2, fleet_min_peers=3)
    router = AppServer(build_router(robj)).__enter__()
    try:
        # spread keyed traffic over the whole ring until detection (the
        # first parse latches its replica slow); every member needs fresh
        # parse_ms signals each window
        end = time.monotonic() + 30.0
        detected = False
        i = 0
        while time.monotonic() < end and not detected:
            for j in range(6):
                _post(router.url + "/parse",
                      {"text": "scroll down", "session_id": f"e2e{i}_{j}",
                       "context": {}})
            i += 1
            detected = _get(router.url + "/health")["replicas"]["gray"] == 1
        assert detected, "the degraded replica was never marked gray"
        h = _get(router.url + "/health")
        gray_urls = [d["url"] for d in h["replica_detail"] if d["gray"]]
        assert len(gray_urls) == 1
        dump = _get(router.url + "/debug/flightrecorder")
        assert dump["frozen"] and dump["reason"] == "fleet.gray"
        ev = dump["extra"]["fleet"]
        assert ev["replica"] == gray_urls[0]
        # a middleware-level slowdown is invisible to the replica's own
        # spans — the router-OBSERVED forward wall is what catches it
        assert ev["signal"] == "fwd_ms" and len(ev["peers"]) == 3
        assert ev["value"] > ev["fleet_median"]
        # fleetview renders the saved dump
        p = tmp_path / "gray_dump.json"
        p.write_text(json.dumps(dump))
        assert fleetview.main(["--file", str(p)]) == 0
        txt = fleetview.render_file(dump)
        assert "demoted on fwd_ms" in txt and gray_urls[0] in txt
        # the live fan-out renders too (real /debug/timeseries bodies)
        health, series, autopilot, costs = fleetview.one_frame(router.url, 32)
        # the costs fan-out answers per replica; rule-based brains carry
        # no engine meter, so every body reports the lanes off
        cost_reps = costs["replicas"]
        assert len(cost_reps) == 3
        assert all(b.get("enabled") is False for b in cost_reps.values())
        assert "[cost lanes off]" in fleetview.render_costs(costs, series)
        # no controller attached in this harness -> the panel degrades
        assert not autopilot.get("enabled")
        assert fleetview.render_autopilot(autopilot) == \
            "autopilot: not attached"
        frame = fleetview.render_fleet(health, series)
        assert "GRAY" in frame and "parse_ms" in frame
    finally:
        _teardown(router, servers)
        chaos_mod.reset()
        get_flight_recorder().rearm()


# --------------------------------------------------------------- sampler


def test_swarm_sampler_reads_timeseries(monkeypatch):
    import swarm

    monkeypatch.setenv("TS_INTERVAL_S", "0.05")
    with AppServer(build_brain(RuleBasedParser())) as srv:
        _post(srv.url + "/parse", {"text": "scroll down", "context": {}})
        sampler = swarm.MetricsSampler([srv.url], interval_s=0.05)
        with sampler:
            time.sleep(0.5)
        assert sampler.samples, "sampler collected nothing"
        assert srv.url not in sampler._legacy, \
            "sampler fell back to /metrics despite a live timeseries ring"
        merged = sampler.samples[-1]["gauges"]
        assert "ts.samples_buffered" in merged
        # the delta cursor advanced past the first poll
        assert sampler._since[srv.url] > 0


def test_sampler_primes_cursor_and_latches_only_on_404(monkeypatch):
    """The first contact with a ring must PRIME the cursor and discard
    the backlog (a prior probe's saturated gauges would otherwise stamp
    stale saturation onto this run's timeline); the legacy ?gauges=1
    fallback latches only on a definitive 404, never a transient error."""
    import swarm

    monkeypatch.setenv("TS_INTERVAL_S", "0.05")
    with AppServer(build_brain(RuleBasedParser())) as srv:
        _post(srv.url + "/parse", {"text": "scroll down", "context": {}})
        time.sleep(0.3)  # let a backlog accumulate in the ring
        backlog = _get(srv.url + "/debug/timeseries")
        assert len(backlog["samples"]) >= 3
        sampler = swarm.MetricsSampler([srv.url])
        sampler._poll_once()
        # the cursor drained the whole backlog, but at most a sliver of
        # post-construction samples may have landed on the timeline — the
        # prior history must never merge
        assert len(sampler.samples) <= 1
        assert sampler._since[srv.url] >= backlog["next_seq"]
        time.sleep(0.15)
        sampler._poll_once()
        assert sampler.samples, "post-prime deltas must merge"
        # a dead URL is a TRANSIENT failure: no legacy latch
        dead = "http://127.0.0.1:9"
        s2 = swarm.MetricsSampler([dead])
        s2._poll_once()
        assert dead not in s2._legacy
    # a service without the endpoint at all (404) latches the fallback
    from aiohttp import web as _web

    app = _web.Application()

    async def metrics(_req):
        return _web.json_response({"runtime": {"gauges": {"old.gauge": 1.0}}})

    app.router.add_get("/metrics", metrics)
    with AppServer(app) as old:
        s3 = swarm.MetricsSampler([old.url])
        s3._poll_once()
        assert old.url in s3._legacy
        assert s3.samples and s3.samples[-1]["gauges"]["old.gauge"] == 1.0


def test_fleetview_self_test():
    assert fleetview.self_test() == 0
