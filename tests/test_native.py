"""Native C++ audio frontend vs the numpy twins."""

import numpy as np
import pytest

from tpu_voice_agent import native
from tpu_voice_agent.audio.endpoint import EnergyEndpointer
from tpu_voice_agent.audio.mel import pcm16_to_float as np_pcm16


@pytest.fixture(scope="module", autouse=True)
def require_native():
    # force the lazy build; if g++ is genuinely unavailable the fallback
    # paths are exercised instead (still valid tests)
    native.rms(np.zeros(4, np.float32))
    yield


def test_native_built():
    assert native.frontend.NATIVE_AVAILABLE, "g++ is in this image; build must succeed"


class TestPCM:
    def test_matches_numpy(self):
        rng = np.random.default_rng(0)
        pcm = rng.integers(-32768, 32767, 1000, dtype=np.int16).tobytes()
        np.testing.assert_allclose(native.pcm16_to_float(pcm), np_pcm16(pcm), atol=1e-7)

    def test_empty(self):
        assert len(native.pcm16_to_float(b"")) == 0


class TestRMS:
    def test_matches_numpy(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal(4096).astype(np.float32)
        assert abs(native.rms(x) - float(np.sqrt(np.mean(x**2)))) < 1e-6

    def test_empty(self):
        assert native.rms(np.zeros(0, np.float32)) == 0.0


class TestResample:
    def test_sine_preserved_48k_to_16k(self):
        """A 1 kHz tone survives 48k->16k with correct frequency and amplitude."""
        sr_in, sr_out, f0 = 48_000, 16_000, 1000.0
        t = np.arange(sr_in) / sr_in  # 1 s
        x = np.sin(2 * np.pi * f0 * t).astype(np.float32)
        y = native.resample(x, sr_in, sr_out)
        assert len(y) == sr_out
        # dominant DFT bin == 1 kHz
        spec = np.abs(np.fft.rfft(y[1000:-1000] * np.hanning(len(y) - 2000)))
        peak_hz = np.argmax(spec) * sr_out / (len(y) - 2000)
        assert abs(peak_hz - f0) < 5.0
        assert 0.9 < np.max(np.abs(y[1000:-1000])) < 1.1

    def test_antialiasing_kills_out_of_band_tone(self):
        """A 10 kHz tone (above the 8 kHz Nyquist of 16 k) must be attenuated —
        the reference's nearest-neighbor decimation would alias it to 6 kHz."""
        sr_in, sr_out = 48_000, 16_000
        t = np.arange(sr_in // 2) / sr_in
        x = np.sin(2 * np.pi * 10_000.0 * t).astype(np.float32)
        y = native.resample(x, sr_in, sr_out)
        assert np.max(np.abs(y[200:-200])) < 0.15

    def test_identity_and_length(self):
        x = np.linspace(-1, 1, 1600).astype(np.float32)
        np.testing.assert_array_equal(native.resample(x, 16_000, 16_000), x)
        assert len(native.resample(x, 48_000, 16_000)) == 533


class TestEndpointerParity:
    def _signal(self):
        rng = np.random.default_rng(2)
        sr = 16_000
        silence = (rng.standard_normal(sr // 2) * 1e-4).astype(np.float32)
        speech = (rng.standard_normal(sr) * 0.3).astype(np.float32)
        return np.concatenate([silence, speech, silence, speech, silence])

    def test_same_segmentation_as_python(self):
        sig = self._signal()
        py = EnergyEndpointer()
        cc = native.NativeEndpointer()
        chunk = 320
        py_ends, cc_ends = [], []
        for i in range(0, len(sig) - chunk, chunk):
            c = sig[i : i + chunk]
            if py.feed(c):
                py_ends.append(i)
            if cc.feed(c):
                cc_ends.append(i)
        assert py_ends == cc_ends
        assert len(cc_ends) == 2  # both utterances detected

    def test_reset(self):
        cc = native.NativeEndpointer()
        cc.feed(np.ones(16_000, np.float32) * 0.5)
        assert cc.in_speech
        cc.reset()
        assert not cc.in_speech
