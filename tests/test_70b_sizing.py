"""Sizing the 70B flagship (round-3 VERDICT next #6): BASELINE config 4 —
Llama-3-70B-class planner, int8, 32-session continuous batching on v5e-8 —
must PHYSICALLY fit and its pp×tp program must lower at real dims.

Three guards:
- the HBM budget (utils/hbm_budget.py, mirroring pp_engine's placement)
  stays under the 90% planning ceiling — this test FAILS the build if a
  placement change makes the flagship config stop fitting
- the pp×tp cached forward AOT-lowers at FULL 70B dims with abstract
  int8 params over the virtual 8-device (pp=2, tp=4) mesh (no weights are
  materialized; .lower() checks shapes/shardings/collectives end to end)
- the int8 pp engine serves grammar-valid output and stays close to its
  bf16 twin on a tiny config (the runtime path the sizing assumes)
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import pytest

from tpu_voice_agent.models.llama import PRESETS
from tpu_voice_agent.utils.hbm_budget import (
    USABLE_FRACTION,
    V5E_HBM_PER_CHIP,
    flagship_70b_breakdown,
)


def test_flagship_70b_fits_v5e8():
    b = flagship_70b_breakdown(batch_slots=32, max_len=2048, pp=2, tp=4)
    frac = b.fraction_of(V5E_HBM_PER_CHIP)
    assert frac <= USABLE_FRACTION, (
        f"flagship config no longer fits: {b.row()} -> {100 * frac:.1f}% "
        f"of a v5e chip (ceiling {100 * USABLE_FRACTION:.0f}%)")
    # and it genuinely needs int8: bf16 weights alone would blow the chip
    from tpu_voice_agent.utils.hbm_budget import pp_tp_hbm_per_chip

    cfg = replace(PRESETS["llama3-70b"], vocab_size=128_256)
    bf16 = pp_tp_hbm_per_chip(cfg, 2, 4, batch_slots=32, max_len=2048,
                              quant=None)
    assert bf16.fraction_of(V5E_HBM_PER_CHIP) > 1.0


@pytest.mark.slow
def test_pp_tp_forward_aot_lowers_at_70b_dims():
    """AOT .lower() of the servable pp×tp forward at FULL 70B dimensions
    (abstract int8 params — nothing materializes). Catches shape/sharding
    mismatches that tiny-dim dryruns cannot (e.g. a head-count or stage
    split that only breaks at 64 heads / 80 layers / 128k vocab)."""
    from tpu_voice_agent.parallel.pipeline import (
        llama_pp_tp_forward_cached,
        pp_tp_mesh,
        staged_tp_shardings,
    )

    mesh = pp_tp_mesh(2, 4)
    cfg = replace(PRESETS["llama3-70b"], vocab_size=128_256, max_seq_len=2048)
    S, Lps = 2, cfg.n_layers // 2
    d, hd, nq, nkv, f, V = (cfg.dim, cfg.head_dim, cfg.n_heads,
                            cfg.n_kv_heads, cfg.ffn_dim, cfg.vocab_size)

    def leaf(shape, dtype=jnp.bfloat16):
        return jax.ShapeDtypeStruct(shape, dtype)

    def q8(*shape):
        return {"q": leaf(shape, jnp.int8),
                "s": leaf((*shape[:-2], 1, shape[-1]), jnp.float32)}

    staged = {
        "attn_norm": leaf((S, Lps, d)),
        "wq": q8(S, Lps, d, nq * hd),
        "wk": q8(S, Lps, d, nkv * hd),
        "wv": q8(S, Lps, d, nkv * hd),
        "wo": q8(S, Lps, nq * hd, d),
        "mlp_norm": leaf((S, Lps, d)),
        "w_gate": q8(S, Lps, d, f),
        "w_up": q8(S, Lps, d, f),
        "w_down": q8(S, Lps, f, d),
    }
    params = {
        "embed": leaf((V, d)),
        "staged": staged,
        "final_norm": leaf((d,)),
        "lm_head": {"q": leaf((d, V), jnp.int8), "s": leaf((1, V), jnp.float32)},
    }
    B, T, max_len = 32, 1, 2048
    cache = {
        "k": leaf((S, Lps, B, max_len, nkv, hd)),
        "v": leaf((S, Lps, B, max_len, nkv, hd)),
    }
    tokens = jax.ShapeDtypeStruct((B, T), jnp.int32)
    positions = jax.ShapeDtypeStruct((B, T), jnp.int32)
    lowered = llama_pp_tp_forward_cached.lower(
        params, cache, cfg, tokens, positions, mesh)
    text = lowered.as_text()
    assert "stablehlo" in text or "module" in text
    # sanity: the staged int8 sharding tree matches the abstract structure
    sh = staged_tp_shardings(mesh, staged)
    assert set(sh) == set(staged)
    assert isinstance(sh["wq"], dict) and "s" in sh["wq"]


@pytest.mark.slow
def test_pp_engine_int8_serves_grammar_valid():
    """The int8 pp×tp engine (the flagship's runtime path) must produce
    grammar-valid constrained output; int8 rounding may flip tokens vs
    bf16, so the assertion is validity + near-identical logits, not
    token identity."""
    from tpu_voice_agent.parallel.pipeline import pp_tp_mesh
    from tpu_voice_agent.serve.pp_engine import PPDecodeEngine
    from tpu_voice_agent.services.prompts import render_prompt

    from tpu_voice_agent.serve.scheduler import ContinuousBatcher

    mesh = pp_tp_mesh(2, 2)
    eng = PPDecodeEngine(preset="test-tiny", mesh=mesh, max_len=1024,
                         prefill_buckets=(1024,), quant="int8")
    assert isinstance(eng.params["staged"]["wq"], dict)  # int8 staged
    assert isinstance(eng.params["lm_head"], dict)
    [res] = ContinuousBatcher(eng, chunk_steps=16,
                              max_new_tokens=48).generate_many(
        [render_prompt("scroll down", {})])
    state = eng.fsm.walk([int(t) for t in res.token_ids])
    assert state >= 0, "int8 pp decode left the grammar"
    assert res.text.startswith('{"version":"1.0"')
