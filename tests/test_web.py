"""Web client serving: the voice service hosts the UI on one origin.

Reference parity (SURVEY.md §2 #1-#4): the shell, capture pipeline, intent
review, and executor client all live in the served static bundle; the WS
contract they speak is covered end-to-end by tests/test_voice.py.
"""

import asyncio

import aiohttp

from tpu_voice_agent.serve.stt import NullSTT
from tpu_voice_agent.services.voice import VoiceConfig, build_app as build_voice
from tests.http_helper import AppServer


def _get(url: str) -> tuple[int, str]:
    async def run():
        async with aiohttp.ClientSession() as sess:
            async with sess.get(url) as r:
                return r.status, await r.text()

    return asyncio.run(run())


def test_index_and_assets_served():
    app = build_voice(VoiceConfig(stt_factory=NullSTT))
    with AppServer(app) as srv:
        status, html = _get(srv.url + "/")
        assert status == 200 and "tpu-voice-agent" in html
        # the shell wires exactly one socket: /stream on the same origin
        status, js = _get(srv.url + "/static/app.js")
        assert status == 200
        assert "ws://${location.host}/stream" in js
        assert "7071" not in js  # the reference's phantom-port bug stays dead
        status, css = _get(srv.url + "/static/style.css")
        assert status == 200 and ".badge" in css


def test_client_covers_reference_capture_contract():
    """The capture pipeline constants match the reference behavior the
    framework replicates (60 ms batching, 2 s keep-alive, 16 kHz)."""
    from tpu_voice_agent.web import static_dir

    js = (static_dir() / "app.js").read_text()
    assert "BATCH_MS = 60" in js
    assert "KEEPALIVE_MS = 2000" in js
    assert "TARGET_RATE = 16000" in js
    for feature in ("confirm_execute", "uploads", "fileRef", "AudioWorkletNode",
                    "transcript_partial", "confirmation_required", "execution_result"):
        assert feature in js, feature
