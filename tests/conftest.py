"""Test harness config.

All model/mesh tests run on CPU with 8 virtual XLA devices
(SURVEY.md §4: mirror the reference's seam strategy; multi-chip behavior is
validated via xla_force_host_platform_device_count).

NOTE: this environment's axon TPU plugin force-prepends itself to
``jax_platforms`` regardless of the JAX_PLATFORMS env var, so we must also
override the config after import — before any backend initialization.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_engine():
    """Shared tiny random-weight engine (compile once per test session)."""
    from tpu_voice_agent.serve import DecodeEngine

    return DecodeEngine(preset="test-tiny", max_len=2048, prefill_buckets=(64, 128, 256, 512, 1024))


@pytest.fixture(scope="session")
def tiny_batch_engine():
    from tpu_voice_agent.serve import DecodeEngine

    return DecodeEngine(
        preset="test-tiny", max_len=1024, batch_slots=3, prefill_buckets=(64, 128, 256, 512)
    )
