"""Test harness config.

All model/mesh tests run on CPU with 8 virtual XLA devices
(SURVEY.md §4: mirror the reference's seam strategy; multi-chip behavior is
validated via xla_force_host_platform_device_count). Must run before any
``import jax`` in test modules.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
