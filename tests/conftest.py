"""Test harness config.

All model/mesh tests run on CPU with 8 virtual XLA devices
(SURVEY.md §4: mirror the reference's seam strategy; multi-chip behavior is
validated via xla_force_host_platform_device_count).

NOTE: this environment's axon TPU plugin force-prepends itself to
``jax_platforms`` regardless of the JAX_PLATFORMS env var, so we must also
override the config after import — before any backend initialization.
"""

import os
import pathlib

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_want_cache = os.environ.get("JAX_TEST_CACHE") != "0"
if _want_cache:
    # the CPU AOT cache loader logs TWO ERROR-level lines PER CACHE HIT
    # about XLA's prefer-no-scatter/gather pseudo-features (benign: they
    # are compiler preferences, not ISA features; verified level 2 does
    # not silence them). The cost of "3" is that other C++ ERROR logs are
    # also hidden during tests — export TF_CPP_MIN_LOG_LEVEL yourself (or
    # JAX_TEST_CACHE=0) when debugging a suspected XLA runtime failure.
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache (round-3 VERDICT next #8: the full slow tier
# outgrew a 10-minute budget on this 1-core box — compiles dominate it, and
# they repeat identically across runs). Repo-local so `git clean` resets it;
# JAX_TEST_CACHE=0 opts out. Measured: warm runs cut engine build+first
# generate ~3.5x (10.4 s -> 3.0 s).
if _want_cache:
    _cache_dir = pathlib.Path(__file__).resolve().parents[1] / ".jax_cache"
    jax.config.update("jax_compilation_cache_dir", str(_cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402

# Two test tiers (round-2 VERDICT weak #7: the full suite is too slow to be
# a habit). Fast tier = the service/contract/unit tests plus the shared
# session-scoped engines: `pytest -m "not slow"` (< ~3 min on CPU). Slow
# tier = compile-heavy mesh/parity/model tests, auto-marked per module here
# (one central list instead of 19 scattered pytestmark lines). The plain
# `pytest tests/` still runs EVERYTHING — the driver's green bar covers
# both tiers.
SLOW_MODULES = {
    "test_brain_planner",
    "test_ckpt",
    "test_colocate",
    "test_expert",
    "test_fastforward",
    "test_hf_real",
    "test_llama",
    "test_longctx",
    "test_moe_llama",
    "test_multihost",
    "test_ops_sharded",
    "test_paged",
    "test_pipeline",
    "test_prefix",
    "test_qwen2vl",
    "test_races",
    "test_ring",
    "test_stt",
    "test_whisper",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.fspath.purebasename in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def tiny_engine():
    """Shared tiny random-weight engine (compile once per test session)."""
    from tpu_voice_agent.serve import DecodeEngine

    return DecodeEngine(preset="test-tiny", max_len=2048, prefill_buckets=(64, 128, 256, 512, 1024))


@pytest.fixture(scope="session")
def tiny_batch_engine():
    from tpu_voice_agent.serve import DecodeEngine

    return DecodeEngine(
        preset="test-tiny", max_len=1024, batch_slots=3, prefill_buckets=(64, 128, 256, 512)
    )
