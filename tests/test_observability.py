"""End-to-end utterance observability (ISSUE 2).

The executable spec for the observability plane: cross-service trace
collection (span ring + /debug/trace + traceview waterfall assembly),
Prometheus text exposition with golden-format validation, SLO state
transitions on an injected clock, runtime saturation gauges under a full
scheduler batch, and the tooling lints (traceview --self-test, metric-name
collision) wired into tier-1.
"""

import asyncio
import json
import pathlib
import re
import subprocess
import sys

import threading

import aiohttp
import numpy as np
import pytest

from tpu_voice_agent.utils import (
    FlightRecorder,
    Metrics,
    SLOTracker,
    Tracer,
    get_flight_recorder,
    get_metrics,
)
from tpu_voice_agent.utils.tracing import (
    HIST_BUCKETS_MS,
    nearest_rank,
    prometheus_exposition,
)

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "tools"))

import metrics_lint  # noqa: E402
import traceview  # noqa: E402


# ------------------------------------------------------------ metrics math


def test_percentile_and_snapshot_agree_on_one_sample():
    m = Metrics()
    m.observe_ms("k", 42.0)
    snap = m.snapshot()["latency_ms"]["k"]
    assert m.percentile_ms("k", 0.5) == 42.0
    assert m.percentile_ms("k", 0.95) == 42.0
    assert snap["p50"] == snap["p95"] == snap["p99"] == snap["max"] == 42.0


def test_percentile_and_snapshot_agree_on_two_samples():
    m = Metrics()
    m.observe_ms("k", 10.0)
    m.observe_ms("k", 90.0)
    snap = m.snapshot()["latency_ms"]["k"]
    # ONE nearest-rank rule for both paths (they used to disagree on
    # index rounding): q*(n-1) rounded half-up
    assert m.percentile_ms("k", 0.5) == snap["p50"] == 90.0
    assert m.percentile_ms("k", 0.95) == snap["p95"] == 90.0
    assert m.percentile_ms("k", 0.2) == 10.0


def test_nearest_rank_rejects_empty():
    with pytest.raises(ValueError):
        nearest_rank([], 0.5)


def test_metrics_kind_collision_tracking():
    m = Metrics()
    m.inc("dup")
    m.set_gauge("dup", 1.0)
    m.observe_ms("clean", 5.0)
    assert m.collisions() == [("dup", "counter", "gauge")]


# ------------------------------------------------------------ span guard


def test_span_name_guard_rejects_cardinality_smuggling():
    t = Tracer("svc", emit=False)
    for bad in ("has space", "attr=1", "brace{x}", "tab\tname", ""):
        with pytest.raises(ValueError):
            with t.span(bad):
                pass
        with pytest.raises(ValueError):
            t.record_span(bad, "tid", 0.0, 1.0)
    with t.span("fine_name", trace_id="tid", chars=3):
        pass  # attrs are the right place for per-request values
    assert t.spans_for("tid")[0]["chars"] == 3


def test_trace_ring_bounded_and_lru():
    t = Tracer("svc", emit=False)
    t.MAX_TRACES = 4
    for i in range(10):
        with t.span("s", trace_id=f"trace{i}"):
            pass
    assert t.spans_for("trace0") == []  # evicted
    assert len(t.spans_for("trace9")) == 1


def test_trace_sink_appends_jsonl(tmp_path):
    sink = tmp_path / "spans.jsonl"
    t = Tracer("svc", emit=False, sink_path=str(sink))
    with t.span("one", trace_id="tid"):
        pass
    t.record_span("two", "tid", 0.0, 0.005)
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert [ln["span"] for ln in lines] == ["one", "two"]
    assert all(ln["svc"] == "svc" and ln["trace"] == "tid" for ln in lines)


# ------------------------------------------------------------ exposition


_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le="[^"]+"\})? -?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?$')
_TYPE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$")


def _assert_valid_exposition(text: str) -> dict:
    """Golden-format check: every line is a TYPE comment or a sample, and
    histograms are cumulative with le=+Inf == count. Returns name->value."""
    values = {}
    for line in text.strip().splitlines():
        assert _TYPE.match(line) or _SAMPLE.match(line), f"bad exposition line: {line!r}"
        if not line.startswith("#"):
            name, val = line.rsplit(" ", 1)
            values[name] = float(val)
    # histogram invariants
    for name in {n.split("_bucket{")[0] for n in values if "_bucket{" in n}:
        inf = values.get(f'{name}_bucket{{le="+Inf"}}')
        assert inf is not None, f"{name} missing the +Inf bucket"
        assert inf == values[f"{name}_count"]
        bucket_vals = [v for k, v in values.items()
                       if k.startswith(f"{name}_bucket{{")]
        assert bucket_vals == sorted(bucket_vals), f"{name} buckets not cumulative"
    return values


def test_prometheus_exposition_golden_format():
    m = Metrics()
    m.inc("svc.requests", 3)
    m.set_gauge("svc.depth", 2.5)
    for v in (0.4, 3, 70, 99999):
        m.observe_ms("svc.lat", v)
    text = prometheus_exposition(m)
    values = _assert_valid_exposition(text)
    assert values["svc_requests_total"] == 3
    assert values["svc_depth"] == 2.5
    assert values['svc_lat_ms_bucket{le="1"}'] == 1
    assert values['svc_lat_ms_bucket{le="100"}'] == 3  # cumulative
    assert values['svc_lat_ms_bucket{le="+Inf"}'] == 4  # 99999 overflows all bounds
    assert values["svc_lat_ms_count"] == 4
    assert len([k for k in values if k.startswith("svc_lat_ms_bucket")]) \
        == len(HIST_BUCKETS_MS) + 1


def test_exposition_first_registry_wins_on_collision():
    a, b = Metrics(), Metrics()
    a.set_gauge("g", 1.0)
    b.set_gauge("g", 99.0)
    assert "g 1" in prometheus_exposition(a, b).splitlines()


# ------------------------------------------------------------ SLO tracker


def test_slo_state_transitions_ok_at_risk_violated_recovered():
    clock = {"t": 0.0}
    s = SLOTracker("t", window_s=60.0, target_p50_ms=100.0, target_p99_ms=400.0,
                   error_rate_target=0.5, at_risk_fraction=0.8, min_samples=3,
                   clock=lambda: clock["t"])
    # below min_samples: always ok (warmup must not page)
    s.record(5000.0)
    s.record(5000.0)
    assert s.state() == "ok"
    clock["t"] += 61.0  # age the warmup out
    # fast traffic: ok
    for _ in range(10):
        s.record(50.0)
    assert s.state() == "ok"
    # p50 drifts past 80% of target: at_risk
    for _ in range(30):
        s.record(90.0)
    assert s.state() == "at_risk"
    # p50 blows the budget: violated
    for _ in range(60):
        s.record(300.0)
    ev = s.evaluate()
    assert ev["state"] == "violated" and ev["reasons"]
    # window slides: the slow samples age out -> recovered
    clock["t"] += 61.0
    for _ in range(10):
        s.record(50.0)
    assert s.state() == "ok"
    # error budget burn alone also violates (15 errors / 25 samples = 0.6)
    for _ in range(15):
        s.record(10.0, ok=False)
    assert s.state() == "violated"
    g = get_metrics().snapshot()["gauges"]
    assert g["slo.t.state"] == 2.0


def test_slo_p99_guard():
    clock = {"t": 0.0}
    s = SLOTracker("t99", window_s=60.0, target_p50_ms=1000.0, target_p99_ms=200.0,
                   min_samples=5, clock=lambda: clock["t"])
    for _ in range(99):
        s.record(10.0)
    assert s.state() == "ok"
    for _ in range(5):
        s.record(5000.0)  # a thin slow tail
    assert s.state() == "violated"


# ------------------------------------------------------- flight recorder


def test_flight_recorder_buffers_freezes_and_rearms():
    rec = FlightRecorder(max_traces=4, max_snapshots=8, snapshot_interval_s=999)
    for i in range(10):  # 10 traces through a 4-trace ring
        rec.observe_span({"svc": "t", "span": "s", "trace": f"tr{i}", "ms": 1.0,
                          "wall_start_s": float(i), "wall_end_s": float(i) + 0.1})
    st = rec.state("svc")
    assert st["frozen"] is False and st["traces_buffered"] == 4
    assert st["service"] == "svc"
    assert rec.trigger("slo.test.violated", detail="p50 blown") is True
    dump = rec.frozen_dump()
    assert dump["reason"] == "slo.test.violated" and dump["detail"] == "p50 blown"
    assert [t["trace_id"] for t in dump["traces"]] == ["tr6", "tr7", "tr8", "tr9"]
    assert dump["metric_snapshots"], "trigger snapshots the knee itself"
    # first freeze wins; the dump is immutable under later spans/triggers
    assert rec.trigger("breaker.x.open") is False
    rec.observe_span({"svc": "t", "span": "s", "trace": "later", "ms": 1.0})
    assert rec.frozen_dump()["reason"] == "slo.test.violated"
    assert len(rec.frozen_dump()["traces"]) == 4
    rec.rearm()
    assert rec.state()["frozen"] is False
    assert rec.trigger("second.incident") is True


def test_breaker_trip_freezes_global_flight_recorder():
    from tpu_voice_agent.utils.resilience import CircuitBreaker

    rec = get_flight_recorder()
    rec.rearm()
    try:
        b = CircuitBreaker("flighttestdep", failure_threshold=1,
                           reset_after_s=60.0)
        b.record_failure()  # threshold 1: first failure trips -> open
        dump = rec.frozen_dump()
        assert dump is not None
        assert dump["reason"] == "breaker.flighttestdep.open"
    finally:
        rec.rearm()


def test_slo_violation_freezes_global_flight_recorder():
    clock = {"t": 0.0}
    rec = get_flight_recorder()
    rec.rearm()
    try:
        s = SLOTracker("flightslo", window_s=60.0, target_p50_ms=1.0,
                       min_samples=2, clock=lambda: clock["t"])
        for _ in range(5):
            s.record(100.0)
        assert s.state() == "violated"
        dump = rec.frozen_dump()
        assert dump is not None and dump["reason"] == "slo.flightslo.violated"
        assert "p50_ms" in (dump["detail"] or "")
    finally:
        rec.rearm()


def test_passive_slo_tracker_never_mutates_the_system():
    """A measurement-side tracker (the swarm's client verdict) must score
    without side effects: no flight freeze, no slo.* gauge export."""
    rec = get_flight_recorder()
    rec.rearm()
    try:
        s = SLOTracker("passiveprobe", window_s=60.0, target_p50_ms=1.0,
                       min_samples=2, passive=True)
        for _ in range(5):
            s.record(100.0)
        assert s.state() == "violated"
        assert rec.frozen_dump() is None
        assert "slo.passiveprobe.state" not in get_metrics().snapshot()["gauges"]
    finally:
        rec.rearm()


def test_flight_sink_writes_dump_on_freeze(tmp_path, monkeypatch):
    monkeypatch.setenv("FLIGHT_SINK", str(tmp_path / "fl"))
    rec = FlightRecorder(max_traces=4, snapshot_interval_s=999)
    rec.observe_span({"svc": "t", "span": "s", "trace": "tr", "ms": 1.0})
    assert rec.trigger("slo.sink.violated")
    files = list(tmp_path.glob("fl_slo.sink.violated_*.json"))
    assert len(files) == 1
    body = json.loads(files[0].read_text())
    assert body["frozen"] and body["traces"][0]["trace_id"] == "tr"


# ------------------------------------ concurrent writers (the race hammer)


def test_slo_tracker_concurrent_record_and_eval_loses_nothing():
    """8 threads hammer record() while 2 more hammer evaluate(): no lost
    samples (the window is huge and under MAX_SAMPLES), no exceptions, and
    the percentile verdict is stable — p50 must be one of the recorded
    values, identical across back-to-back evaluations."""
    s = SLOTracker("hammer", window_s=86_400.0, target_p50_ms=10_000.0,
                   min_samples=5)
    n_threads, per_thread = 8, 400  # 3200 < MAX_SAMPLES
    stop = threading.Event()
    errors: list[Exception] = []

    def writer(t):
        try:
            for i in range(per_thread):
                s.record(1.0 + (i % 7), ok=True)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                ev = s.evaluate()
                assert ev["state"] in ("ok", "at_risk", "violated")
                if ev["p50_ms"] is not None:
                    assert 1.0 <= ev["p50_ms"] <= 8.0
        except Exception as e:  # pragma: no cover
            errors.append(e)

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    for th in readers + writers:
        th.start()
    for th in writers:
        th.join(timeout=60)
        assert not th.is_alive(), "writer hung"
    stop.set()
    for th in readers:
        th.join(timeout=60)
        assert not th.is_alive(), "reader hung"
    assert not errors, errors[0]
    ev1, ev2 = s.evaluate(), s.evaluate()
    assert ev1["samples"] == n_threads * per_thread, "lost SLO samples"
    assert ev1["errors"] == 0
    assert ev1["p50_ms"] == ev2["p50_ms"] and ev1["p99_ms"] == ev2["p99_ms"]


def test_trace_and_flight_rings_bounded_under_concurrent_writers():
    """Many threads complete spans with mostly-unique trace ids (the
    abandoned-trace shape: one span, never finished into an utterance):
    nothing is lost from the metrics, and neither the tracer ring nor the
    flight ring grows past its cap. A freeze racing the writers snapshots a
    consistent dump that later writes never mutate."""
    t = Tracer("hammer", emit=False)
    rec = FlightRecorder(max_traces=16, max_snapshots=8,
                         snapshot_interval_s=0.01)
    n_threads, per_thread = 8, 250  # 2000 spans < reservoir cap
    barrier = threading.Barrier(n_threads + 1)
    errors: list[Exception] = []

    def worker(w):
        try:
            barrier.wait(timeout=30)
            for i in range(per_thread):
                with t.span("s", trace_id=f"w{w}i{i}"):
                    pass
                rec.observe_span({"svc": "hammer", "span": "s",
                                  "trace": f"w{w}i{i}", "ms": 0.1})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    frozen_sizes: list[int] = []

    def freezer():
        try:
            barrier.wait(timeout=30)
            rec.trigger("hammer.freeze")
            frozen_sizes.append(len(rec.frozen_dump()["traces"]))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(n_threads)]
    threads.append(threading.Thread(target=freezer))
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
        assert not th.is_alive(), "hammer thread hung"
    assert not errors, errors[0]
    # no lost spans: the histogram counted every completion
    assert t.metrics.snapshot()["latency_ms"]["hammer.s"]["count"] \
        == n_threads * per_thread
    # the tracer ring stayed LRU-bounded despite n_threads*per_thread ids
    assert len(t._ring) <= t.MAX_TRACES
    # the flight ring never outgrew its cap, frozen or live
    assert len(rec._traces) <= rec.max_traces
    assert frozen_sizes and frozen_sizes[0] <= rec.max_traces
    # the frozen dump did not grow after the freeze
    assert len(rec.frozen_dump()["traces"]) == frozen_sizes[0]
    assert len(rec.frozen_dump()["metric_snapshots"]) <= rec.max_snapshots


# ------------------------------------------------- scheduler saturation


def test_saturation_gauges_under_full_scheduler_batch(tiny_batch_engine):
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher

    b = ContinuousBatcher(tiny_batch_engine, chunk_steps=16, max_new_tokens=64)
    prompts = ["search for laptops", "scroll down", "go back",
               "take a screenshot", "sort by price"]
    ttft_before = get_metrics().snapshot()["latency_ms"].get(
        "scheduler.ttft", {}).get("count", 0)
    for p in prompts:
        b.submit(p)
    b.step()  # admits B=3, decodes one chunk; 2 queue
    g = get_metrics().snapshot()["gauges"]
    assert g["scheduler.batch_slots"] == 3.0
    assert g["scheduler.batch_occupancy"] == 1.0  # every slot occupied
    assert g["scheduler.queue_depth"] >= 1.0
    assert g["scheduler.tokens_per_s"] > 0.0
    snap = get_metrics().snapshot()["latency_ms"]
    assert snap["scheduler.ttft"]["count"] >= ttft_before + 3
    b.run_until_done()  # drain: the shared engine goes back clean
    g = get_metrics().snapshot()["gauges"]
    assert g["scheduler.batch_occupancy"] == 0.0
    assert g["scheduler.queue_depth"] == 0.0


def test_ttft_includes_queue_wait(tiny_batch_engine):
    """TTFT is enqueue -> first token: a request that sat in the pending
    queue must not report prefill-only latency (the flat-TTFT-under-load
    failure mode)."""
    import time as _time

    from tpu_voice_agent.serve.scheduler import ContinuousBatcher

    b = ContinuousBatcher(tiny_batch_engine, chunk_steps=16, max_new_tokens=32)
    b.submit("scroll down")
    _time.sleep(0.15)  # simulated queue wait before the scheduler turns over
    b.step()
    last_ttft = get_metrics()._latencies["scheduler.ttft"][-1]
    assert last_ttft >= 150.0, last_ttft
    b.run_until_done()


def test_kv_pool_utilization_gauges():
    from tpu_voice_agent.serve.paged import BlockAllocator, record_pool_gauges

    alloc = BlockAllocator(10, n_groups=2)  # 8 usable (2 trash-reserved)
    record_pool_gauges(alloc)
    g = get_metrics().snapshot()["gauges"]
    assert g["paged.kv_blocks_total"] == 8.0
    assert g["paged.kv_utilization"] == 0.0
    held = alloc.alloc(3, group=0) + alloc.alloc(1, group=1)
    record_pool_gauges(alloc)
    g = get_metrics().snapshot()["gauges"]
    assert g["paged.kv_blocks_used"] == 4.0
    assert g["paged.kv_utilization"] == pytest.approx(0.5)
    alloc.free(held)
    record_pool_gauges(alloc)
    assert get_metrics().snapshot()["gauges"]["paged.kv_utilization"] == 0.0


# ----------------------------------------------------- cross-service e2e


PCM_SILENCE = (np.zeros(1600, dtype="<i2")).tobytes()  # 100 ms


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """voice + brain + executor on real sockets (http_helper harness)."""
    from tests.http_helper import AppServer
    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app as build_brain
    from tpu_voice_agent.services.executor import SessionManager, build_app as build_executor
    from tpu_voice_agent.services.executor.page import FakePage
    from tpu_voice_agent.services.voice import VoiceConfig, build_app as build_voice

    tmp = tmp_path_factory.mktemp("obs_stack")
    brain = AppServer(build_brain(RuleBasedParser())).__enter__()
    manager = SessionManager(page_factory=FakePage.demo,
                             artifacts_root=str(tmp / "art"),
                             uploads_dir=str(tmp / "up"))
    executor = AppServer(build_executor(manager)).__enter__()
    scripted: list = []

    def stt_factory():
        return NullSTT(scripted=list(scripted))

    voice = AppServer(build_voice(VoiceConfig(
        brain_url=brain.url, executor_url=executor.url,
        stt_factory=stt_factory))).__enter__()
    yield {"voice": voice, "brain": brain, "executor": executor,
           "scripted": scripted}
    for srv in (voice, executor, brain):
        srv.__exit__(None, None, None)


def _ws_collect(voice_url, inbound, expect_types, timeout_s=30.0):
    async def run():
        events, seen = [], set()
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(voice_url.replace("http", "ws") + "/stream") as ws:
                for kind, payload in inbound:
                    if kind == "binary":
                        await ws.send_bytes(payload)
                    else:
                        await ws.send_json(payload)
                end = asyncio.get_event_loop().time() + timeout_s
                while asyncio.get_event_loop().time() < end:
                    try:
                        msg = await ws.receive(timeout=1.0)
                    except asyncio.TimeoutError:
                        continue
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        break
                    ev = json.loads(msg.data)
                    events.append(ev)
                    seen.add(ev["type"])
                    if set(expect_types) <= seen:
                        break
        return events

    return asyncio.run(run())


def _get(url, accept=None):
    async def run():
        headers = {"Accept": accept} if accept else {}
        async with aiohttp.ClientSession() as sess:
            async with sess.get(url, headers=headers) as r:
                return r.status, r.headers.get("Content-Type", ""), await r.text()

    return asyncio.run(run())


def test_cross_service_trace_waterfall_for_real_utterance(stack):
    """The acceptance drill: one WS utterance (audio in) -> the SAME trace
    id is visible in all three services' /debug/trace, and traceview
    reassembles the complete capture -> STT -> parse -> execute waterfall."""
    stack["scripted"][:] = [("final", "search for laptops")]
    events = _ws_collect(stack["voice"].url, [("binary", PCM_SILENCE)],
                         ["latency_budget"])
    budget = next(e for e in events if e["type"] == "latency_budget")
    trace_id = budget["trace_id"]
    assert trace_id

    # the stage-split dict the web HUD renders
    st = budget["stages"]
    for key in ("audio_ingest_ms", "stt_finalize_ms", "parse_ms",
                "execute_ms", "total_ms"):
        assert key in st and st[key] >= 0.0, (key, st)
    assert st["total_ms"] == pytest.approx(
        st["stt_finalize_ms"] + st["parse_ms"] + st["execute_ms"], abs=0.01)

    # every service saw the SAME id
    urls = {n: stack[n].url for n in ("voice", "brain", "executor")}
    per_service = {}
    for name, url in urls.items():
        status, _, body = _get(f"{url}/debug/trace/{trace_id}")
        assert status == 200
        payload = json.loads(body)
        assert payload["service"] == name
        per_service[name] = payload["spans"]
        assert payload["spans"], f"{name} has no spans for {trace_id}"
        assert all(sp["trace"] == trace_id for sp in payload["spans"])

    assert {sp["span"] for sp in per_service["voice"]} >= {
        "audio_ingest", "stt_finalize", "parse_roundtrip", "execute_roundtrip"}
    assert {sp["span"] for sp in per_service["brain"]} == {"parse"}
    assert {sp["span"] for sp in per_service["executor"]} == {"execute"}

    # traceview fans out to the real endpoints and derives the stage splits
    out = traceview.waterfall(trace_id, urls)
    assert len(out["spans"]) >= 6
    stages = out["stages"]
    for stage in ("audio_ingest", "stt_finalize", "parse", "execute"):
        assert stage in stages, stages
    assert stages["parse"]["svc"] == "brain"
    assert stages["execute"]["svc"] == "executor"
    assert "queue_ms" in stages["parse"]  # the decomposition attr
    gantt = traceview.render_gantt(out["spans"])
    assert "voice.audio_ingest" in gantt and "executor.execute" in gantt


def test_each_utterance_gets_its_own_trace(stack):
    stack["scripted"][:] = [("final", "scroll down")]
    first = _ws_collect(stack["voice"].url, [("binary", PCM_SILENCE)],
                        ["latency_budget"])
    stack["scripted"][:] = [("final", "go back")]
    second = _ws_collect(stack["voice"].url, [("binary", PCM_SILENCE)],
                         ["latency_budget"])
    t1 = next(e for e in first if e["type"] == "latency_budget")["trace_id"]
    t2 = next(e for e in second if e["type"] == "latency_budget")["trace_id"]
    assert t1 != t2


def test_typed_text_path_emits_latency_budget(stack):
    events = _ws_collect(stack["voice"].url,
                         [("json", {"type": "text", "text": "take a screenshot"})],
                         ["latency_budget"])
    budget = next(e for e in events if e["type"] == "latency_budget")
    st = budget["stages"]
    assert "parse_ms" in st and "audio_ingest_ms" not in st


def test_prometheus_exposition_on_all_services(stack):
    """curl -H 'Accept: text/plain' /metrics on every service: valid 0.0.4
    exposition including the saturation + SLO gauges (the scheduler/KV
    gauges live in the process-global registry all three apps share here)."""
    values_by_service = {}
    for name in ("voice", "brain", "executor"):
        status, ctype, text = _get(stack[name].url + "/metrics",
                                   accept="text/plain")
        assert status == 200
        assert ctype.startswith("text/plain") and "version=0.0.4" in ctype
        values_by_service[name] = _assert_valid_exposition(text)

    # SLO gauges: each service exports its own verdict
    assert "slo_voice_state" in values_by_service["voice"]
    assert "slo_brain_state" in values_by_service["brain"]
    assert "slo_executor_state" in values_by_service["executor"]
    # saturation gauges (global registry; earlier tests drove the real
    # scheduler and allocator in this process)
    for vals in values_by_service.values():
        assert "scheduler_queue_depth" in vals
        assert "scheduler_batch_occupancy" in vals
        assert "paged_kv_utilization" in vals
    # breaker state + inflight ride the voice/exposed registries as gauges
    assert "resilience_brain_breaker_state" in values_by_service["voice"]
    assert "resilience_executor_inflight" in values_by_service["executor"]
    # JSON stays the default contract
    status, ctype, body = _get(stack["voice"].url + "/metrics")
    assert status == 200 and "json" in ctype
    js = json.loads(body)
    assert js["service"] == "voice" and js["slo"]["name"] == "voice"


def test_health_reports_slo_state(stack):
    for name in ("voice", "brain", "executor"):
        status, _, body = _get(stack[name].url + "/health")
        assert status == 200
        assert json.loads(body)["slo"] in ("ok", "at_risk", "violated")


# ------------------------------------------------------------ tooling/CI


def test_traceview_self_test_passes():
    proc = subprocess.run([sys.executable, str(ROOT / "tools" / "traceview.py"),
                           "--self-test"], capture_output=True, text=True,
                          timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "traceview self-test ok" in proc.stdout


def test_metrics_name_collision_lint_clean_on_repo():
    reg = metrics_lint.scan_source(ROOT / "tpu_voice_agent")
    assert reg, "lint found no registrations — scanner broke"
    collisions = metrics_lint.find_collisions(reg)
    assert collisions == [], f"metric name(s) registered under two types: {collisions}"
    # the speculative-decoding gauges/counters (serve.spec) are registered
    # where the lint can see them — a rename there must show up here
    for name, kind in (("spec.drafted_tokens", "counter"),
                       ("spec.accepted_tokens", "counter"),
                       ("spec.verify_steps", "counter"),
                       ("spec.accept_rate", "gauge"),
                       ("spec.tokens_per_step", "gauge"),
                       ("scheduler.forwards", "counter"),
                       ("scheduler.tokens_per_forward", "gauge")):
        assert list(reg[name]) == [kind], name


def test_metrics_name_collision_lint_catches_mismatch(tmp_path):
    (tmp_path / "bad.py").write_text(
        'm.inc("svc.thing")\n'
        'm.set_gauge(f"svc.{dep}.state", 1)\n'
        'other.observe_ms("svc.thing", 3.0)\n')
    reg = metrics_lint.scan_source(tmp_path)
    assert reg["svc.*.state"] == {"gauge": ["bad.py:2"]}
    cols = metrics_lint.find_collisions(reg)
    assert len(cols) == 1 and cols[0][0] == "svc.thing"
    assert set(cols[0][1]) == {"counter", "histogram"}


def test_metrics_lint_pinned_stt_names_present():
    """The multi-stream STT metric names have an external contract (bench
    artifacts, OBSERVABILITY.md catalog): the lint pins name AND kind, so a
    rename or kind flip fails tier-1 here."""
    reg = metrics_lint.scan_source(ROOT / "tpu_voice_agent")
    assert metrics_lint.check_pinned(reg) == []
    for name in ("stt.feed_lag_s", "stt.buffered_audio_s",
                 "stt.batch_occupancy", "stt.partials_coalesced",
                 "stt.finals_batched"):
        assert name in metrics_lint.PINNED
    # the capacity-observatory contract: the flight recorder's metrics, the
    # aborted-utterance error accounting, the live-session gauge, and the
    # saturation gauges the swarm's attribution keys on
    for name, kind in (("flight.freezes", "counter"),
                       ("flight.traces_buffered", "gauge"),
                       ("flight.snapshots_buffered", "gauge"),
                       ("voice.utterances_aborted", "counter"),
                       ("voice.live_sessions", "gauge"),
                       ("scheduler.batch_occupancy", "gauge"),
                       ("paged.kv_utilization", "gauge")):
        assert metrics_lint.PINNED.get(name) == kind, name


def test_metrics_lint_pinned_catches_missing_and_wrong_kind():
    reg = {"stt.feed_lag_s": {"counter": ["x.py:1"]}}  # wrong kind, rest absent
    problems = metrics_lint.check_pinned(reg)
    assert any("must be a gauge" in p for p in problems)
    assert any("not registered anywhere" in p for p in problems)
