"""Model correctness: KV-cache decode equivalence and TP-sharded equivalence.

Mirrors the reference's seam strategy (SURVEY.md §4): everything runs on CPU
with 8 virtual devices; multi-chip behavior is validated on a (1, tp) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.llama import (
    LlamaConfig,
    PRESETS,
    forward,
    init_kv_cache,
    init_params,
    param_count,
)
from tpu_voice_agent.parallel.mesh import (
    default_rules,
    kv_cache_shardings,
    make_mesh,
    param_shardings,
)

CFG = LlamaConfig(
    vocab_size=64, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq_len=64
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_param_count_matches_preset_scale():
    from dataclasses import replace

    # with their real vocabs (32k / 128k) the presets hit the advertised sizes
    assert 1.0e9 < param_count(replace(PRESETS["tinyllama-1.1b"], vocab_size=32000)) < 1.3e9
    assert 7.5e9 < param_count(replace(PRESETS["llama3-8b"], vocab_size=128256)) < 8.5e9


def test_full_forward_shapes(params):
    T = 8
    tokens = jnp.arange(T, dtype=jnp.int32)[None, :] % CFG.vocab_size
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    logits, cache2 = forward(params, CFG, tokens, positions, cache)
    assert logits.shape == (1, T, CFG.vocab_size)
    assert cache2["k"].shape == (CFG.n_layers, 1, CFG.max_seq_len, CFG.n_kv_heads, CFG.head_dim)


def test_incremental_decode_matches_full_forward(params):
    """Token-by-token decode through the KV cache must reproduce the full
    (teacher-forced) forward logits — validates cache writes, RoPE positions,
    and causal masking in one shot."""
    T = 12
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(1, T)), dtype=jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]

    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    full_logits, _ = forward(params, CFG, tokens, positions, cache)

    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    step_logits = []
    for t in range(T):
        lg, cache = forward(
            params, CFG, tokens[:, t : t + 1], positions[:, t : t + 1], cache
        )
        step_logits.append(lg[:, 0, :])
    step_logits = jnp.stack(step_logits, axis=1)

    np.testing.assert_allclose(
        np.asarray(full_logits), np.asarray(step_logits), rtol=2e-4, atol=2e-4
    )


def test_padded_prefill_matches_exact(params):
    """Pad tokens written past the frontier must never leak into real logits."""
    T = 6
    rng = np.random.default_rng(1)
    toks = rng.integers(0, CFG.vocab_size, size=(1, T))
    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    exact, _ = forward(
        params, CFG, jnp.asarray(toks, jnp.int32), jnp.arange(T, dtype=jnp.int32)[None, :], cache
    )
    padded = np.zeros((1, 16), dtype=np.int32)
    padded[0, :T] = toks
    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    pad_logits, _ = forward(
        params, CFG, jnp.asarray(padded), jnp.arange(16, dtype=jnp.int32)[None, :], cache
    )
    np.testing.assert_allclose(
        np.asarray(exact[:, :T]), np.asarray(pad_logits[:, :T]), rtol=2e-4, atol=2e-4
    )


def test_tp_sharded_forward_matches_unsharded(params):
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh(dp=1, tp=2)
    rules = default_rules(mesh, CFG.n_kv_heads, CFG.n_heads)
    sharded_params = jax.device_put(params, param_shardings(mesh, CFG.n_kv_heads))
    cache = init_kv_cache(CFG, 1, CFG.max_seq_len, dtype=jnp.float32)
    sharded_cache = jax.device_put(cache, kv_cache_shardings(mesh, CFG.n_kv_heads))

    T = 8
    tokens = (jnp.arange(T, dtype=jnp.int32)[None, :] * 3) % CFG.vocab_size
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]

    ref_logits, _ = forward(params, CFG, tokens, positions, cache)
    tp_logits, _ = forward(sharded_params, CFG, tokens, positions, sharded_cache, rules)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(tp_logits), rtol=2e-3, atol=2e-3
    )


def test_mesh_too_big_raises():
    with pytest.raises(ValueError):
        make_mesh(dp=4, tp=4)
