"""Prefill/decode disaggregation (ISSUE 20) — FAST tier.

Three planes, bottom-up:

- the multi-part frame wire (sequence-numbered, CRC-checked, torn-tail
  tolerant) shared by the warm re-home blob and the KV stream
- the KV stream itself: a prefill engine's ``prefill_export`` feeding a
  decode engine's ``StreamAdopter`` must leave the decode side serving
  TOKEN-IDENTICAL output from adopted cache, and every failure (death
  mid-stream, tier mismatch) must close clean-or-cold with balanced
  block accounting on both engines
- router placement: ``url#role`` tags and ``ROUTER_PREFILL_REPLICAS``
  build the pools, sticky placement excludes prefill members (with the
  degraded-beats-error fallback), and ``ROUTER_DISAGG`` unset leaves
  every touched structure byte-identical to the pre-disagg build
"""

import pytest

from tpu_voice_agent.serve import PagedDecodeEngine
from tpu_voice_agent.serve import handoff
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import install_prompt_prefix
from tpu_voice_agent.services.prompts import render_prompt
from tpu_voice_agent.services.router import BrainRouter
from tpu_voice_agent.utils import get_metrics

BUCKETS = (128, 256, 512, 1024, 2048)

PROMPT_TEXT = ("search for wireless noise cancelling headphones under two "
               "hundred dollars and sort the results by customer rating "
               "then open the second result and add it to the cart")


def _counters():
    return get_metrics().snapshot()["counters"]


def _paged(kv_quant=None, **kw):
    eng = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=2,
                            prefill_buckets=BUCKETS, radix_enable=True,
                            kv_quant=kv_quant, **kw)
    install_prompt_prefix(eng)
    return eng


def _assert_balanced(eng):
    pb = len(eng._prefix_blocks[0])
    nodes = eng.radix[0].nodes
    assert eng.allocator.blocks_in_use == pb + (nodes - pb)


def _prompt(_eng=None):
    """A prompt long enough to stream several chunks past the pinned
    prefix (the interesting disagg case): a fat context payload stands in
    for a long cold transcript."""
    ctx = {"last_query": "usb c hub", "page": "results",
           "history": [f"step {i}: compared item number {i} against the "
                       "shortlist and kept the cheaper one"
                       for i in range(12)]}
    return render_prompt(PROMPT_TEXT, ctx)


# ------------------------------------------------------------- frame wire


def test_frame_roundtrip_incremental_and_torn_tail():
    payloads = [b"alpha", b"", b"x" * 3000]
    wire = b"".join(handoff.frame_pack(i, p, final=(i == 2))
                    for i, p in enumerate(payloads))
    # feed byte-at-a-time: frames pop exactly when complete, the partial
    # tail is never an error
    buf, got = b"", []
    for i in range(len(wire)):
        buf += wire[i:i + 1]
        frames, buf = handoff.frame_feed(buf)
        got.extend(frames)
    assert buf == b""
    assert [(s, p) for s, p, _ in got] == list(enumerate(payloads))
    assert [f for _, _, f in got] == [False, False, True]
    # a torn tail (mid-frame cut) stays pending, no frames lost before it
    frames, rest = handoff.frame_feed(wire[:-4])
    assert len(frames) == 2  # the third frame is incomplete, not an error
    assert rest != b"" and wire.endswith(rest + wire[-4:])


def test_frame_corruption_raises():
    good = handoff.frame_pack(0, b"payload", final=True)
    with pytest.raises(ValueError, match="magic"):
        handoff.frame_feed(b"XXXXXX" + good[6:])
    flipped = bytearray(good)
    flipped[-1] ^= 0xFF  # corrupt payload byte -> CRC mismatch
    with pytest.raises(ValueError, match="CRC"):
        handoff.frame_feed(bytes(flipped))


def test_deframe_rejects_reorder_truncation_and_bad_final():
    blob = bytes(range(256)) * 20
    parts = handoff.frame_split(blob, 1000)
    assert len(parts) > 3
    assert handoff.deframe(b"".join(parts)) == blob
    # reordered parts: sequence numbers expose the swap
    swapped = parts[:]
    swapped[0], swapped[1] = swapped[1], swapped[0]
    with pytest.raises(ValueError, match="out of order"):
        handoff.deframe(b"".join(swapped))
    # truncated body: a torn tail must not reassemble
    with pytest.raises(ValueError, match="torn tail"):
        handoff.deframe(b"".join(parts)[:-3])
    # FINAL frame missing entirely (stream cut between frames)
    with pytest.raises(ValueError, match="FINAL"):
        handoff.deframe(b"".join(parts[:-1]))
    with pytest.raises(ValueError, match="no handoff frames"):
        handoff.deframe(b"")


# --------------------------------------------------------- the KV stream


def test_export_stream_adopt_token_identical():
    """THE disagg differential: a prefill engine exports the chain in
    streamed segments, a decode engine adopts them, and the decode-side
    parse is token-identical to a cold control — served from adopted KV
    (cached_tokens covers the streamed chain), blocks balanced on BOTH
    engines."""
    pf, dec, control = _paged(), _paged(), _paged()
    prompt = _prompt(pf)
    blobs = []
    pf_batcher = ContinuousBatcher(pf, chunk_steps=16, max_new_tokens=8)
    out = pf_batcher.prefill_export(prompt, stream_blocks=2,
                                    emit=blobs.append, stream_id="s1")
    assert out["ok"], out
    assert out["segments"] == len(blobs) >= 2  # chunk-pipelined, not 1-shot
    assert out["chain_tokens"] > len(pf.prefix_ids)
    _assert_balanced(pf)  # exporter committed its own radix copy, no leak

    ad = handoff.StreamAdopter(dec)
    for blob in blobs:
        r = ad.feed(blob)
        assert r["ok"] and not r["final"]
    adopted = ad.feed(handoff.pack_kv_end("s1", {"ok": True}))
    assert adopted["final"] and adopted["adopted_tokens"] > 0
    assert dec.radix[0].cached_tokens(
        dec.tokenizer.encode(prompt, bos=True)) \
        >= adopted["adopted_tokens"]
    _assert_balanced(dec)

    run = ContinuousBatcher(dec, chunk_steps=16, max_new_tokens=24)
    moved = run.generate_many([prompt])[0]
    cold = ContinuousBatcher(control, chunk_steps=16,
                             max_new_tokens=24).generate_many([prompt])[0]
    assert moved.error is None and cold.error is None
    assert moved.token_ids == cold.token_ids
    assert moved.cached_tokens >= adopted["adopted_tokens"]  # KV was SERVED
    _assert_balanced(dec)


def test_mid_stream_death_partial_adopt_clean_or_cold():
    """The prefill replica dies mid-stream (only some segments arrived):
    abandon commits the partial frontier as ordinary warm cache, frees
    every ref (zero leaks), and the decode-side parse is still
    token-identical to cold."""
    pf, dec, control = _paged(), _paged(), _paged()
    prompt = _prompt(pf)
    blobs = []
    ContinuousBatcher(pf, chunk_steps=16, max_new_tokens=8).prefill_export(
        prompt, stream_blocks=1, emit=blobs.append)
    assert len(blobs) >= 2
    before = _counters().get("disagg.streams_aborted", 0)
    ad = handoff.StreamAdopter(dec)
    ad.feed(blobs[0])  # only the first segment lands, then the wire dies
    assert ad.abandon() == 0
    assert _counters().get("disagg.streams_aborted", 0) == before + 1
    _assert_balanced(dec)  # partial chain is tree-owned or freed, no limbo
    moved = ContinuousBatcher(dec, chunk_steps=16,
                              max_new_tokens=24).generate_many([prompt])[0]
    cold = ContinuousBatcher(control, chunk_steps=16,
                             max_new_tokens=24).generate_many([prompt])[0]
    assert moved.token_ids == cold.token_ids
    _assert_balanced(dec)
    # a closed adopter refuses further feeds (late frames after the kill)
    with pytest.raises(ValueError):
        ad.feed(blobs[1])


def test_tier_mismatch_stream_aborts_clean():
    """Donor int8, decode-side bf16: the first segment is incompatible —
    the adopter self-abandons, raises for the caller's fallback, and the
    decode engine stays balanced and cold-correct."""
    pf, dec = _paged("int8"), _paged(None)
    prompt = _prompt(pf)
    blobs = []
    ContinuousBatcher(pf, chunk_steps=16, max_new_tokens=8).prefill_export(
        prompt, stream_blocks=2, emit=blobs.append)
    assert blobs
    ad = handoff.StreamAdopter(dec)
    with pytest.raises(ValueError, match="incompatible"):
        ad.feed(blobs[0])
    assert ad.closed
    _assert_balanced(dec)
    r = ContinuousBatcher(dec, chunk_steps=16,
                          max_new_tokens=16).generate_many([prompt])[0]
    assert r.error is None


def test_out_of_order_segment_aborts():
    """A skipped segment (start_block ahead of the frontier) must abort:
    adopting a gapped chain would serve wrong KV."""
    pf, dec = _paged(), _paged()
    prompt = _prompt(pf)
    blobs = []
    ContinuousBatcher(pf, chunk_steps=16, max_new_tokens=8).prefill_export(
        prompt, stream_blocks=1, emit=blobs.append)
    assert len(blobs) >= 2
    ad = handoff.StreamAdopter(dec)
    with pytest.raises(ValueError, match="incompatible|out of order"):
        ad.feed(blobs[1])  # second segment first
    _assert_balanced(dec)


# ------------------------------------------------------- router placement


def test_role_tags_and_prefill_env_build_the_pools(monkeypatch):
    monkeypatch.setenv("ROUTER_PREFILL_REPLICAS", "http://pf2,http://pf2")
    r = BrainRouter(["http://d0", "http://pf1#prefill", "http://d1#decode"],
                    disagg=True)
    roles = {m.url: m.role for m in r.replicas}
    assert roles == {"http://d0": "both", "http://pf1": "prefill",
                     "http://d1": "decode", "http://pf2": "prefill"}
    assert r.exclude_roles == {"prefill"}
    # sticky placement never lands on a prefill member
    for i in range(40):
        home = r.route(f"sess-{i}")
        assert home is not None and home.role != "prefill"
    # the prefill picker only returns prefill members, least-inflight
    pf = r._pick_prefill(exclude=set())
    assert pf is not None and pf.role == "prefill"
    assert r._pick_prefill(exclude={"http://pf1", "http://pf2"}) is None


def test_all_prefill_ring_still_serves():
    """Degraded beats error: if role filtering would empty the ring,
    every member serves (same contract as all-over-pressure)."""
    r = BrainRouter(["http://pf1#prefill", "http://pf2#prefill"],
                    disagg=True)
    assert r.route("s") is not None


def test_probe_role_refines_but_both_never_clears_a_tag():
    r = BrainRouter(["http://a#prefill", "http://b"], disagg=True)
    a = r._by_url["http://a"]
    b = r._by_url["http://b"]
    # a member that never set BRAIN_ROLE reports the "both" default — it
    # must NOT clear the router-side tag
    r.apply_probe(a, True, {"status": "ok", "role": "both"})
    assert a.role == "prefill"
    r.apply_probe(b, True, {"status": "ok", "role": "decode"})
    assert b.role == "decode"
    r.apply_probe(b, True, {"status": "ok", "role": "prefill"})
    assert b.role == "prefill"


def test_uncached_estimate_cold_sticky_rehomed():
    r = BrainRouter(["http://d0"], disagg=True)
    body = {"text": "w" * 400, "context": {}}
    cold = r._uncached_estimate("s1", body)
    assert cold >= 100  # ~len/4: a long cold prompt clears the gate
    # sticky with a warm cache: only the delta plus the new turn counts
    import httpx
    r._sessions["s1"] = "http://d0"
    r._note_session_tokens("s1", "http://d0", httpx.Response(
        200, headers={"x-prompt-tokens": "600", "x-cached-tokens": "590"}))
    sticky = r._uncached_estimate("s1", body)
    assert sticky < cold + 20 and sticky >= 10
    # re-homed (recorded home differs): the whole transcript re-prefills
    r._sessions["s1"] = "http://elsewhere"
    rehomed = r._uncached_estimate("s1", body)
    assert rehomed >= 600


def test_disagg_unset_is_byte_identical():
    """ROUTER_DISAGG unset: no role exclusion, no session-token tracking,
    every disagg counter absent/zero, members all report role 'both' —
    the pre-disagg router, exactly."""
    import os
    assert os.environ.get("ROUTER_DISAGG") is None
    r = BrainRouter(["http://d0", "http://d1"])
    assert r.disagg is False
    assert r.exclude_roles == set()
    assert all(m.role == "both" for m in r.replicas)
    assert r._session_tokens == {}
    # describe() carries no role key for "both" members (wire unchanged)
    assert all("role" not in m.describe() for m in r.replicas)
    stats = r.disagg_stats()
    assert stats["enabled"] is False
