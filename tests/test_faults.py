"""Fault injection + failure detection (SURVEY.md §5 rebuild notes).

The reference's recovery story is manual (README.md:273-276: a dead browser
is replaced on the next command). Here faults are injectable at every seam
— STT stream, decode lane, fake page — and the serving loops survive them.
"""

import asyncio
import json

import numpy as np
import pytest

from tpu_voice_agent.serve.colocate import ColocatedServing
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.stt import NullSTT, SpeechEngine


def _prompt(utterance: str) -> str:
    import json

    user = json.dumps({"text": utterance, "context": {}}, separators=(",", ":"))
    return f"<|user|>\n{user}\n<|assistant|>\n"


def test_null_stt_fault_injection():
    stt = NullSTT(scripted=[("final", "hello")])
    stt.fail_next = True
    with pytest.raises(RuntimeError, match="injected STT fault"):
        stt.feed(np.zeros(160, np.float32))
    # one-shot: the stream recovers on the next frame
    assert stt.feed(np.zeros(160, np.float32)) == [("final", "hello")]


def test_voice_session_survives_stt_fault():
    """A bad frame emits a warn and the WS session keeps going (same
    contract as the reference's per-frame error isolation)."""
    import asyncio
    import json

    import aiohttp

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.voice import VoiceConfig, build_app

    stt = NullSTT(scripted=[("partial", "still alive")])
    stt.fail_next = True
    app = build_app(VoiceConfig(stt_factory=lambda: stt,
                                brain_url="http://127.0.0.1:1",
                                executor_url="http://127.0.0.1:1"))

    async def drive(url):
        events = []
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(url.replace("http", "ws") + "/stream") as ws:
                frame = np.zeros(1600, "<i2").tobytes()
                await ws.send_bytes(frame)  # hits the injected fault
                await ws.send_bytes(frame)  # stream must have recovered
                # (asyncio.timeout is 3.11+; receive(timeout=) spells the
                # same bound on every supported interpreter)
                end = asyncio.get_event_loop().time() + 20
                while asyncio.get_event_loop().time() < end:
                    try:
                        msg = await ws.receive(timeout=1.0)
                    except asyncio.TimeoutError:
                        continue
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        break
                    events.append(json.loads(msg.data))
                    if any(e["type"] == "transcript_partial" for e in events):
                        break
        return events

    with AppServer(app) as srv:
        events = asyncio.run(drive(srv.url))
    assert any("bad audio frame" in e.get("message", "")
               for e in events if e["type"] == "warn")
    assert any(e["type"] == "transcript_partial" and e["text"] == "still alive"
               for e in events)


class _BoomBatcher(ContinuousBatcher):
    """Batcher whose next step raises once (decode-lane fault)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.boom = False

    def step(self):
        if self.boom:
            self.boom = False
            raise RuntimeError("injected decode fault")
        super().step()


@pytest.fixture(scope="module")
def stt_engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(100,), max_new_tokens=4)


def test_colocated_loop_survives_decode_fault(stt_engine, tiny_batch_engine):
    co = ColocatedServing(stt_engine,
                          _BoomBatcher(tiny_batch_engine, chunk_steps=8, max_new_tokens=48))
    fut = co.submit_parse(_prompt("scroll down"))
    co.batcher.boom = True
    co.step()  # decode lane blows up
    assert co.stats.errors == 1
    with pytest.raises(RuntimeError, match="injected decode fault"):
        fut.result(timeout=1)  # inflight request failed fast, no hang
    # the loop still serves both lanes afterwards
    audio = np.zeros(3200, np.float32)
    stt_fut = co.submit_stt(audio)
    fut2 = co.submit_parse(_prompt("go back"))
    co.drain(timeout_s=300)
    assert stt_fut.result(timeout=1).n_frames > 0
    assert fut2.result(timeout=1).error is None


def test_worker_thread_healthy_probe(stt_engine, tiny_batch_engine):
    co = ColocatedServing(stt_engine, ContinuousBatcher(tiny_batch_engine, chunk_steps=8))
    assert not co.healthy()
    co.start()
    try:
        assert co.healthy()
    finally:
        co.stop()
    assert not co.healthy()


# ---------------------------------------------------------------------------
# Cross-service resilience drills (deadlines, breakers, degradation — the
# fault model SURVEY §5 says the reference only handles by hand).
# ---------------------------------------------------------------------------


class _WsDriver:
    """One LIVE WebSocket session across multiple commands — the whole point
    of the drills is that a single session survives the outage, so each
    command must NOT get a fresh connection the way test_voice.ws_session
    does."""

    def __init__(self, ws):
        self.ws = ws
        self.events: list[dict] = []

    async def command(self, text: str) -> None:
        await self.ws.send_json({"type": "text", "text": text})

    async def until(self, type_: str, timeout_s: float = 10.0) -> dict:
        import aiohttp

        loop = asyncio.get_event_loop()
        end = loop.time() + timeout_s
        while loop.time() < end:
            try:
                msg = await self.ws.receive(timeout=1.0)
            except asyncio.TimeoutError:
                continue
            assert msg.type == aiohttp.WSMsgType.TEXT, f"session dropped: {msg.type}"
            ev = json.loads(msg.data)
            self.events.append(ev)
            if ev["type"] == type_:
                return ev
        raise AssertionError(f"no {type_!r} event within {timeout_s}s; saw "
                             f"{[e['type'] for e in self.events]}")


def _voice_stack(tmp_path, brain_url: str, **cfg_kw):
    from tests.http_helper import AppServer
    from tpu_voice_agent.services.executor import SessionManager, build_app as build_executor
    from tpu_voice_agent.services.executor.page import FakePage
    from tpu_voice_agent.services.voice import VoiceConfig, build_app as build_voice

    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    voice = AppServer(build_voice(VoiceConfig(
        brain_url=brain_url,
        executor_url=cfg_kw.pop("executor_url", executor.url),
        stt_factory=lambda: NullSTT(),
        **cfg_kw,
    ))).__enter__()
    return voice, executor


def test_brain_down_degrades_to_rule_parse_then_recovers(tmp_path):
    """The acceptance drill: kill the brain mid-session. The SAME WS serves
    rule-based parses tagged degraded:true while the circuit is open (zero
    further brain roundtrips), /health reports degraded, and full parsing
    resumes automatically once the half-open probe finds the brain back."""
    import aiohttp
    from aiohttp import web

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser

    rule = RuleBasedParser()
    broken = {"on": False}
    calls = {"n": 0}

    async def parse(request):
        calls["n"] += 1
        if broken["on"]:
            return web.json_response({"error": "overloaded", "detail": "down"},
                                     status=503, headers={"Retry-After": "0"})
        body = await request.json()
        res = rule.parse(body["text"], body.get("context") or {})
        return web.json_response(json.loads(res.model_dump_json()))

    brain_app = web.Application()
    brain_app.router.add_post("/parse", parse)
    brain = AppServer(brain_app).__enter__()
    # reset window long enough that the zero-roundtrip assertion below
    # cannot race a half-open probe on a slow machine
    voice, executor = _voice_stack(
        tmp_path, brain.url,
        parse_timeout_s=5.0, retry_attempts=1,
        breaker_threshold=1, breaker_reset_s=2.0,
    )

    async def drive():
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(
                    voice.url.replace("http", "ws") + "/stream") as ws:
                d = _WsDriver(ws)

                # healthy brain: a normal (untagged) intent
                await d.command("scroll down")
                ev = await d.until("intent")
                assert "degraded" not in ev
                brain_calls_healthy = calls["n"]

                # brain dies: the 503 trips the breaker; the session gets a
                # rule-based parse tagged degraded — not a terminal error
                broken["on"] = True
                await d.command("scroll down")
                ev = await d.until("intent")
                assert ev["degraded"] is True
                assert ev["data"]["intents"][0]["type"] == "scroll"

                # circuit open: the next command degrades WITHOUT a roundtrip
                calls_after_trip = calls["n"]
                await d.command("search for lamps")
                ev = await d.until("intent")
                assert ev["degraded"] is True
                assert ev["data"]["intents"][0]["type"] == "search"
                assert calls["n"] == calls_after_trip

                # /health says degraded during the outage
                async with sess.get(voice.url + "/health") as r:
                    h = await r.json()
                assert h["status"] == "degraded" and h["breakers"]["brain"] != "closed"

                # brain recovers; after the reset window the half-open probe
                # succeeds and full parsing resumes, untagged
                broken["on"] = False
                await asyncio.sleep(2.2)  # past the 2.0s reset window
                await d.command("scroll down")
                ev = await d.until("intent")
                assert "degraded" not in ev
                assert calls["n"] > calls_after_trip

                async with sess.get(voice.url + "/health") as r:
                    h = await r.json()
                assert h["status"] == "ok"

                # counters surfaced through /metrics
                async with sess.get(voice.url + "/metrics") as r:
                    m = await r.json()
                counters = m["runtime"]["counters"]
                assert counters.get("voice.degraded_parses", 0) >= 2
                assert counters.get("resilience.brain.breaker_opened", 0) >= 1
                return brain_calls_healthy

    try:
        assert asyncio.run(drive()) >= 1
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


def test_executor_unreachable_reports_error_session_survives(tmp_path):
    """A dead executor produces execution_error events; the WS session (and
    the parse pipeline) keeps working."""
    import aiohttp

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app as build_brain

    brain = AppServer(build_brain(RuleBasedParser())).__enter__()
    voice, executor = _voice_stack(
        tmp_path, brain.url,
        executor_url="http://127.0.0.1:1",  # nothing listens here
        exec_timeout_s=5.0, retry_attempts=2,
        breaker_threshold=2, breaker_reset_s=60.0,
    )

    async def drive():
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(
                    voice.url.replace("http", "ws") + "/stream") as ws:
                d = _WsDriver(ws)
                await d.command("take a screenshot")
                await d.until("execution_error")
                # session still parses (and reports) the next command
                await d.command("take a screenshot")
                assert (await d.until("intent"))["data"]["intents"][0]["type"] == "screenshot"
                await d.until("execution_error")

    try:
        asyncio.run(drive())
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


def test_brain_sheds_expired_deadline_before_decode():
    """An x-deadline-ms budget of 0 is shed with 503 + Retry-After before
    any parser work; a live budget parses normally."""
    import httpx

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app as build_brain
    from tpu_voice_agent.utils import get_metrics

    with AppServer(build_brain(RuleBasedParser())) as srv:
        shed0 = get_metrics().snapshot()["counters"].get("brain.shed_deadline_expired", 0)
        r = httpx.post(srv.url + "/parse", json={"text": "scroll down"},
                       headers={"x-deadline-ms": "0"})
        assert r.status_code == 503
        assert "Retry-After" in r.headers
        assert r.json()["error"] == "overloaded"
        shed = get_metrics().snapshot()["counters"].get("brain.shed_deadline_expired", 0)
        assert shed - shed0 == 1

        r = httpx.post(srv.url + "/parse", json={"text": "scroll down"},
                       headers={"x-deadline-ms": "30000"})
        assert r.status_code == 200


def test_executor_sheds_expired_deadline():
    import httpx

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.executor import SessionManager, build_app as build_executor
    from tpu_voice_agent.services.executor.page import FakePage

    manager = SessionManager(page_factory=FakePage.demo)
    with AppServer(build_executor(manager)) as srv:
        r = httpx.post(srv.url + "/execute",
                       json={"intents": [{"type": "screenshot"}]},
                       headers={"x-deadline-ms": "0"})
        assert r.status_code == 503 and "Retry-After" in r.headers
        r = httpx.post(srv.url + "/execute",
                       json={"intents": [{"type": "screenshot"}]},
                       headers={"x-deadline-ms": "30000"})
        assert r.status_code == 200


def test_brain_sheds_overload_at_inflight_cap():
    """Past the inflight cap /parse answers 503 + Retry-After immediately
    instead of queueing behind the busy parser."""
    import threading

    import httpx

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app as build_brain

    entered = threading.Event()
    gate = threading.Event()
    rule = RuleBasedParser()

    class SlowParser:
        def parse(self, text, context):
            entered.set()
            assert gate.wait(10)
            return rule.parse(text, context)

    with AppServer(build_brain(SlowParser(), max_inflight=1)) as srv:
        results = []
        t = threading.Thread(target=lambda: results.append(
            httpx.post(srv.url + "/parse", json={"text": "scroll down"},
                       timeout=15)))
        t.start()
        try:
            assert entered.wait(5)  # first request is admitted and decoding
            r = httpx.post(srv.url + "/parse", json={"text": "scroll down"})
            assert r.status_code == 503 and "Retry-After" in r.headers
            # health still answers while saturated, and says so
            h = httpx.get(srv.url + "/health")
            assert h.status_code == 200 and h.json()["status"] == "degraded"
        finally:
            gate.set()
            t.join(timeout=10)
        assert results and results[0].status_code == 200
        h = httpx.get(srv.url + "/health")
        assert h.json()["status"] == "ok"


class _DeadableBatcher:
    """Fake batcher (no engine, no jax): completes one pending request per
    step, or kills the worker THREAD outright when armed — SystemExit is not
    an Exception, so it escapes the loop's survival handler exactly like an
    interpreter-level thread death."""

    def __init__(self):
        self.pending: list = []
        self.slots: list = []
        self.results: dict = {}
        self.die = False
        self._n = 0

    def submit(self, prompt: str, deadline=None) -> int:
        rid, self._n = self._n, self._n + 1
        self.pending.append((rid, prompt))
        return rid

    def cancel(self, rid: int, reason: str = "client gone") -> bool:
        live = [(r, p) for (r, p) in self.pending if r != rid]
        found = len(live) != len(self.pending)
        self.pending = live
        return found

    def step(self) -> None:
        if self.die:
            self.die = False
            raise SystemExit("injected worker death")
        if self.pending:
            rid, prompt = self.pending.pop(0)
            self.results[rid] = f"done:{prompt}"

    def reset(self) -> None:
        self.pending = []


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_watchdog_restarts_dead_worker_and_fails_inflight_fast():
    import time

    co = ColocatedServing(None, _DeadableBatcher())
    co.start()
    co.start_watchdog(interval_s=0.05)
    try:
        co.batcher.die = True
        fut = co.submit_parse("doomed")  # wakes the worker into SystemExit
        with pytest.raises(RuntimeError, match="worker died"):
            fut.result(timeout=5)  # failed fast by the watchdog, no hang
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not co.healthy():
            time.sleep(0.01)
        assert co.healthy(), "watchdog did not restart the serving loop"
        assert co.stats.restarts == 1
        fut2 = co.submit_parse("revived")
        assert fut2.result(timeout=5) == "done:revived"
    finally:
        co.stop()
    assert not co.healthy()
