"""Fault injection + failure detection (SURVEY.md §5 rebuild notes).

The reference's recovery story is manual (README.md:273-276: a dead browser
is replaced on the next command). Here faults are injectable at every seam
— STT stream, decode lane, fake page — and the serving loops survive them.
"""

import numpy as np
import pytest

from tpu_voice_agent.serve.colocate import ColocatedServing
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.stt import NullSTT, SpeechEngine


def _prompt(utterance: str) -> str:
    import json

    user = json.dumps({"text": utterance, "context": {}}, separators=(",", ":"))
    return f"<|user|>\n{user}\n<|assistant|>\n"


def test_null_stt_fault_injection():
    stt = NullSTT(scripted=[("final", "hello")])
    stt.fail_next = True
    with pytest.raises(RuntimeError, match="injected STT fault"):
        stt.feed(np.zeros(160, np.float32))
    # one-shot: the stream recovers on the next frame
    assert stt.feed(np.zeros(160, np.float32)) == [("final", "hello")]


def test_voice_session_survives_stt_fault():
    """A bad frame emits a warn and the WS session keeps going (same
    contract as the reference's per-frame error isolation)."""
    import asyncio
    import json

    import aiohttp

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.voice import VoiceConfig, build_app

    stt = NullSTT(scripted=[("partial", "still alive")])
    stt.fail_next = True
    app = build_app(VoiceConfig(stt_factory=lambda: stt,
                                brain_url="http://127.0.0.1:1",
                                executor_url="http://127.0.0.1:1"))

    async def drive(url):
        events = []
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(url.replace("http", "ws") + "/stream") as ws:
                frame = np.zeros(1600, "<i2").tobytes()
                await ws.send_bytes(frame)  # hits the injected fault
                await ws.send_bytes(frame)  # stream must have recovered
                async with asyncio.timeout(20):
                    async for msg in ws:
                        events.append(json.loads(msg.data))
                        if any(e["type"] == "transcript_partial" for e in events):
                            break
        return events

    with AppServer(app) as srv:
        events = asyncio.run(drive(srv.url))
    assert any("bad audio frame" in e.get("message", "")
               for e in events if e["type"] == "warn")
    assert any(e["type"] == "transcript_partial" and e["text"] == "still alive"
               for e in events)


class _BoomBatcher(ContinuousBatcher):
    """Batcher whose next step raises once (decode-lane fault)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.boom = False

    def step(self):
        if self.boom:
            self.boom = False
            raise RuntimeError("injected decode fault")
        super().step()


@pytest.fixture(scope="module")
def stt_engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(100,), max_new_tokens=4)


def test_colocated_loop_survives_decode_fault(stt_engine, tiny_batch_engine):
    co = ColocatedServing(stt_engine,
                          _BoomBatcher(tiny_batch_engine, chunk_steps=8, max_new_tokens=48))
    fut = co.submit_parse(_prompt("scroll down"))
    co.batcher.boom = True
    co.step()  # decode lane blows up
    assert co.stats.errors == 1
    with pytest.raises(RuntimeError, match="injected decode fault"):
        fut.result(timeout=1)  # inflight request failed fast, no hang
    # the loop still serves both lanes afterwards
    audio = np.zeros(3200, np.float32)
    stt_fut = co.submit_stt(audio)
    fut2 = co.submit_parse(_prompt("go back"))
    co.drain(timeout_s=300)
    assert stt_fut.result(timeout=1).n_frames > 0
    assert fut2.result(timeout=1).error is None


def test_worker_thread_healthy_probe(stt_engine, tiny_batch_engine):
    co = ColocatedServing(stt_engine, ContinuousBatcher(tiny_batch_engine, chunk_steps=8))
    assert not co.healthy()
    co.start()
    try:
        assert co.healthy()
    finally:
        co.stop()
    assert not co.healthy()
