"""Invariant-firewall tests (ISSUE 11, ``tools/analyze``).

Each checker is proven BOTH ways on tmp-tree fixtures — it catches a
seeded violation and stays silent on the clean twin — because a lint that
only has positive tests rots into noise and one that only has negative
tests rots into a no-op. Plus the suppression contract (inline marker,
justification required, baseline round-trip incl. stale detection) and
the tier-1 tree-clean gate: the REAL repo, with its REAL baseline, must
be analyzer-clean on every commit.

All fast-tier: pure AST, no jax import, no services.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools.analyze import run  # noqa: E402
from tools.analyze import metrics_catalog  # noqa: E402
from tools.analyze.__main__ import main as analyze_main  # noqa: E402


# ------------------------------------------------------------- fixtures


def make_tree(tmp_path: Path, files: dict[str, str]) -> Path:
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return tmp_path


def run_only(root: Path, checker: str, baseline: Path | None = None):
    """(live, suppressed) for one checker over a tmp tree. The default
    baseline is a path that does not exist — tmp trees never see the real
    repo's baseline."""
    return run(repo_root=root, baseline=baseline or root / "no_baseline.json",
               only={checker})


def keys(findings) -> set[str]:
    return {f.key for f in findings}


# ----------------------------------------------------------- jit-sentinel


def test_jit_sentinel_catches_unwrapped_def_stored_and_order(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import jax
        from functools import partial
        from .utils.compilewatch import watch_compiles

        @jax.jit
        def naked(x):
            return x

        @partial(jax.jit, static_argnames=("k",))
        def naked_partial(x, k):
            return x

        stored = jax.jit(lambda x: x)

        @jax.jit
        @watch_compiles("mod.inside_out")
        def inside_out(x):
            return x
        """})
    live, _ = run_only(root, "jit-sentinel")
    assert {"naked", "naked_partial", "stored", "inside_out:order"} <= keys(live)


def test_jit_sentinel_passes_wrapped_and_immediate_invoke(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import jax
        from functools import partial
        from .utils.compilewatch import watch_compiles

        @watch_compiles("mod.good")
        @jax.jit
        def good(x):
            return x

        @watch_compiles("mod.good_partial")
        @partial(jax.jit, static_argnames=("k",))
        def good_partial(x, k):
            return x

        stored = watch_compiles("mod.stored")(jax.jit(lambda x: x))
        one_shot = jax.jit(lambda: 0)()  # immediately invoked: init compile
        """})
    live, _ = run_only(root, "jit-sentinel")
    assert live == []


# --------------------------------------------------------- async-blocking


def test_async_blocking_catches_loop_stalls(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/services/svc.py": """
        import time, requests, httpx

        async def handler(engine, fut):
            time.sleep(1)
            requests.get("http://x")
            httpx.post("http://x")
            fut.result()
            engine.generate("prompt")
        """})
    live, _ = run_only(root, "async-blocking")
    assert {"handler:time.sleep", "handler:requests.get", "handler:httpx.post",
            "handler:fut.result", "handler:engine.generate"} <= keys(live)


def test_async_blocking_passes_offload_idiom_and_sync_code(tmp_path):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/services/svc.py": """
        import asyncio, time

        def sync_path(engine):
            time.sleep(0.1)  # not on the loop: no finding
            return engine.generate("p")

        async def handler(loop, engine):
            def work():
                time.sleep(0.1)  # worker thread: the offload idiom
                return engine.generate("p")
            await asyncio.sleep(0)
            return await loop.run_in_executor(None, work)
        """,
        # blocking calls OUTSIDE services/ are out of scope for this checker
        "tpu_voice_agent/serve/eng.py": """
        import time

        async def warmup():
            time.sleep(0.1)
        """})
    live, _ = run_only(root, "async-blocking")
    assert live == []


# --------------------------------------------------------- atomic-section


def test_atomic_section_catches_suspension_and_marker_imbalance(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/services/r.py": """
        # end-atomic-section

        async def mutate(state, q):
            # atomic-section: table-update -- must commit in one loop step
            state["a"] = 1
            await q.put(state)
            state["b"] = 2
            # end-atomic-section

        async def unclosed(state):
            # atomic-section: never-closed -- oops
            state["c"] = 3
        """})
    live, _ = run_only(root, "atomic-section")
    ks = keys(live)
    assert "table-update:await" in ks
    assert "never-closed:unclosed" in ks
    assert any(k.startswith("unopened@") for k in ks)


def test_atomic_section_passes_await_free_region(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/services/r.py": """
        async def mutate(state, q):
            # atomic-section: table-update -- must commit in one loop step
            state["a"] = 1
            state["b"] = 2
            # end-atomic-section
            await q.put(state)
        """})
    live, _ = run_only(root, "atomic-section")
    assert live == []


# --------------------------------------------------------------- env-knob


_KNOBS_HEADER = """
    KNOBS = {}

    def declare(name, default, doc, table=None):
        KNOBS[name] = (default, doc, table)
"""


def test_env_knob_catches_undeclared_undocumented_stale_and_dynamic(tmp_path):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/utils/knobs.py": _KNOBS_HEADER + """
        declare("DOCLESS_KNOB", "1", "declared for PERF but missing its row", table="docs/PERF.md")
        declare("STALE_KNOB", "1", "declared but nothing reads it", table=None)
        """,
        "tpu_voice_agent/mod.py": """
        import os
        a = os.environ.get("UNDECLARED_KNOB")
        b = os.environ.get("DOCLESS_KNOB")
        c = os.getenv(compute_name())
        """,
        "docs/PERF.md": """
        | knob | default | meaning |
        |---|---|---|
        | `ORPHAN_KNOB` | 1 | documented but never declared |
        """})
    live, _ = run_only(root, "env-knob")
    ks = keys(live)
    assert "UNDECLARED_KNOB" in ks
    assert "DOCLESS_KNOB:undocumented" in ks
    assert "STALE_KNOB:unread" in ks
    assert "ORPHAN_KNOB:orphan" in ks
    assert "dynamic-env-read" in ks


def test_env_knob_registry_accessor_is_validated(tmp_path):
    """knobs.get("NAME") call sites resolve NAME against the registry like
    any raw env read — migrating a read to the accessor must not orphan
    the declaration (':unread') or skip validation of the literal."""
    root = make_tree(tmp_path, {
        "tpu_voice_agent/utils/knobs.py": _KNOBS_HEADER + """
        declare("VIA_ACCESSOR", "1", "read only through knobs.get")
        """,
        "tpu_voice_agent/mod.py": """
        from .utils import knobs
        a = knobs.get("VIA_ACCESSOR")
        b = knobs.get("ACCESSOR_UNDECLARED")
        """})
    live, _ = run_only(root, "env-knob")
    ks = keys(live)
    assert "ACCESSOR_UNDECLARED" in ks
    assert "VIA_ACCESSOR:unread" not in ks


def test_knob_accessors_fall_back_to_declared_defaults():
    """The runtime half of the registry: accessors honor the DECLARED
    default when the env is unset (knob_bool regression: it used to
    override the declared default with its own '' fallback)."""
    from tpu_voice_agent.utils import knobs
    assert "STEPLOG_ENABLE" not in __import__("os").environ
    assert knobs.get("STEPLOG_ENABLE") == "1"  # declared default
    assert knobs.knob_bool("STEPLOG_ENABLE") is True
    assert knobs.knob_bool("STEPLOG_ENABLE", default=False) is False  # override
    assert knobs.knob_bool("SPEC_ENABLE") is False  # declared default None
    assert knobs.knob_int("STEPLOG_STEPS") == 256
    with pytest.raises(KeyError):
        knobs.get("NOT_A_DECLARED_KNOB")


def test_env_knob_passes_declared_documented_read_knob(tmp_path):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/utils/knobs.py": _KNOBS_HEADER + """
        declare("GOOD_KNOB", "1", "a documented tunable", table="docs/PERF.md")
        declare("INFRA_KNOB", None, "harness plumbing, deliberately undocumented")
        """,
        "tpu_voice_agent/mod.py": """
        import os
        from .utils import knobs
        a = os.environ.get("GOOD_KNOB")
        b = os.getenv("INFRA_KNOB")
        c = knobs.get("GOOD_KNOB")  # the registry accessor counts as a read
        """,
        "docs/PERF.md": """
        | knob | default | meaning |
        |---|---|---|
        | `GOOD_KNOB` | 1 | a documented tunable |
        """})
    live, _ = run_only(root, "env-knob")
    assert live == []


def test_env_knob_catches_infra_knob_with_doc_row_and_wrong_table(tmp_path):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/utils/knobs.py": _KNOBS_HEADER + """
        declare("INFRA_KNOB", None, "infrastructure", table=None)
        declare("PERF_KNOB", "1", "lives in PERF", table="docs/PERF.md")
        """,
        "tpu_voice_agent/mod.py": """
        import os
        a = os.environ.get("INFRA_KNOB")
        b = os.environ.get("PERF_KNOB")
        """,
        "docs/PERF.md": """
        | knob | default | meaning |
        |---|---|---|
        | `INFRA_KNOB` | - | should not be documented |
        | `PERF_KNOB` | 1 | correctly here |
        """,
        "docs/RESILIENCE.md": """
        | knob | default | meaning |
        |---|---|---|
        | `PERF_KNOB` | 1 | drifted into the wrong doc |
        """})
    live, _ = run_only(root, "env-knob")
    ks = keys(live)
    assert "INFRA_KNOB:infra-documented" in ks
    assert "PERF_KNOB:wrong-table" in ks


# ---------------------------------------------------------- traced-purity


def test_traced_purity_catches_host_nondeterminism(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import os, time
        import jax
        import numpy as np
        from jax import lax

        @jax.jit
        def traced(x):
            t = time.time()
            seed = os.environ.get("SEED")
            n = np.random.rand()
            print("tracing", x)
            return x + t + n

        def body(carry, x):
            time.sleep_val = time.monotonic()
            return carry, x

        def scanned(xs):
            return lax.scan(body, 0, xs)
        """})
    live, _ = run_only(root, "traced-purity")
    ks = keys(live)
    assert "traced:time.time" in ks
    assert "traced:os.environ.get" in ks
    assert "traced:np.random.rand" in ks
    assert "traced:print" in ks
    assert "body:time.monotonic" in ks  # via lax.scan


def test_traced_purity_passes_host_code_and_debug_print(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import time
        import jax

        def host_side():
            return time.time()  # untraced: fine

        @jax.jit
        def traced(x):
            jax.debug.print("step {x}", x=x)  # the traced-safe spelling
            return x * 2
        """})
    live, _ = run_only(root, "traced-purity")
    assert live == []


# -------------------------------------------------------- metrics-catalog


@pytest.fixture
def pinned_off(monkeypatch):
    """Tmp trees register none of the real repo's pinned names — silence
    the pin gate so fixtures test collisions/catalog sync in isolation."""
    ml = metrics_catalog._lint()
    monkeypatch.setattr(ml, "PINNED", {})
    return ml


def test_metrics_catalog_catches_collision_and_two_way_drift(tmp_path, pinned_off):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/mod.py": """
        def record(m):
            m.inc("svc.requests")
            m.set_gauge("svc.requests", 1)  # KIND COLLISION
            m.inc("svc.undocumented")
        """,
        "docs/OBSERVABILITY.md": """
        | name | type | meaning |
        |---|---|---|
        | `svc.requests` | counter | requests |
        | `svc.gone` | gauge | documented but not registered |
        """})
    live, _ = run_only(root, "metrics-catalog")
    ks = keys(live)
    assert "collision:svc.requests" in ks
    assert "catalog:svc.undocumented" in ks
    assert "catalog:svc.gone" in ks


def test_metrics_catalog_passes_synced_tree(tmp_path, pinned_off):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/mod.py": """
        def record(m):
            m.inc("svc.requests")
            m.set_gauge("svc.depth", 2)
        """,
        "docs/OBSERVABILITY.md": """
        | name | type | meaning |
        |---|---|---|
        | `svc.requests` | counter | requests |
        | `svc.depth` | gauge | queue depth |
        """})
    live, _ = run_only(root, "metrics-catalog")
    assert live == []


def test_metrics_catalog_catches_wrong_documented_type(tmp_path, pinned_off):
    root = make_tree(tmp_path, {
        "tpu_voice_agent/mod.py": """
        def record(m):
            m.inc("svc.requests")
        """,
        "docs/OBSERVABILITY.md": """
        | name | type | meaning |
        |---|---|---|
        | `svc.requests` | gauge | documented as the WRONG kind |
        """})
    live, _ = run_only(root, "metrics-catalog")
    assert "catalog:svc.requests" in keys(live)


def test_env_knob_catches_default_drift_and_tolerates_equivalents(tmp_path):
    """A call-site literal default must agree with the declaration (the
    three-copies-of-a-default drift class); numeric/unset-class
    equivalence is tolerated so '2.0' vs 2 is not noise."""
    root = make_tree(tmp_path, {
        "tpu_voice_agent/utils/knobs.py": _KNOBS_HEADER + """
        declare("DRIFTY", "8", "declared 8")
        declare("NUMERIC", "2.0", "declared 2.0")
        declare("OFFISH", None, "declared unset-means-off")
        """,
        "tpu_voice_agent/mod.py": """
        import os
        a = int(os.environ.get("DRIFTY", "0"))   # DRIFT: 0 != 8
        b = float(os.getenv("NUMERIC", 2))       # ok: 2 == 2.0
        c = os.environ.get("OFFISH", "")         # ok: "" == unset class
        """})
    live, _ = run_only(root, "env-knob")
    ks = keys(live)
    assert "DRIFTY:default-drift" in ks
    assert not any(k.startswith(("NUMERIC:", "OFFISH:")) for k in ks)


def test_async_blocking_catches_result_with_timeout(tmp_path):
    """fut.result(timeout=5) parks the loop up to 5 s — the no-args-only
    guard used to let it through."""
    root = make_tree(tmp_path, {"tpu_voice_agent/services/svc.py": """
        async def handler(fut):
            return fut.result(timeout=5)
        """})
    live, _ = run_only(root, "async-blocking")
    assert "handler:fut.result" in keys(live)


def test_unparseable_file_is_a_finding_not_a_silent_pass(tmp_path):
    """tree=None makes every checker skip the file — the suite must emit
    a syntax-error finding or the firewall exits 0 on a broken tree."""
    root = make_tree(tmp_path, {
        "tpu_voice_agent/mod.py": "def broken(:\n",
    })
    live, _ = run_only(root, "jit-sentinel")
    assert any(f.checker == "syntax-error" and f.path.endswith("mod.py")
               for f in live)


def test_metrics_catalog_universal_family_does_not_hide_stale_rows(tmp_path, pinned_off):
    """The tracer's ``{service}.{span}`` histogram normalizes to ``*.*``
    and matches every dotted string — it must not vouch for stale doc rows
    of OTHER kinds, only for span-shaped histogram rows."""
    root = make_tree(tmp_path, {
        "tpu_voice_agent/mod.py": """
        def record(m, service, span):
            m.observe_ms(f"{service}.{span}", 1.0)
        """,
        "docs/OBSERVABILITY.md": """
        | name | type | meaning |
        |---|---|---|
        | `svc.some_span` | histogram | per-span latency (the family's row) |
        | `svc.totally_gone` | gauge | deleted metric whose row rotted |
        """})
    live, _ = run_only(root, "metrics-catalog")
    ks = keys(live)
    assert "catalog:svc.totally_gone" in ks
    assert "catalog:svc.some_span" not in ks


# ------------------------------------------------------------ suppression


_VIOLATION = """
    import jax

    @jax.jit
    def naked(x):
        return x
"""


def test_inline_suppression_with_justification_suppresses(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import jax

        # analyze: ok[jit-sentinel] -- unit-test fixture, not a dispatch site
        @jax.jit
        def naked(x):
            return x
        """})
    live, suppressed = run_only(root, "jit-sentinel")
    assert live == []
    assert keys(suppressed) == {"naked"}


def test_inline_suppression_without_justification_is_a_finding(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import jax

        # analyze: ok[jit-sentinel]
        @jax.jit
        def naked(x):
            return x
        """})
    live, suppressed = run_only(root, "jit-sentinel")
    assert suppressed == []
    assert any(k.endswith(":no-justification") for k in keys(live))
    assert "naked" in keys(live)  # the original finding survives too


def test_inline_suppression_for_other_checker_does_not_apply(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": """
        import jax

        # analyze: ok[traced-purity] -- wrong checker id
        @jax.jit
        def naked(x):
            return x
        """})
    live, _ = run_only(root, "jit-sentinel")
    assert "naked" in keys(live)


def test_baseline_round_trip_and_stale_detection(tmp_path):
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": _VIOLATION})
    baseline = root / "baseline.json"

    # 1. no baseline: the finding is live
    live, _ = run_only(root, "jit-sentinel", baseline)
    assert keys(live) == {"naked"}

    # 2. a justified baseline entry suppresses it
    baseline.write_text(json.dumps({"suppressions": [
        {"checker": "jit-sentinel", "path": "tpu_voice_agent/mod.py",
         "key": "naked", "justification": "fixture for the round-trip test"},
    ]}))
    live, suppressed = run_only(root, "jit-sentinel", baseline)
    assert live == []
    assert keys(suppressed) == {"naked"}

    # 3. justification-less entries do NOT suppress and are findings
    baseline.write_text(json.dumps({"suppressions": [
        {"checker": "jit-sentinel", "path": "tpu_voice_agent/mod.py",
         "key": "naked", "justification": "   "},
    ]}))
    live, suppressed = run_only(root, "jit-sentinel", baseline)
    assert suppressed == []
    assert "naked" in keys(live)
    assert any("no" in f.message and "justification" in f.message for f in live)

    # 4. an entry that outlived its violation is a stale finding
    (root / "tpu_voice_agent/mod.py").write_text("x = 1\n")
    baseline.write_text(json.dumps({"suppressions": [
        {"checker": "jit-sentinel", "path": "tpu_voice_agent/mod.py",
         "key": "naked", "justification": "now stale"},
    ]}))
    live, _ = run_only(root, "jit-sentinel", baseline)
    assert any(k.startswith("stale:") for k in keys(live))


def test_baseline_key_survives_line_churn(tmp_path):
    """Finding identity is (checker, path, key) with a SYMBOL key — adding
    lines above the violation must not invalidate the suppression."""
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": _VIOLATION})
    baseline = root / "baseline.json"
    baseline.write_text(json.dumps({"suppressions": [
        {"checker": "jit-sentinel", "path": "tpu_voice_agent/mod.py",
         "key": "naked", "justification": "churn-stability fixture"},
    ]}))
    live, _ = run_only(root, "jit-sentinel", baseline)
    assert live == []
    src = (root / "tpu_voice_agent/mod.py").read_text()
    (root / "tpu_voice_agent/mod.py").write_text(
        "# pushed\n# down\n# by\n# comments\n" + src)
    live, _ = run_only(root, "jit-sentinel", baseline)
    assert live == []


# --------------------------------------------------------- tree-clean gate


def test_repo_tree_is_analyzer_clean():
    """THE gate: the real repo, real baseline, all six checkers, zero live
    findings. Every suppression in the tree carries a justification (a
    bare marker or justification-less baseline entry would be a live
    finding and fail right here)."""
    live, suppressed = run(repo_root=REPO_ROOT)
    assert live == [], "analyzer findings on the tree:\n" + "\n".join(
        f.format() for f in live)
    assert suppressed, "expected the tree's documented suppressions to apply"


def test_cli_exit_codes(tmp_path):
    assert analyze_main([]) == 0  # the real tree, via the CLI entry point
    root = make_tree(tmp_path, {"tpu_voice_agent/mod.py": _VIOLATION})
    rc = analyze_main(["--root", str(root),
                       "--baseline", str(root / "nope.json")])
    assert rc == 1


def test_cli_module_invocation():
    """`python -m tools.analyze` — exactly what run_all.py and operators
    run — exits 0 on the tree."""
    proc = subprocess.run([sys.executable, "-m", "tools.analyze"],
                          cwd=REPO_ROOT, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_unknown_checker_id_rejected():
    with pytest.raises(SystemExit):
        analyze_main(["--only", "no-such-checker"])
