"""Continuous batching: concurrent slots must be isolated and all outputs
grammar-valid; batch composition must not change a greedy request's tokens."""

import pytest

from tpu_voice_agent.schemas import parse_response_from_json
from tpu_voice_agent.serve.scheduler import ContinuousBatcher


@pytest.fixture()
def batcher(tiny_batch_engine):
    return ContinuousBatcher(tiny_batch_engine, chunk_steps=16, max_new_tokens=300)


PROMPTS = [
    "search for laptops under 1000",
    "upload my resume and submit",
    "take a screenshot of this page",
]


def _assert_grammar_consistent(batcher, r):
    """Finished outputs must validate; truncated ones must be live DFA
    prefixes (the constraint never went off the rails mid-decode)."""
    if r.finished:
        model, err = parse_response_from_json(r.text)
        assert model is not None, f"finished slot failed schema: {err} :: {r.text[:100]}"
    else:
        state = batcher.engine.fsm.walk(r.token_ids)
        assert state >= 0, f"truncated slot left the grammar: {r.text[:100]}"


def test_batched_outputs_are_all_grammar_consistent(batcher):
    results = batcher.generate_many(PROMPTS)
    assert len(results) == 3
    for r in results:
        _assert_grammar_consistent(batcher, r)


def test_batch_composition_does_not_change_greedy_output(batcher):
    """Trash-slot isolation: a greedy request decodes identically whether it
    runs alone or alongside other slots."""
    solo = batcher.generate_many([PROMPTS[0]])[0]
    packed = batcher.generate_many(PROMPTS)[0]
    assert solo.token_ids == packed.token_ids


def test_more_requests_than_slots_queue_up(batcher):
    results = batcher.generate_many(PROMPTS + ["scroll down", "go back"])
    assert len(results) == 5
    for r in results:
        _assert_grammar_consistent(batcher, r)
