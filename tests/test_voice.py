"""Voice service: WS event vocabulary + the FULL pipeline end to end.

The crown-jewel test boots all three real services (voice with scripted STT,
brain with the rule parser, executor with the fake page) on real sockets and
pushes binary audio frames through the WS: audio -> transcript_final ->
intent -> auto-execute -> execution_result, and the risky path ->
confirmation_required -> confirm_execute -> execution_result. This is the
integration test the reference never had (SURVEY.md §4: "no integration or
e2e tests").
"""

import asyncio
import json

import numpy as np
import pytest
import aiohttp

from tpu_voice_agent.serve.stt import NullSTT
from tpu_voice_agent.services.brain import RuleBasedParser, build_app as build_brain
from tpu_voice_agent.services.executor import SessionManager, build_app as build_executor
from tpu_voice_agent.services.executor.page import FakePage
from tpu_voice_agent.services.voice import VoiceConfig, build_app as build_voice
from tests.http_helper import AppServer

PCM_SILENCE = (np.zeros(1600, dtype="<i2")).tobytes()  # 100 ms


def ws_session(voice_url, inbound, expect_types, timeout_s=30.0):
    """Connect to /stream, send frames, collect events until all expected
    types were seen (or timeout). Returns the ordered event list."""

    async def run():
        events = []
        seen = set()
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(voice_url.replace("http", "ws") + "/stream") as ws:
                for kind, payload in inbound:
                    if kind == "binary":
                        await ws.send_bytes(payload)
                    else:
                        await ws.send_json(payload)
                end = asyncio.get_event_loop().time() + timeout_s
                while asyncio.get_event_loop().time() < end:
                    try:
                        msg = await ws.receive(timeout=1.0)
                    except asyncio.TimeoutError:
                        continue
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        break
                    ev = json.loads(msg.data)
                    events.append(ev)
                    seen.add(ev["type"])
                    if set(expect_types) <= seen:
                        break
        return events

    return asyncio.run(run())


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """voice + brain + executor on real sockets."""
    tmp = tmp_path_factory.mktemp("stack")
    brain = AppServer(build_brain(RuleBasedParser())).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp / "art"),
        uploads_dir=str(tmp / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()

    scripted: list = []

    def stt_factory():
        return NullSTT(scripted=list(scripted))

    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url, stt_factory=stt_factory))
    ).__enter__()
    yield {"voice": voice, "brain": brain, "executor": executor, "scripted": scripted}
    for srv in (voice, executor, brain):
        srv.__exit__(None, None, None)


def test_first_frame_warns_in_null_mode(stack):
    events = ws_session(stack["voice"].url, [], ["warn"], timeout_s=5)
    assert events[0]["type"] == "warn"


def test_full_pipeline_audio_to_execution(stack):
    stack["scripted"][:] = [("partial", "search for"), ("final", "search for laptops")]
    events = ws_session(
        stack["voice"].url,
        [("binary", PCM_SILENCE), ("binary", PCM_SILENCE)],
        ["execution_result"],
    )
    types = [e["type"] for e in events]
    assert "transcript_partial" in types
    assert "transcript_final" in types
    assert "intent" in types and "tts" in types
    intent_ev = next(e for e in events if e["type"] == "intent")
    assert intent_ev["data"]["intents"][0]["type"] == "search"
    result_ev = next(e for e in events if e["type"] == "execution_result")
    assert result_ev["data"]["results"][0]["ok"]
    assert result_ev["data"]["session_id"]


def test_risky_path_requires_confirmation_then_executes(stack):
    stack["scripted"][:] = [("final", "upload my resume and submit the form")]
    events = ws_session(
        stack["voice"].url, [("binary", PCM_SILENCE)], ["confirmation_required"]
    )
    conf = next(e for e in events if e["type"] == "confirmation_required")
    risky = conf["intents"]
    assert all(i["requires_confirmation"] for i in risky)
    assert not any(e["type"] == "execution_result" for e in events)

    # user approves: send confirm_execute with a safe screenshot instead of
    # the upload (no stored file in this test)
    events2 = ws_session(
        stack["voice"].url,
        [("json", {"type": "confirm_execute", "intents": [{"type": "screenshot"}]})],
        ["execution_result"],
    )
    res = next(e for e in events2 if e["type"] == "execution_result")
    assert res["data"]["results"][0]["ok"]


def test_typed_text_command_path(stack):
    events = ws_session(
        stack["voice"].url,
        [("json", {"type": "text", "text": "take a screenshot"})],
        ["execution_result"],
    )
    assert any(e["type"] == "transcript_final" for e in events)
    assert any(e["type"] == "execution_result" for e in events)


def test_context_update_control_frame(stack):
    events = ws_session(
        stack["voice"].url,
        [("json", {"type": "context_update", "data": {"last_query": "tvs"}})],
        ["info"],
        timeout_s=5,
    )
    assert any(e["type"] == "info" and "context" in e.get("message", "") for e in events)


def test_bad_control_frame_warns_not_crashes(stack):
    # the null-mode warn fires first, so collect for a fixed window instead
    # of stopping at the first warn
    events = ws_session(
        stack["voice"].url,
        [("json", {"type": "florble"})],
        ["__collect_until_timeout__"],
        timeout_s=3,
    )
    warns = [e for e in events if e["type"] == "warn"]
    assert any("unknown control" in e.get("message", "") for e in warns)


@pytest.fixture()
def spec_stack(tmp_path):
    """voice + counting brain + executor, for speculative-parse tests."""
    calls: list = []

    class CountingParser(RuleBasedParser):
        def parse(self, text, context):
            calls.append(text)
            return super().parse(text, context)

    brain = AppServer(build_brain(CountingParser())).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    scripted: list = []

    def stt_factory():
        return NullSTT(scripted=list(scripted))

    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=stt_factory))
    ).__enter__()
    yield {"voice": voice, "scripted": scripted, "calls": calls}
    for srv in (voice, executor, brain):
        srv.__exit__(None, None, None)


def test_speculative_parse_confirmed_by_final_is_one_roundtrip(spec_stack):
    """spec_final starts the parse inside the endpoint window; the matching
    transcript_final DELIVERS that result — one brain roundtrip total, and
    the intent event only appears after the final (never speculatively)."""
    spec_stack["scripted"][:] = [
        ("spec_final", "search for usb hubs"),
        ("final", "search for usb hubs"),
    ]
    events = ws_session(
        spec_stack["voice"].url,
        [("binary", PCM_SILENCE), ("binary", PCM_SILENCE)],
        ["execution_result"],
    )
    types = [e["type"] for e in events]
    assert "intent" in types and "execution_result" in types
    # the speculative parse was REUSED, not repeated
    assert spec_stack["calls"] == ["search for usb hubs"]
    # nothing is emitted between the speculation and the final: the first
    # model-facing event after the warn/info preamble is transcript_final
    first_payload = next(t for t in types if t not in ("warn", "info"))
    assert first_payload == "transcript_final"


def test_speculative_parse_superseded_by_different_final(spec_stack):
    """The speaker resumed after the pause: the confirmed final differs
    from the speculated text, so the speculation is discarded and the
    final's own parse is delivered."""
    spec_stack["scripted"][:] = [
        ("spec_final", "sort by price"),
        ("final", "search for red shoes"),
    ]
    events = ws_session(
        spec_stack["voice"].url,
        [("binary", PCM_SILENCE), ("binary", PCM_SILENCE)],
        ["execution_result"],
    )
    intent_ev = next(e for e in events if e["type"] == "intent")
    assert intent_ev["data"]["intents"][0]["type"] == "search"
    assert intent_ev["data"]["intents"][0]["args"]["query"] == "red shoes"
    # the final's text was parsed; the stale speculation may or may not
    # have reached the brain before cancellation, but it is never delivered
    assert spec_stack["calls"][-1] == "search for red shoes"


def test_speculation_sticky_off_against_session_keyed_brain(tmp_path):
    """A session-keyed brain refuses speculation with 409; the voice
    service must remember that after the FIRST refusal and stop paying a
    wasted roundtrip per utterance — while finals still parse normally."""
    spec_calls = []
    final_calls = []
    rule = RuleBasedParser()

    class SessionParser:
        wants_session = True

        def parse(self, text, context, session_id=None):
            final_calls.append(text)
            return rule.parse(text, context)

    brain = AppServer(build_brain(SessionParser())).__enter__()

    # count speculative requests at the HTTP layer: wrap the brain app's
    # /parse by inspecting the request body via middleware-free approach —
    # the 409 happens before the parser, so parser calls are finals only.
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    scripted = [
        ("spec_final", "search for usb hubs"),
        ("final", "search for usb hubs"),
        ("spec_final", "scroll down"),
        ("final", "scroll down"),
    ]

    def stt_factory():
        return NullSTT(scripted=list(scripted))

    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=stt_factory))
    ).__enter__()
    try:
        from tpu_voice_agent.utils import get_metrics

        started0 = get_metrics().snapshot()["counters"].get(
            "voice.spec_parse_started", 0)
        events = ws_session(
            voice.url,
            [("binary", PCM_SILENCE)] * 4,
            ["execution_result"],
            timeout_s=30,
        )
        intents = [e for e in events if e["type"] == "intent"]
        assert len(intents) >= 1
        # both finals reached the parser (non-speculatively)
        assert final_calls == ["search for usb hubs", "scroll down"]
        # only the FIRST utterance attempted a speculation; the 409 made
        # the second skip it entirely
        started = get_metrics().snapshot()["counters"].get(
            "voice.spec_parse_started", 0)
        assert started - started0 == 1
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


def test_speculation_latch_reprobes_after_n_skips(tmp_path, monkeypatch):
    """The sticky 409 latch is not app-lifetime (round-4 advisor finding):
    after VOICE_RESPEC_AFTER skipped utterances one speculation re-probes,
    so a brain restarted into a speculation-capable backend recovers
    without a voice restart."""
    monkeypatch.setenv("VOICE_RESPEC_AFTER", "2")
    rule = RuleBasedParser()

    class SessionParser:
        wants_session = True

        def parse(self, text, context, session_id=None):
            return rule.parse(text, context)

    brain = AppServer(build_brain(SessionParser())).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    # 5 utterances: spec #1 latches; #2 and #3 skip; #4 re-probes (409
    # latches again); #5 skips. => exactly 2 speculative attempts.
    scripted = []
    for i in range(5):
        scripted += [("spec_final", f"scroll down"), ("final", "scroll down")]

    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=lambda: NullSTT(scripted=list(scripted))))
    ).__enter__()
    try:
        from tpu_voice_agent.utils import get_metrics

        started0 = get_metrics().snapshot()["counters"].get(
            "voice.spec_parse_started", 0)
        ws_session(voice.url, [("binary", PCM_SILENCE)] * 10,
                   ["__never__"], timeout_s=8)
        started = get_metrics().snapshot()["counters"].get(
            "voice.spec_parse_started", 0)
        assert started - started0 == 2
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


def test_transient_409_does_not_latch(tmp_path):
    """A 409 whose body is NOT the brain's speculation_unsupported refusal
    (a proxy, a restarting upstream) must not permanently disable
    speculation (round-4 advisor finding)."""
    from aiohttp import web

    calls = {"spec": 0}
    rule = RuleBasedParser()

    async def parse(request):
        body = await request.json()
        if body.get("speculative"):
            calls["spec"] += 1
            return web.json_response({"error": "upstream_restarting"},
                                     status=409)
        res = rule.parse(body["text"], body.get("context") or {})
        return web.json_response(json.loads(res.model_dump_json()))

    app = web.Application()
    app.router.add_post("/parse", parse)
    brain = AppServer(app).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    scripted = [
        ("spec_final", "scroll down"), ("final", "scroll down"),
        ("spec_final", "scroll down"), ("final", "scroll down"),
    ]
    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=lambda: NullSTT(scripted=list(scripted))))
    ).__enter__()
    try:
        ws_session(voice.url, [("binary", PCM_SILENCE)] * 4,
                   ["__never__"], timeout_s=8)
        # BOTH utterances attempted speculation: no latch on a foreign 409
        assert calls["spec"] == 2
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


def test_speculation_commits_on_session_keyed_planner_brain(tmp_path):
    """Full-stack closure of the endpoint-window win on the PLANNER brain:
    spec_final starts a speculative /parse that the planner records
    two-phase; the matching transcript_final COMMITS it (zero extra plan
    decode) and the intent is delivered. One WS, three real services."""
    from tpu_voice_agent.services.brain import PlannerParser
    from tpu_voice_agent.utils import get_metrics

    class OneShotPlanner:
        """Deterministic stub planner (same seam as test_brain_planner)."""

        max_new_tokens = 64
        PLAN = (
            '{"version":"1.0","intents":[{"type":"scroll","target":null,'
            '"args":{"direction":"down"},"priority":1,'
            '"requires_confirmation":false,"timeout_ms":15000,"retries":0}],'
            '"context_updates":{},"confidence":0.9,"tts_summary":"ok",'
            '"follow_up_question":null}'
        )

        def __init__(self):
            self.plans = 0

        def start(self, text):
            from types import SimpleNamespace

            return SimpleNamespace(ids=list(range(4)), pos=4, anchors=1,
                                   last_logits=object(), cache=None)

        def extend(self, sess, text):
            sess.ids.extend([7] * 2)

        def plan_many(self, sessions, max_new_tokens=None, **kw):
            self.plans += len(sessions)
            for s in sessions:
                s.ids.extend([9] * 3)
            return [(self.PLAN, [9] * 3) for _ in sessions]

        def session_bytes(self, sess):
            return 0

        def park(self, sess):
            pass

        def unpark(self, sess):
            pass

        def parked_bytes(self, sess):
            return 0

    planner = OneShotPlanner()
    brain = AppServer(build_brain(PlannerParser(planner))).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    scripted = [("spec_final", "scroll down"), ("final", "scroll down")]
    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=lambda: NullSTT(scripted=list(scripted))))
    ).__enter__()
    try:
        commits0 = get_metrics().snapshot()["counters"].get(
            "planner.spec_commits", 0)
        events = ws_session(
            voice.url,
            [("binary", PCM_SILENCE), ("binary", PCM_SILENCE)],
            ["execution_result"],
        )
        intent_ev = next(e for e in events if e["type"] == "intent")
        assert intent_ev["data"]["intents"][0]["type"] == "scroll"
        # ONE plan decode total: the final committed the speculative turn
        assert planner.plans == 1
        commits = get_metrics().snapshot()["counters"].get(
            "planner.spec_commits", 0)
        assert commits - commits0 == 1
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


# ------------------------------------------------------- multi-stream batched


def test_stt_factory_env_gating_batched_vs_per_connection(monkeypatch):
    """STT_BATCH_ENABLE unset -> the historical per-connection plane
    (LockedStreaming); =1 -> every connection shares ONE engine and ONE
    batcher sized by STT_BATCH_SLOTS."""
    from tpu_voice_agent.serve.stt_batch import BatchedStreamingSTT
    from tpu_voice_agent.services.voice import stt_factory_from_env

    monkeypatch.setenv("VOICE_STT", "whisper:whisper-test")
    monkeypatch.delenv("STT_BATCH_ENABLE", raising=False)
    s = stt_factory_from_env()()
    assert type(s).__name__ == "LockedStreaming"
    assert not isinstance(s, BatchedStreamingSTT)

    monkeypatch.setenv("STT_BATCH_ENABLE", "1")
    monkeypatch.setenv("STT_BATCH_SLOTS", "2")
    factory = stt_factory_from_env()
    a, b = factory(), factory()
    try:
        assert isinstance(a, BatchedStreamingSTT) and isinstance(b, BatchedStreamingSTT)
        assert a.batcher is b.batcher  # process-wide batcher
        assert a.engine is b.engine  # process-wide engine
        assert a.batcher.S == 2
        assert a._utt != b._utt  # distinct utterance keys
    finally:
        a.batcher.stop()


def test_batched_multiconnection_e2e_over_ws(tmp_path):
    """Two real WS connections against a voice service running the batched
    STT plane (real whisper-test engine, shared batcher): both stream
    audio concurrently and both receive the SAME transcript_final a B=1
    per-connection StreamingSTT produces for identical chunks."""
    import threading

    from tpu_voice_agent.audio.endpoint import EnergyEndpointer
    from tpu_voice_agent.audio.mel import pcm16_to_float
    from tpu_voice_agent.serve.stt import SpeechEngine, StreamingSTT
    from tpu_voice_agent.serve.stt_batch import BatchedStreamingSTT, STTBatcher

    engine = SpeechEngine(preset="whisper-test", frame_buckets=(50, 100, 200),
                          max_new_tokens=16)
    batcher = STTBatcher(engine, slots=4)

    def make_endpointer():
        return EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=100)

    def stt_factory():
        return BatchedStreamingSTT(engine, batcher, endpointer=make_endpointer(),
                                   early_close_ms=None)

    # the audio both connections will stream: 0.6 s tone + trailing silence,
    # in 100 ms PCM16 frames (quantized exactly like the wire format)
    sr = 16_000
    t = np.arange(int(0.6 * sr)) / sr
    tone_f32 = (0.3 * np.sin(2 * np.pi * 300 * t)).astype(np.float32)
    audio = np.concatenate([tone_f32, np.zeros(int(0.6 * sr), np.float32)])
    pcm = (np.clip(audio, -1, 1) * 32767.0).astype("<i2").tobytes()
    frames = [pcm[i:i + 3200] for i in range(0, len(pcm), 3200)]

    # B=1 reference over the SAME quantized chunks (computed before the
    # service boots so the engine isn't shared mid-flight)
    ref = StreamingSTT(engine, endpointer=make_endpointer(), early_close_ms=None)
    ref_finals = [txt for f in frames for k, txt in ref.feed(pcm16_to_float(f))
                  if k == "final"]
    if not ref_finals:
        pytest.skip("random-weight engine transcribed this tone to empty text")

    brain = AppServer(build_brain(RuleBasedParser())).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=stt_factory))
    ).__enter__()
    try:
        inbound = [("binary", f) for f in frames]
        results: dict = {}

        def one_conn(idx):
            results[idx] = ws_session(voice.url, inbound, ["transcript_final"],
                                      timeout_s=60)

        threads = [threading.Thread(target=one_conn, args=(i,)) for i in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for idx in range(2):
            finals = [e["text"] for e in results[idx]
                      if e["type"] == "transcript_final"]
            assert finals, f"connection {idx} never got a final"
            assert finals[0] == ref_finals[0]
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)
        batcher.stop()
