"""Speculative decoding over the paged/radix plane (ISSUE 8) — FAST tier.

The compound-path contract: with spec × radix × continuous batching stacked
in ONE PagedDecodeEngine, greedy output stays byte-identical to the plain
paged greedy path for EVERY drafter, warm and cold, across ragged block
boundaries and mid-chain eviction; rejected draft tokens never reach a
radix-cached block (they only ever land past the accepted frontier, in
COW-owned blocks the tree refuses to adopt); a chaos NaN injected into a
verify pass quarantines its row alone with zero leaked blocks; and the
accounting plane (spec.accept_rate / scheduler.tokens_per_forward /
per-request forwards / SPEC_TRACE_SINK) reflects paged traffic.
"""

import json

import numpy as np
import pytest

from tpu_voice_agent.serve import PagedDecodeEngine, SpecConfig, SpecDecoder
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.spec import Drafter
from tpu_voice_agent.services.brain import (
    SessionTranscripts,
    install_prompt_prefix,
)
from tpu_voice_agent.services.prompts import render_prompt
from tpu_voice_agent.utils import chaos, get_metrics

BUCKETS = (128, 256, 512, 1024, 2048)
PROMPT_TEXTS = ["search for usb hubs", "scroll down", "open the first result"]
MAXTOK = 48


def _paged(radix: bool, spec=None, **kw):
    eng = PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=2,
        prefill_buckets=BUCKETS, radix_enable=radix, spec=spec, **kw)
    install_prompt_prefix(eng)
    return eng


def _run(eng, prompts, max_new=MAXTOK):
    return ContinuousBatcher(eng, chunk_steps=8,
                             max_new_tokens=max_new).generate_many(prompts)


@pytest.fixture(scope="module")
def eng_plain():
    """The undisturbed baseline: paged, radix off, no speculation."""
    return _paged(False)


@pytest.fixture(scope="module")
def prompts():
    return [render_prompt(t, {}) for t in PROMPT_TEXTS[:2]]


@pytest.fixture(scope="module")
def baseline(eng_plain, prompts):
    res = _run(eng_plain, prompts)
    assert all(r.error is None for r in res)
    return res


@pytest.fixture(scope="module")
def eng_warm():
    """The full stack: paged + radix + spec (fsm,prompt chain)."""
    return _paged(True, spec=SpecConfig(k=4, drafter="fsm,prompt"))


# ------------------------------------------------------------ identity


@pytest.mark.parametrize("drafter", ["fsm", "prompt", "fsm,prompt", "model"])
def test_paged_spec_cold_token_identity(eng_plain, prompts, baseline, drafter):
    """Cold admissions, every drafter: paged+spec output == plain paged
    greedy, with forwards < steps proving multi-token verify actually ran
    (the fsm drafter lands structural JSON runs even on random weights)."""
    eng = _paged(False, spec=SpecConfig(k=4, drafter=drafter))
    res = _run(eng, prompts)
    for ref, r in zip(baseline, res):
        assert r.error is None, r.error
        assert r.token_ids == ref.token_ids, (drafter, r.text[:80])
        assert r.finished == ref.finished
        assert r.steps == len(r.token_ids)
        assert r.forwards > 0  # per-request participation (widened readback)
    if drafter != "prompt":  # prompt-only rarely lands on random weights
        assert eng.spec.stats()["accepted"] > 0, drafter


def test_paged_self_draft_multiplier(eng_plain, prompts, baseline):
    """Self-draft (draft model == target weights) on the PAGED layout: the
    strongest end-to-end probe of block-granular verify/rollback. Accept
    rate ~1 (EOS proposals are structurally rejected at stream ends) and
    the step reduction clears the 3x acceptance bar."""
    from tpu_voice_agent.serve import DraftModelDrafter

    eng = _paged(False)
    eng.spec = SpecDecoder(
        eng, SpecConfig(k=4),
        drafter=DraftModelDrafter(eng, cfg=eng.cfg, params=eng.params))
    res = _run(eng, prompts)
    for ref, r in zip(baseline, res):
        assert r.error is None and r.token_ids == ref.token_ids
        assert r.forwards < r.steps / 2  # >= 2 tokens per forward per row
    s = eng.spec.stats()
    assert s["accept_rate"] > 0.9
    assert s["tokens_per_step"] / len(prompts) > 3.0  # per-row multiplier


TURNS = [
    ("search for wireless headphones", {}),
    ("open the second result", {"last_query": "wireless headphones"}),
    ("sort these by price from low to high", {"last_query": "wireless headphones"}),
]


def _play_session(eng, turns=TURNS, max_new=MAXTOK):
    """Drive a multi-turn session through the PRODUCTION transcript
    renderer (services.brain.SessionTranscripts — the one owner of the
    strict-token-extension construction): warm turns extend the cached
    chain at block granularity and the drafters get seeded with the full
    transcript. Returns (per-turn results, per-turn accepted-stream ids
    = prompt+generated histories)."""
    tok = eng.tokenizer
    st = SessionTranscripts(tok)
    results, hists = [], []
    for text, ctx in turns:
        prompt = st.prompt_for("sess", text, ctx)
        ids = (tok.encode(prompt, bos=True) if isinstance(prompt, str)
               else list(prompt))
        r = _run(eng, [ids], max_new=max_new)[0]
        assert r.error is None, r.error
        results.append(r)
        st.record("sess", ids, r.token_ids)
        hists.append(ids + list(r.token_ids))
    return results, hists


def test_warm_radix_spec_compound_identity(eng_plain, eng_warm):
    """THE compound differential: warm radix admissions under speculative
    decode are token-identical to plain paged greedy, turn by turn, and
    turn 2+ still rides the cached chain (the two multipliers stack
    instead of excluding each other)."""
    cold, _ = _play_session(eng_plain)
    warm, _ = _play_session(eng_warm)
    P = len(eng_warm.prefix_ids)
    for c, w in zip(cold, warm):
        assert c.token_ids == w.token_ids
        assert eng_warm.fsm.walk(w.token_ids) >= 0
    assert warm[0].cached_tokens == P       # turn 1: static prefix only
    assert warm[1].cached_tokens > P        # turn 2+: session chain hit
    assert warm[2].cached_tokens >= warm[1].cached_tokens
    for w in warm[1:]:
        assert w.forwards > 0               # speculation ran ON a warm turn
    # full-replay warm turns stay identical (drafters re-seeded from the
    # cached prompt ids on the radix-hit admission path)
    warm2, _ = _play_session(eng_warm)
    for c, w in zip(cold, warm2):
        assert c.token_ids == w.token_ids
    assert eng_warm.spec.stats()["accepted"] > 0


def test_mid_chain_eviction_with_spec_identity(eng_plain):
    """A deliberately tight pool churns session chains out of the tree
    between turns while spec decode claims verify-step coverage — output
    stays identical and pool accounting drains to exactly the tree."""
    eng = _paged(True, spec=SpecConfig(k=4, drafter="fsm,prompt"),
                 pool_blocks=10)
    sessions = [
        TURNS,
        [("navigate to example dot com", {}),
         ("take a screenshot of this page", {"last_url": "example.com"})],
        [("filter results under one hundred dollars", {}),
         ("extract the product table", {"last_query": "deals"})],
    ]
    for turns in sessions:
        cold, _ = _play_session(eng_plain, turns=turns)
        warm, _ = _play_session(eng, turns=turns)
        for c, w in zip(cold, warm):
            assert c.token_ids == w.token_ids
    assert sum(t.evictions for t in eng.radix) > 0, \
        "pool was sized to force eviction churn under spec"
    assert eng.allocator.blocks_in_use == sum(t.nodes for t in eng.radix)


# ------------------------------------------------------------ containment


class _WrongLegalDrafter(Drafter):
    """Adversarial: grammar-LEGAL tokens chosen to disagree with the model
    (highest legal id) — every verify step exercises rejection rollback on
    the paged layout, leaving stale draft KV past every accepted frontier."""

    name = "wrong"

    def __init__(self, fsm):
        self.fsm = fsm

    def draft_one(self, ctx, state, k):
        out, s = [], state
        for _ in range(k):
            if s < 0:
                break
            allowed = np.nonzero(self.fsm.allowed(s))[0]
            if len(allowed) == 0:
                break
            t = int(allowed[-1])
            out.append(t)
            s = self.fsm.step(s, t)
        return out


def test_rejected_drafts_never_reach_radix(eng_plain):
    """The block-granular rollback guarantee, asserted structurally: after
    multi-turn sessions under an adversarial mostly-rejected drafter,
    EVERY cached radix chain is a prefix of some request's accepted
    prompt+generated stream — zero cached blocks contain a rejected draft
    token — and a warm replay served FROM those chains stays identical."""
    eng = _paged(True)
    eng.spec = SpecDecoder(eng, SpecConfig(k=4),
                           drafter=_WrongLegalDrafter(eng.fsm))
    cold, cold_hists = _play_session(eng_plain)
    warm, hists = _play_session(eng)
    for c, w in zip(cold, warm):
        assert c.token_ids == w.token_ids
    s = eng.spec.stats()
    assert s["drafted"] > 0 and s["accepted"] < s["drafted"], \
        "the adversarial drafter must actually be rejected"
    # accepted-stream containment: every cached chain spells accepted ids
    accepted = [list(eng.prefix_ids)] + hists
    for tree in eng.radix:
        for chain in tree.chains():
            assert any(chain == h[: len(chain)] for h in accepted), \
                "radix-cached chain contains tokens outside every " \
                "accepted stream (rejected draft leaked into the cache)"
    # warm replay decoding FROM the cached chains: still identical
    warm2, _ = _play_session(eng)
    for c, w in zip(cold, warm2):
        assert c.token_ids == w.token_ids
    # zero leaked blocks: with no slots live, residency == tree + nothing
    for tree in eng.radix:
        tree.clear()
    assert eng.allocator.blocks_in_use == len(eng._prefix_blocks[0])


def _counter(name):
    return get_metrics().snapshot()["counters"].get(name, 0)


def test_chaos_nan_in_verify_pass_quarantines_alone(eng_plain, prompts,
                                                    baseline, eng_warm):
    """A NaN injected into a verify pass poisons ONE row: typed error,
    quarantine counter, batch-mate token-identical, poisoned chain never
    cached, zero leaked blocks."""
    before = _counter("scheduler.slots_quarantined")
    b = ContinuousBatcher(eng_warm, chunk_steps=8, max_new_tokens=MAXTOK)
    chaos.configure("nan_logits@2")  # 2nd admission's first verify poisoned
    try:
        res = b.generate_many(prompts)
    finally:
        chaos.reset()
    assert res[1].error is not None and \
        res[1].error.startswith("poisoned: non-finite"), res[1].error
    assert res[0].error is None
    assert res[0].token_ids == baseline[0].token_ids
    assert _counter("scheduler.slots_quarantined") == before + 1
    # the poisoned request's chain was released ok=False and must NOT be
    # cached: no tree chain may extend its full prompt into generated ids
    bad = eng_warm.tokenizer.encode(prompts[1], bos=True)
    for tree in eng_warm.radix:
        for chain in tree.chains():
            assert not (len(chain) > len(bad)
                        and chain[: len(bad)] == bad), \
                "poisoned request's chain was cached"
    # no slots live: every resident block is owned by the tree
    assert eng_warm.allocator.blocks_in_use == \
        sum(t.nodes for t in eng_warm.radix)


def test_chaos_dead_fsm_in_verify_pass(eng_plain, prompts, baseline):
    eng = _paged(False, spec=SpecConfig(k=4, drafter="fsm"))
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=MAXTOK)
    chaos.configure("dead_fsm@2")
    try:
        res = b.generate_many(prompts)
    finally:
        chaos.reset()
    assert res[1].error is not None and \
        res[1].error.startswith("poisoned: grammar dead state"), res[1].error
    assert res[0].error is None and res[0].token_ids == baseline[0].token_ids
    assert eng.allocator.blocks_in_use == len(eng._prefix_blocks[0])


# ------------------------------------------------------------ accounting


def test_paged_spec_accounting_and_gauges(eng_warm, prompts):
    """satellite 2: the spec gauges and the scheduler's tokens-per-forward
    must reflect PAGED-plane traffic, and per-request forwards ride the
    widened readback into batched GenerationResults."""
    res = _run(eng_warm, prompts)
    snap = get_metrics().snapshot()
    for name in ("spec.drafted_tokens", "spec.accepted_tokens",
                 "spec.verify_steps"):
        assert snap["counters"].get(name, 0) > 0, name
    assert "spec.accept_rate" in snap["gauges"]
    assert snap["gauges"]["spec.tokens_per_step"] >= 1.0
    assert snap["gauges"].get("scheduler.tokens_per_forward", 0) >= 1.0
    for r in res:
        assert r.error is None
        assert 0 < r.forwards <= r.steps
        # per-request accept counts ride the same widened readback; every
        # verify step a row participates in emits exactly 1 + accepted
        # tokens, so the three accounting fields must reconcile exactly
        assert 0 <= r.spec_accepted < r.steps
        assert r.spec_accepted + r.forwards == r.steps
    assert sum(r.spec_accepted for r in res) > 0  # fsm drafts land
    assert get_metrics().collisions() == []


def test_spec_trace_sink_feeds_distill(tmp_path):
    """satellite 3: SPEC_TRACE_SINK JSONL records round-trip into
    train.distill draft retraining (the accept-rate flywheel)."""
    from tpu_voice_agent.train import distill

    sink = tmp_path / "trace.jsonl"
    eng = _paged(True, spec=SpecConfig(k=4, drafter="fsm,prompt",
                                       trace_sink=str(sink)))
    prompts = [render_prompt(t, {}) for t in PROMPT_TEXTS[:2]]
    res = _run(eng, prompts)
    assert all(r.error is None for r in res)
    recs = distill.load_spec_trace(str(sink))
    assert len(recs) == 2
    for rec in recs:
        assert rec["plane"] == "paged"
        assert 0 <= rec["accepted"] <= rec["drafted"]
        assert rec["verify_steps"] > 0
    assert sorted(tuple(r["generated_ids"]) for r in recs) == \
        sorted(tuple(r.token_ids) for r in res)
    # a torn tail line (killed mid-write) must not poison the loader
    with open(sink, "a") as f:
        f.write('{"prompt_ids": [1, 2')
    assert len(distill.load_spec_trace(str(sink))) == 2
    cfg, params, stats = distill.train_draft_from_trace(
        str(sink), steps=6, batch=2, seq_len=192)
    assert stats["records"] == 2 and stats["final_loss"] < stats["first_loss"]
    # the retrained checkpoint loads straight into the drafter path
    from tpu_voice_agent.serve import DraftModelDrafter

    path = distill.save_ckpt(str(tmp_path), distill.DRAFT_CKPT, cfg, params,
                             stats)
    d = DraftModelDrafter.from_checkpoint(eng, path)
    assert d.cfg.vocab_size == eng.cfg.vocab_size
    assert _counter("spec.trace_records") >= 2


# ------------------------------------------------------------ gating


def test_spec_env_unset_keeps_paged_paths(monkeypatch):
    """SPEC_ENABLE unset: the paged engine never constructs a SpecDecoder
    — decode_chunk/prefill/release never branch, byte-for-byte the
    pre-spec paths."""
    monkeypatch.delenv("SPEC_ENABLE", raising=False)
    from tpu_voice_agent.serve import spec_from_env

    assert spec_from_env() is None
    eng = PagedDecodeEngine(preset="test-tiny", max_len=512,
                            prefill_buckets=(64,), init_weights=False)
    assert eng.spec is None and eng._spec_cfg is None


def test_brain_factory_enables_spec_on_paged(monkeypatch):
    """satellite 1: the brain factory no longer warn+ignores SPEC_ENABLE
    on the paged backend — the engine behind /parse carries a live paged
    SpecDecoder (and the radix tree beside it)."""
    from tpu_voice_agent.services import brain

    monkeypatch.setenv("BRAIN_BACKEND", "engine:test-tiny")
    monkeypatch.setenv("BRAIN_PAGED", "1")
    monkeypatch.setenv("BRAIN_BATCH", "2")
    monkeypatch.setenv("RADIX_ENABLE", "1")
    monkeypatch.setenv("SPEC_ENABLE", "1")
    monkeypatch.setenv("SPEC_DRAFTER", "fsm")
    parser = brain.make_parser_from_env()
    try:
        assert parser.engine.spec is not None
        assert parser.engine.spec.paged
        assert parser.engine.radix is not None
        assert parser.wants_session  # session-aware transcripts still on
    finally:
        parser.close()
