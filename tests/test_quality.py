"""Quality observatory (ISSUE 15): differential token-identity of the
confidence lanes per plane, zero post-fence recompiles with the lanes on,
the quality-SLO floor/freeze contract, the golden-replay canary's
admission gating, STT confidence + the stt_garble heuristic, and the
intent_downgrade latch.

Fast tier on purpose: "enabling quality signals changes no generated
token on any plane" is the acceptance bar of the whole observatory and
must gate every tier-1 run.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from tpu_voice_agent.serve.engine import DecodeEngine
from tpu_voice_agent.serve.paged import PagedDecodeEngine
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.spec import SpecConfig
from tpu_voice_agent.utils import chaos as chaos_mod
from tpu_voice_agent.utils.quality import (
    GoldenCanary,
    QualityMonitor,
    conf_summary,
    repetition_score,
)
from tpu_voice_agent.utils.slo import QualityTracker
from tpu_voice_agent.utils.tracing import Metrics, get_flight_recorder

PROMPTS = ["search for usb hubs", "scroll down",
           "sort by price from high to low", "go back"]


def _dense(quality, **kw):
    return DecodeEngine(preset="test-tiny", max_len=256,
                        prefill_buckets=(64, 128, 256), batch_slots=2,
                        quality_lanes=quality, **kw)


def _paged(quality, **kw):
    return PagedDecodeEngine(preset="test-tiny", max_len=256,
                             prefill_buckets=(64, 128, 256), batch_slots=2,
                             block_size=16, pool_blocks=64,
                             quality_lanes=quality, **kw)


def _run(engine):
    return ContinuousBatcher(engine, chunk_steps=8,
                             max_new_tokens=48).generate_many(PROMPTS)


# ------------------------------------------------------------ differentials


def test_token_identity_dense_ff():
    """Dense plane + grammar fast-forward: lanes on vs off, same tokens."""
    on = _run(_dense(True, fast_forward=4))
    off = _run(_dense(False, fast_forward=4))
    assert [r.token_ids for r in on] == [r.token_ids for r in off]
    for r in on:
        assert r.error is None
        assert r.quality is not None and r.quality["decisions"] > 0
        assert r.prompt_tokens > 0
    for r in off:
        assert r.quality is None  # lanes off: no vector, not a zeroed one


def test_token_identity_paged_radix():
    """Paged+radix plane: lanes on vs off, same tokens, vector present."""
    on = _run(_paged(True, radix_enable=True, fast_forward=4))
    off = _run(_paged(False, radix_enable=True, fast_forward=4))
    assert [r.token_ids for r in on] == [r.token_ids for r in off]
    assert all(r.quality is not None for r in on)


def test_token_identity_spec_verify():
    """Spec-verify plane (dense + paged): the verify steps carry the same
    readback contract; acceptance/rollback boundaries are untouched."""
    on = _run(_dense(True, spec=SpecConfig(k=3)))
    off = _run(_dense(False, spec=SpecConfig(k=3)))
    assert [r.token_ids for r in on] == [r.token_ids for r in off]
    pon = _run(_paged(True, radix_enable=True, spec=SpecConfig(k=3)))
    poff = _run(_paged(False, radix_enable=True, spec=SpecConfig(k=3)))
    assert [r.token_ids for r in pon] == [r.token_ids for r in poff]
    # the spec plane still reports per-request quality AND speculation
    assert all(r.quality is not None for r in pon)
    assert any(r.spec_accepted > 0 for r in pon)


def test_zero_postfence_recompiles_with_lanes_on():
    """The instrumented loops must not thrash the jit cache: after warmup,
    arming the sentinel fence and decoding again compiles NOTHING."""
    from tpu_voice_agent.utils.compilewatch import get_compile_watcher

    eng = _dense(True, fast_forward=4)
    batcher = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=48)
    batcher.generate_many(PROMPTS)  # warmup: every bucket/loop traced
    w = get_compile_watcher()
    before = w.state()["post_fence_compiles"]
    w.arm_fence("test_quality")
    batcher.generate_many(PROMPTS)
    assert w.state()["post_fence_compiles"] == before


# ------------------------------------------------------------ quality SLO


def test_quality_tracker_floor_violation_freezes_flight():
    fr = get_flight_recorder()
    fr.rearm()
    try:
        qt = QualityTracker("quality", floors={"golden_accuracy": 0.7},
                            min_samples=3, metrics=Metrics())
        qt.record("golden_accuracy", 1.0, {"text": "warm"})
        assert qt.state() == "ok"
        for i in range(6):
            qt.record("golden_accuracy", 0.0, {"text": f"bad{i}"})
        out = qt.evaluate()
        assert out["state"] == "violated"
        dump = fr.frozen_dump()
        assert dump is not None
        assert dump["reason"] == "slo.quality.violated"
        ev = dump["extra"]["quality"]["golden_accuracy"]
        assert ev["floor"] == 0.7 and ev["mean"] < 0.7
        # the failing utterances' quality vectors ride the dump
        assert any(s.get("text", "").startswith("bad") for s in ev["recent"])
    finally:
        fr.rearm()


def test_quality_tracker_ceiling_and_disarmed_floor():
    qt = QualityTracker("quality", floors={"intent_margin": 0},
                        ceilings={"stt_repetition": 0.9},
                        min_samples=2, metrics=Metrics())
    for _ in range(4):
        qt.record("intent_margin", 0.0)  # floor 0 = disarmed
        qt.record("stt_repetition", 1.0)
    out = qt.evaluate()
    assert out["state"] == "violated"
    assert all("repetition" in r for r in out["reasons"])


# ------------------------------------------------------ monitor + canary


def test_monitor_windows_and_gauges():
    m = Metrics()
    qm = QualityMonitor("test", metrics=m,
                        tracker=QualityTracker(metrics=m))
    qm.record_stt(-0.5, -2.0, 0.1, text="hi", logp_first=-0.3)
    qm.record_intent(margin=3.0, entropy=0.5, forced_frac=0.25, text="hi")
    qm.record_exec("click", True)
    qm.record_exec("click", False)
    qm.record_golden(True, 1.0, text="case")
    g = m.gauges()
    assert g["stt.confidence_mean"] == pytest.approx(-0.5)
    assert g["quality.intent_margin"] == pytest.approx(3.0)
    assert g["quality.exec_success_rate"] == pytest.approx(0.5)
    assert g["quality.golden_accuracy"] == pytest.approx(1.0)
    st = qm.state()
    assert st["exec_by_type"]["click"] == {"ok": 1, "total": 2, "rate": 0.5}
    assert st["counts"]["quality.parses"] == 1


def test_canary_scores_rule_parser_and_respects_busy_gate():
    from tpu_voice_agent.services.brain import RuleBasedParser

    m = Metrics()
    qm = QualityMonitor("test", metrics=m,
                        tracker=QualityTracker(metrics=m))
    parser = RuleBasedParser()
    busy = {"on": True}
    canary = GoldenCanary(lambda t, c: parser.parse(t, c), qm,
                          interval_s=999, slice_n=5,
                          busy_fn=lambda: busy["on"])
    assert canary.run_once() == 0  # admission-gated: busy replica skipped
    assert qm.state()["counts"]["quality.canary_skipped_busy"] == 1
    busy["on"] = False
    scored = 0
    for _ in range(3):
        scored += canary.run_once()
    assert scored == 15
    # the rule parser IS the golden baseline: the live canary must agree
    assert m.gauges()["quality.golden_accuracy"] >= 0.8
    assert qm.state()["counts"]["quality.canary_runs"] == 3


def test_conf_summary_and_repetition():
    assert conf_summary((0.0, float("inf"), 0.0, 0, 0), 0) is None
    s = conf_summary((6.0, 1.5, 3.0, 2, 3), 4)
    assert s == {"margin_mean": 2.0, "margin_min": 1.5, "entropy_mean": 1.0,
                 "forced_frac": 0.5, "decisions": 3}
    assert repetition_score([]) == 0.0
    assert repetition_score([5, 5, 5, 5]) == 0.75
    assert repetition_score([1, 2, 3, 4]) == 0.0


# ------------------------------------------------------------ STT lanes


@pytest.fixture(scope="module")
def stt_engine():
    from tpu_voice_agent.serve.stt import SpeechEngine

    return SpeechEngine(preset="whisper-test", frame_buckets=(50, 100, 200),
                        max_new_tokens=16)


def _tone(freq, dur_s, amp=0.3, sr=16_000):
    t = np.arange(int(dur_s * sr)) / sr
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


def test_stt_confidence_lanes(stt_engine):
    res = stt_engine.transcribe(_tone(400, 0.8))
    if res.text:
        assert res.logp_mean is not None and res.logp_mean <= 0.0
        assert res.logp_min is not None and res.logp_min <= res.logp_mean
        assert res.logp_first is not None
        assert 0.0 <= res.repetition < 1.0


def test_stt_garble_chaos_flags_repetition(stt_engine):
    clean = stt_engine.transcribe(_tone(400, 0.8))
    if not clean.text:
        pytest.skip("random-init whisper emitted nothing to garble")
    chaos_mod.configure("stt_garble:1", seed=3)
    try:
        garbled = stt_engine.transcribe(_tone(400, 0.8))
    finally:
        chaos_mod.reset()
    # post-decode corruption: one token looped — latency identical,
    # repetition pinned at its ceiling (what the quality SLO alarms on)
    n = len(stt_engine.tokenizer.encode(clean.text, bos=False))
    if n > 1:
        assert garbled.repetition is not None
        assert garbled.repetition > (clean.repetition or 0.0)
        assert garbled.text != clean.text


# ----------------------------------------------------- intent_downgrade


def test_intent_downgrade_latches_brain_replica():
    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app

    chaos_mod.configure("intent_downgrade@1", seed=0)
    try:
        with AppServer(build_app(RuleBasedParser())) as srv:
            def parse(text):
                req = urllib.request.Request(
                    srv.url + "/parse",
                    data=json.dumps({"text": text, "context": {}}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read().decode())

            first = parse("scroll down")
            second = parse("scroll down")
            # the latch: BOTH parses answer the degraded unknown plan —
            # fast, 200, wrong (the fault class only quality signals see)
            assert [i["type"] for i in first["intents"]] == ["unknown"]
            assert [i["type"] for i in second["intents"]] == ["unknown"]
            q = json.loads(urllib.request.urlopen(
                srv.url + "/debug/quality", timeout=10).read().decode())
            assert q["counts"]["quality.intent_downgrades"] >= 2
            assert q["windows"]["degraded"]["mean"] == 1.0
    finally:
        chaos_mod.reset()


def test_brain_parse_reports_quality_headers(tiny_engine):
    """An engine-backed /parse answers with the confidence headers the
    voice service folds into its gauges (x-prompt-tokens powers the
    prefill-remaining-at-endpoint measurement)."""
    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import EngineParser, build_app

    with AppServer(build_app(EngineParser(tiny_engine,
                                          max_new_tokens=48))) as srv:
        req = urllib.request.Request(
            srv.url + "/parse",
            data=json.dumps({"text": "scroll down", "context": {}}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            assert int(float(r.headers["x-prompt-tokens"])) > 0
            assert float(r.headers["x-intent-margin"]) >= 0.0
