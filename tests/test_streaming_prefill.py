"""Incremental streaming prefill (ISSUE 19): chunked prefill in the
batcher + prefill-only prefix feeds — FAST tier, because both identity
contracts gate tier-1.

The non-negotiable contracts, in the PR 3/4/5 differential style:
PREFILL_CHUNK_TOKENS unset keeps the one-shot barrier admission
byte-identical; set, a chunked admission produces TOKEN-IDENTICAL output
for the chunked request AND its batch-mates; a prefix feed is pure cache
warming — the eventual real parse is token-identical to a cold parse,
including when STT RETRACTS a committed prefix (the radix match falls
back to the longest still-valid cached prefix); and no interleaving of
ok/retracted/cancelled work leaks a block (allocator refcounts are the
single source of truth)."""

import random

import pytest

from tpu_voice_agent.serve import PagedDecodeEngine
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import install_prompt_prefix
from tpu_voice_agent.services.prompts import render_prompt
from tpu_voice_agent.services.voice import _PrefixFeedTracker, _prefill_remaining

BUCKETS = (128, 256, 512, 1024, 2048)


def _paged(radix: bool, **kw):
    return PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=2,
        prefill_buckets=BUCKETS, radix_enable=radix, **kw)


def _run(eng, prompts, max_new=48, chunk_tokens=None, monkeypatch=None):
    if monkeypatch is not None:
        if chunk_tokens:
            monkeypatch.setenv("PREFILL_CHUNK_TOKENS", str(chunk_tokens))
        else:
            monkeypatch.delenv("PREFILL_CHUNK_TOKENS", raising=False)
    return ContinuousBatcher(eng, chunk_steps=16,
                             max_new_tokens=max_new).generate_many(prompts)


def _leak_check(eng):
    """With no live slots, every resident block is tree-owned."""
    trees = eng.radix or []
    assert eng.allocator.blocks_in_use == sum(t.nodes for t in trees)


# ------------------------------------------------------------- tracker unit


def test_tracker_commits_only_after_k_stable_partials():
    tr = _PrefixFeedTracker(k=3, min_chars=4)
    assert tr.observe("open the") is None          # ring not full
    assert tr.observe("open the second") is None   # ring not full
    # stable prefix across the 3 = "open the " -> trimmed to "open the"
    assert tr.observe("open the second result") == "open the"
    assert tr.committed == "open the"


def test_tracker_min_chars_growth_gate():
    tr = _PrefixFeedTracker(k=2, min_chars=8)
    tr.observe("search for wireless")
    assert tr.observe("search for wireless head") == "search for wireless"
    # grows by < 8 committable chars -> no new commit yet
    assert tr.observe("search for wireless headph") is None
    tr.observe("search for wireless headphones now")
    got = tr.observe("search for wireless headphones now please")
    assert got == "search for wireless headphones now"


def test_tracker_trims_to_whitespace_boundary():
    tr = _PrefixFeedTracker(k=2, min_chars=1)
    tr.observe("naviga")
    # stable prefix "naviga" is mid-word -> nothing commits
    assert tr.observe("navigate") is None
    tr.observe("navigate to example")
    assert tr.observe("navigate to example dot") == "navigate to example"


def test_tracker_retraction_rebaselines():
    tr = _PrefixFeedTracker(k=2, min_chars=4)
    tr.observe("recognize speech today")
    assert tr.observe("recognize speech today ok") == "recognize speech today"
    # STT revises the committed text ("wreck a nice beach"): the old
    # baseline no longer prefixes the stable text -> re-baseline and
    # commit the revised prefix fresh
    tr.observe("wreck a nice beach today")
    got = tr.observe("wreck a nice beach today ok")
    assert got == "wreck a nice beach today"
    assert tr.committed == "wreck a nice beach today"


def test_tracker_reset():
    tr = _PrefixFeedTracker(k=2, min_chars=1)
    tr.observe("scroll down")
    tr.observe("scroll down now")
    assert tr.committed
    tr.reset()
    assert tr.committed == "" and tr.observe("fresh text") is None


# ------------------------------------------------------------- gauge helper


def test_prefill_remaining_every_utterance_shape():
    # speculative pre-parse: prompt fully prefilled before the endpoint
    assert _prefill_remaining({"prompt_tokens": 900.0}, True, False) == 0.0
    # cold engine parse: whatever the cache did not absorb was outstanding
    assert _prefill_remaining(
        {"prompt_tokens": 900.0, "cached_tokens": 880.0}, False, False) == 20.0
    # cache can block-round past the prompt -> clamped, never negative
    assert _prefill_remaining(
        {"prompt_tokens": 10.0, "cached_tokens": 16.0}, False, False) == 0.0
    # degraded (rule fallback) and headerless parses had no engine prefill
    # pending at the endpoint — recorded as 0, not skipped (the old bug)
    assert _prefill_remaining({"prompt_tokens": 900.0}, False, True) == 0.0
    assert _prefill_remaining({}, False, False) == 0.0


# ---------------------------------------------------------- chunked prefill


@pytest.fixture(scope="module")
def eng_off():
    eng = _paged(False)
    install_prompt_prefix(eng)
    return eng


@pytest.fixture(scope="module")
def eng_on():
    eng = _paged(True)
    install_prompt_prefix(eng)
    return eng


@pytest.fixture(scope="module")
def eng_plain():
    # NO pinned static prefix: the whole ~900-token rendered prompt is
    # computed suffix, so a 64-token chunk size genuinely interleaves
    # many prefill chunks with the batch-mate's decode steps
    return _paged(False)


PROMPTS = [
    render_prompt("search for wireless headphones", {}),
    render_prompt("open the second result please", {"last_query": "x"}),
]


def test_chunk_knob_unset_keeps_barrier_path(eng_off, monkeypatch):
    monkeypatch.delenv("PREFILL_CHUNK_TOKENS", raising=False)
    b = ContinuousBatcher(eng_off, chunk_steps=16, max_new_tokens=8)
    assert b._prefill_chunk == 0 and b._admitting == {}


def test_chunked_prefill_token_identity_and_batchmate_isolation(
        eng_plain, monkeypatch):
    """THE chunked differential: a long cold prompt admitted in 64-token
    chunks yields the same tokens as the barrier admission — and so does
    the batch-mate decoding while the chunks interleave."""
    from tpu_voice_agent.utils import get_metrics
    before = get_metrics().counter_state()[0]
    barrier = _run(eng_plain, PROMPTS, monkeypatch=monkeypatch)
    chunked = _run(eng_plain, PROMPTS, chunk_tokens=64,
                   monkeypatch=monkeypatch)
    for b, c in zip(barrier, chunked):
        assert b.error is None and c.error is None, (b.error, c.error)
        assert b.token_ids == c.token_ids
    after = get_metrics().counter_state()[0]
    adm = after.get("prefill.chunked_admissions", 0) - before.get(
        "prefill.chunked_admissions", 0)
    chunks = after.get("prefill.chunks", 0) - before.get("prefill.chunks", 0)
    assert adm >= 2
    assert chunks > adm  # ~900-token suffixes -> many chunks each
    assert eng_plain.allocator.blocks_in_use == 0  # radix off: all reclaimed


def test_chunked_prefill_identity_with_radix(eng_off, eng_on, monkeypatch):
    """Chunked admissions against the radix plane: the first (cold) run
    seeds chains, the second admits warm through begin_chunked_prefill's
    chain-match path — all token-identical to the barrier cold engine."""
    cold = _run(eng_off, PROMPTS, monkeypatch=monkeypatch)
    warm1 = _run(eng_on, PROMPTS, chunk_tokens=64, monkeypatch=monkeypatch)
    warm2 = _run(eng_on, PROMPTS, chunk_tokens=64, monkeypatch=monkeypatch)
    for c, w1, w2 in zip(cold, warm1, warm2):
        assert c.error is None and w1.error is None and w2.error is None
        assert c.token_ids == w1.token_ids == w2.token_ids
    # the warm rerun never matched LESS than the static prefix, and the
    # longer prompt matched past it through the inserted chain (the shorter
    # prompt's chain rounds to a block boundary beyond its own length, so
    # it legitimately falls back to the pinned prefix)
    assert all(w.cached_tokens >= len(eng_on.prefix_ids) for w in warm2)
    assert any(w.cached_tokens > len(eng_on.prefix_ids) for w in warm2)
    _leak_check(eng_on)


def test_cancel_mid_chunked_admission_releases_everything(monkeypatch):
    """Cancel lands BETWEEN prefill chunks: the admission dies alone with
    a typed cancelled error, its blocks free through the eviction seam,
    and nothing was half-inserted into the radix tree."""
    monkeypatch.setenv("PREFILL_CHUNK_TOKENS", "32")
    # no pinned prefix -> the full prompt chunks (~28 chunks at C=32), so
    # one step leaves the admission genuinely mid-flight
    eng = _paged(True)
    b = ContinuousBatcher(eng, chunk_steps=4, max_new_tokens=16)
    ids = eng.tokenizer.encode(PROMPTS[0], bos=True)
    rid = b.submit(ids)
    b.step()  # begin + first chunks; prompt >> 32 so still admitting
    assert rid not in b.results
    assert b._admitting, "admission should still be mid-flight"
    b.cancel(rid, reason="ws teardown")
    assert rid in b.results
    assert "cancelled" in (b.results[rid].error or "")
    assert not b._admitting
    _leak_check(eng)
    # the engine still serves after the cancelled admission
    r = _run(eng, [PROMPTS[1]])[0]
    assert r.error is None
    _leak_check(eng)


# ------------------------------------------------------------- prefix feeds


def _feed(b, prompt, tenant=None):
    return b.feed_prefix(prompt, tenant=tenant)


def test_feed_then_final_is_warm_and_token_identical(eng_off, eng_on):
    """A fed prefix (the stabilized partial) leaves a radix chain the
    real parse admits against: cached_tokens covers the fed prompt's full
    blocks, and the output matches the cold engine exactly."""
    text_partial = "filter the results under one hundred"
    text_final = "filter the results under one hundred dollars please"
    p_partial = render_prompt(text_partial, {})
    p_final = render_prompt(text_final, {})
    cold = _run(eng_off, [p_final])[0]
    assert cold.error is None

    b = ContinuousBatcher(eng_on, chunk_steps=16, max_new_tokens=48)
    out = _feed(b, p_partial)
    assert out["ok"] is True and out["prompt_tokens"] > 0
    ids_partial = eng_on.tokenizer.encode(p_partial, bos=True)
    ids_final = eng_on.tokenizer.encode(p_final, bos=True)
    # the rendered partial IS a token prefix of the rendered final here —
    # the fed chain's full blocks are exactly what the final can reuse
    shared = 0
    for a_, b_ in zip(ids_partial, ids_final):
        if a_ != b_:
            break
        shared += 1
    warm = _run(eng_on, [p_final])[0]
    assert warm.error is None
    assert warm.token_ids == cold.token_ids
    bs = eng_on.block_size
    assert warm.cached_tokens >= (shared // bs) * bs - bs  # block-rounded
    _leak_check(eng_on)


def test_feed_retraction_falls_back_token_identically(eng_off, eng_on):
    """STT revises a committed prefix: the final shares only a shorter
    prefix with what was fed. The radix match absorbs exactly the
    still-valid cached part and the parse is token-identical to cold —
    the fed-but-retracted tail is dead cache, never wrong output."""
    fed = render_prompt("recognize speech with this microphone", {})
    final = render_prompt("wreck a nice beach with this microphone", {})
    cold = _run(eng_off, [final])[0]
    assert cold.error is None
    b = ContinuousBatcher(eng_on, chunk_steps=16, max_new_tokens=48)
    out = _feed(b, fed)
    assert out["ok"] is True
    warm = _run(eng_on, [final])[0]
    assert warm.error is None
    assert warm.token_ids == cold.token_ids
    # still warm at least through the static prefix (longest valid prefix)
    assert warm.cached_tokens >= len(eng_on.prefix_ids)
    _leak_check(eng_on)


def test_feed_reextension_is_incremental(eng_on):
    """Feed K then K+delta: the second feed's prefill starts from the
    first feed's chain (cached_tokens grows monotonically) — the O(new
    tokens) re-extension the tentpole is built on."""
    t1 = "sort these results by price from low"
    t2 = "sort these results by price from low to high right now"
    b = ContinuousBatcher(eng_on, chunk_steps=16, max_new_tokens=48)
    o1 = _feed(b, render_prompt(t1, {}))
    o2 = _feed(b, render_prompt(t2, {}))
    assert o1["ok"] and o2["ok"]
    assert o2["cached_tokens"] >= len(eng_on.prefix_ids)
    assert o2["cached_tokens"] >= o1["cached_tokens"]
    _leak_check(eng_on)


def test_feed_sheds_for_live_work(eng_on):
    b = ContinuousBatcher(eng_on, chunk_steps=16, max_new_tokens=48)
    b.pending.append((999, "queued work"))
    out = _feed(b, render_prompt("take a screenshot", {}))
    assert out == {"ok": False, "reason": "busy"}
    b.pending.clear()
    # all slots occupied -> no_slot shed
    for sl in b.slots:
        sl.request_id = 1
    b._active_h[:] = True
    out = _feed(b, render_prompt("take a screenshot", {}))
    assert out == {"ok": False, "reason": "no_slot"}
    for sl in b.slots:
        sl.request_id = -1
    b._active_h[:] = False
    _leak_check(eng_on)


def test_feed_requires_radix(eng_off):
    b = ContinuousBatcher(eng_off, chunk_steps=16, max_new_tokens=48)
    out = _feed(b, render_prompt("take a screenshot", {}))
    assert out == {"ok": False, "reason": "radix_off"}


def test_feed_oversized_prompt_fails_closed(eng_on):
    b = ContinuousBatcher(eng_on, chunk_steps=16, max_new_tokens=48)
    ids = list(range(1, 4000))  # past every bucket and max_len
    out = _feed(b, ids)
    assert out["ok"] is False
    _leak_check(eng_on)


# -------------------------------------------------------- brain HTTP seam


def test_parse_prefix_feed_http_contract():
    """/parse with prefix_feed: backends without a prefill-only admission
    path answer 409 prefix_feed_unsupported (the voice service latches
    feeds off on it); feed-capable backends answer 200 with the feed
    verdict and never run a decode."""
    import httpx

    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app

    with AppServer(build_app(RuleBasedParser())) as srv:
        r = httpx.post(srv.url + "/parse",
                       json={"text": "search for hubs", "context": {},
                             "prefix_feed": True})
        assert r.status_code == 409
        assert r.json()["error"] == "prefix_feed_unsupported"

    class _FeedingParser:
        supports_prefix_feed = True
        fed: list[str] = []

        def parse(self, text, context, session_id=None):
            raise AssertionError("a prefix_feed request must never decode")

        def feed_prefix(self, text, context, session_id=None):
            self.fed.append(text)
            return {"ok": True, "prompt_tokens": 9, "cached_tokens": 0}

    with AppServer(build_app(_FeedingParser())) as srv:
        r = httpx.post(srv.url + "/parse",
                       json={"text": "search for hubs", "context": {},
                             "prefix_feed": True})
        assert r.status_code == 200
        body = r.json()
        assert body["prefix_feed"] is True and body["ok"] is True
        assert _FeedingParser.fed == ["search for hubs"]


# ----------------------------------------------------------------- the fuzz


def test_mixed_ok_retracted_cancelled_fuzz_zero_leakage(monkeypatch):
    """The satellite's leak fuzz: random interleavings of committed feeds,
    retracted feeds (revised text), real chunked/barrier parses, and
    mid-admission cancellations on a small pool. Invariant after every
    drain: blocks_in_use == tree-owned blocks (no slot refs leak), and
    every completed parse is error-free."""
    monkeypatch.setenv("PREFILL_CHUNK_TOKENS", "48")
    rng = random.Random(19)
    eng = _paged(True, pool_blocks=48)
    install_prompt_prefix(eng)
    texts = [
        "search for wireless headphones",
        "open the second result",
        "scroll down two pages then go back",
        "take a screenshot of this page",
    ]
    revised = {
        texts[0]: "search for wired headphones",
        texts[1]: "open the second tab",
    }
    for round_ in range(8):
        b = ContinuousBatcher(eng, chunk_steps=4, max_new_tokens=12)
        t = rng.choice(texts)
        op = rng.random()
        if op < 0.4:
            # feed a (possibly soon-retracted) partial, then parse a final
            # that may share only part of it
            _feed(b, render_prompt(t[: max(8, len(t) // 2)], {}))
            final = revised.get(t, t)
            r = b.generate_many([render_prompt(final, {})])[0]
            assert r.error is None, r.error
        elif op < 0.7:
            # cancel mid-chunked-admission
            rid = b.submit(eng.tokenizer.encode(render_prompt(t, {}),
                                                bos=True))
            b.step()
            b.cancel(rid, reason="fuzz")
            assert rid in b.results
        else:
            r = b.generate_many([render_prompt(t, {})])[0]
            assert r.error is None, r.error
        b.run_until_done()
        _leak_check(eng)
    _leak_check(eng)
