"""Sequence-parallel attention (ring / Ulysses) vs full attention, on the
8-virtual-device CPU mesh (conftest sets xla_force_host_platform_device_count)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.ops import attention_reference
from tpu_voice_agent.parallel.ring import ring_attention, sp_mesh, ulysses_attention


def _qkv(key, B, T, nq, nkv, hd):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (B, T, nq, hd)),
        jax.random.normal(kk, (B, T, nkv, hd)),
        jax.random.normal(kv, (B, T, nkv, hd)),
    )


@pytest.fixture(scope="module")
def mesh8():
    return sp_mesh(8)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, mesh8, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 8, 4, 32)
        out = ring_attention(q, k, v, mesh8, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_output_sharded_over_sp(self, mesh8):
        q, k, v = _qkv(jax.random.PRNGKey(1), 1, 32, 4, 4, 16)
        out = ring_attention(q, k, v, mesh8, causal=True)
        assert "sp" in str(out.sharding)

    def test_two_device_ring(self):
        mesh = sp_mesh(2)
        q, k, v = _qkv(jax.random.PRNGKey(2), 1, 16, 4, 2, 16)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, mesh8, causal):
        q, k, v = _qkv(jax.random.PRNGKey(3), 2, 64, 16, 8, 32)
        out = ulysses_attention(q, k, v, mesh8, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_heads(self, mesh8):
        q, k, v = _qkv(jax.random.PRNGKey(4), 1, 32, 6, 6, 16)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, mesh8)


class TestLlamaPallasParity:
    """llama.forward attn_impl='pallas' must match the XLA path."""

    def test_prefill_and_decode_parity(self):
        from tpu_voice_agent.models.llama import (
            LlamaConfig, forward, init_kv_cache, init_params,
        )

        cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, ffn_dim=128, max_seq_len=64)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        rng = np.random.default_rng(0)
        T = 16
        tokens = jnp.asarray(rng.integers(0, 128, (1, T)), jnp.int32)
        positions = jnp.arange(T, dtype=jnp.int32)[None]

        outs = {}
        for impl in ("xla", "pallas"):
            cache = init_kv_cache(cfg, 1, 64, dtype=jnp.float32)
            logits, cache = forward(params, cfg, tokens, positions, cache, attn_impl=impl)
            # one decode step on top
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            logits2, _ = forward(params, cfg, nxt[:, None],
                                 jnp.full((1, 1), T, jnp.int32), cache, attn_impl=impl)
            outs[impl] = (np.asarray(logits), np.asarray(logits2))

        np.testing.assert_allclose(outs["xla"][0], outs["pallas"][0], atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(outs["xla"][1], outs["pallas"][1], atol=1e-4, rtol=1e-4)

    def test_engine_pallas_generates_valid_intent_json(self):
        """End-to-end: a pallas-kernel engine still emits grammar-valid JSON."""
        import json

        from tpu_voice_agent.serve import DecodeEngine

        eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                           kernels="pallas")
        res = eng.generate("parse this", max_new_tokens=96)
        if res.finished:
            json.loads(res.text)  # grammar guarantees parseability on clean finish
        assert res.steps > 0
