"""Grammar fast-forward decoding (fsm.forced_tables + the engine's ff loop).

Forced runs — byte paths the grammar admits uniquely (JSON scaffolding
between free choices) — are appended without sampling: one (1+W)-token
forward per iteration instead of 1+W sequential steps. Memory-bound decode
makes the chain tokens nearly free on TPU.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.grammar.fsm import TokenFSM
from tpu_voice_agent.grammar.intent_grammar import build_intent_fsm
from tpu_voice_agent.grammar.regexlang import compile_regex


@pytest.fixture(scope="module")
def intent():
    return build_intent_fsm()


def test_forced_tables_chains_walk_the_fsm(intent):
    tok, fsm = intent
    ff_tokens, ff_len = fsm.forced_tables(width=8)
    n_chains = int((ff_len > 0).sum())
    assert n_chains > 50, "the intent grammar has plenty of forced scaffolding"
    rng = np.random.default_rng(0)
    for s in rng.choice(np.nonzero(ff_len > 0)[0], size=40, replace=False):
        st = int(s)
        for i in range(int(ff_len[s])):
            t = int(ff_tokens[s, i])
            assert t >= 0
            st = fsm.step(st, t)
            assert st >= 0, "forced chain left the grammar"


def test_forced_chain_bytes_match_dfa_run(intent):
    """The chain's byte decoding must be a prefix of the state's unique
    forced byte path (canonical tokenization changes nothing byte-wise)."""
    tok, fsm = intent
    ff_tokens, ff_len = fsm.forced_tables(width=8)
    trans_b = fsm._trans_b
    legal = trans_b >= 0
    forced = (legal.sum(axis=1) == 1) & ~fsm.accepting
    fbyte = np.argmax(legal, axis=1)
    checked = 0
    for s in np.nonzero(ff_len > 0)[0][:40]:
        run, st = bytearray(), int(s)
        while forced[st] and len(run) < 2048:
            run.append(int(fbyte[st]))
            st = int(trans_b[st, fbyte[st]])
        chain_bytes = b"".join(
            tok.token_bytes(int(t)) for t in ff_tokens[s, : int(ff_len[s])])
        assert bytes(run).startswith(chain_bytes)
        assert len(chain_bytes) > 0
        checked += 1
    assert checked > 0


def test_fully_forced_grammar_decodes_exactly():
    """A literal-string grammar is one long forced run: ANY model must emit
    exactly that string, and the ff loop must produce it in far fewer
    forwards than tokens."""
    from tpu_voice_agent.serve import DecodeEngine

    tok, _ = build_intent_fsm()
    lit = '{"version":"1.0","intents":[]}'
    fsm = TokenFSM(compile_regex(lit.replace("{", "\\{").replace("}", "\\}")
                                 .replace("[", "\\[").replace("]", "\\]")
                                 .replace(".", "\\.")), tok)
    eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                       tokenizer=tok, fsm=fsm, fast_forward=8)
    res = eng.generate("go", max_new_tokens=64)
    assert res.text == lit
    assert res.finished


def test_ff_generate_is_grammar_valid_and_multi_emits(intent):
    from tpu_voice_agent.serve import DecodeEngine

    eng = DecodeEngine(preset="test-tiny", max_len=1024,
                       prefill_buckets=(64, 128, 256, 512), fast_forward=8)
    res = eng.generate("search for usb hubs", max_new_tokens=200)
    assert res.steps > 0
    assert eng.fsm.walk(res.token_ids) >= 0
    if res.finished:
        import json

        json.loads(res.text)
    # the point of ff: emitted tokens contain forced chains, so the decoded
    # byte stream must contain the grammar's fixed scaffolding
    assert '"version"' in res.text


def test_ff_unconstrained_path_unchanged():
    """ff tables must not alter unconstrained decoding (the branch is gated
    on `constrained`)."""
    from tpu_voice_agent.serve import DecodeEngine

    a = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                     fast_forward=8)
    b = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,))
    ra = a.generate("same prompt", max_new_tokens=32, constrained=False)
    rb = b.generate("same prompt", max_new_tokens=32, constrained=False)
    assert ra.token_ids == rb.token_ids


def test_ff_respects_byte_budget():
    """The forced chain must stop at the byte budget like the plain path
    does (at most one token of overshoot) — a wide chain previously added
    its whole width of bytes before the stop check (round-2 advisor)."""
    from tpu_voice_agent.serve import DecodeEngine

    tok, _ = build_intent_fsm()
    lit = '{"version":"1.0","intents":[]}'
    fsm = TokenFSM(compile_regex(lit.replace("{", "\\{").replace("}", "\\}")
                                 .replace("[", "\\[").replace("]", "\\]")
                                 .replace(".", "\\.")), tok)
    eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                       tokenizer=tok, fsm=fsm, fast_forward=8)
    budget = 10
    res = eng.generate("go", max_new_tokens=64, byte_budget=budget)
    n = len(res.text.encode())
    assert not res.finished  # truncated by bytes, not EOS
    # overshoot bounded by ONE token's bytes, exactly like the non-ff path
    max_tok_bytes = max(len(tok.token_bytes(t)) for t in res.token_ids)
    assert n < budget + max_tok_bytes
    assert lit.startswith(res.text)


def test_batched_ff_matches_single_request_ff():
    """Round-3 VERDICT next #4: fast-forward under the BATCHER. Four
    co-batched requests with ff=8 must be token-identical to the same four
    run one-at-a-time through single-request generate() with ff=8 (same
    f32 weights; batching must never change the distribution), and the
    batcher must actually multi-emit (fewer chunks than tokens)."""
    import jax
    import jax.numpy as jnp

    from tpu_voice_agent.models.llama import init_params
    from tpu_voice_agent.serve import DecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher
    from tpu_voice_agent.services.prompts import render_prompt
    from tpu_voice_agent.utils import get_metrics

    single = DecodeEngine(preset="test-tiny", max_len=1024,
                          prefill_buckets=(512, 1024), fast_forward=8,
                          init_weights=False)
    batched = DecodeEngine(preset="test-tiny", max_len=1024, batch_slots=4,
                           prefill_buckets=(512, 1024), fast_forward=8,
                           init_weights=False)
    raw = init_params(single.cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    single.load_params(raw)
    batched.load_params(raw)

    prompts = [render_prompt(u, {}) for u in (
        "search for usb hubs", "scroll down", "go back",
        "take a screenshot",
    )]
    singles = [single.generate(p, max_new_tokens=160) for p in prompts]

    m = get_metrics().snapshot()["counters"]
    chunks0 = m.get("scheduler.chunks", 0)
    toks0 = m.get("scheduler.tokens_generated", 0)
    results = ContinuousBatcher(batched, chunk_steps=8,
                                max_new_tokens=160).generate_many(prompts)
    m = get_metrics().snapshot()["counters"]
    chunks = m.get("scheduler.chunks", 0) - chunks0
    toks = m.get("scheduler.tokens_generated", 0) - toks0

    for s, r in zip(singles, results):
        assert r.error is None
        assert batched.fsm.walk(r.token_ids) >= 0
        assert s.token_ids == r.token_ids, (s.text[:80], r.text[:80])
    # multi-emission proof, per ROW: a row resident for every chunk gets
    # at most chunks * chunk_steps forwards, and without ff one forward
    # emits one token — so ANY row whose token count exceeds that bound
    # must have multi-emitted. (The old aggregate `toks > chunks * 8`
    # passed vacuously once several rows co-resided per chunk.)
    assert max(len(r.token_ids) for r in results) > chunks * 8, (
        [len(r.token_ids) for r in results], chunks)


def test_batched_ff_pallas_matches_xla():
    """The frontier-read block-attention kernel (the lever that lifted the
    single-request restriction) must be token-identical to the exact XLA
    cache path at batch width."""
    import jax
    import jax.numpy as jnp

    from tpu_voice_agent.models.llama import init_params
    from tpu_voice_agent.serve import DecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher
    from tpu_voice_agent.services.prompts import render_prompt

    mk = lambda kern: DecodeEngine(
        preset="test-tiny", max_len=1024, batch_slots=4,
        prefill_buckets=(512, 1024), fast_forward=8, kernels=kern,
        init_weights=False)
    a, b = mk("xla"), mk("pallas")
    raw = init_params(a.cfg, jax.random.PRNGKey(9), dtype=jnp.float32)
    a.load_params(raw)
    b.load_params(raw)
    prompts = [render_prompt(u, {}) for u in (
        "search for red shoes", "sort by price low to high",
        "open the second result", "extract the table as csv",
    )]
    ra = ContinuousBatcher(a, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    rb = ContinuousBatcher(b, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    for x, y in zip(ra, rb):
        assert x.error is None and y.error is None
        assert b.fsm.walk(y.token_ids) >= 0
        assert x.token_ids == y.token_ids, (x.text[:80], y.text[:80])


def test_batched_ff_paged_matches_dense(request):
    """Fast-forward on the PAGED layout (the second half of round-3 next
    #4): the paged batcher with ff must be token-identical to the dense
    batcher with ff — chains write through the block tables and attend via
    the paged frontier-read block kernel, never changing the stream."""
    import jax
    import jax.numpy as jnp

    from tpu_voice_agent.models.llama import init_params
    from tpu_voice_agent.serve import DecodeEngine, PagedDecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher
    from tpu_voice_agent.services.prompts import render_prompt

    dense = DecodeEngine(preset="test-tiny", max_len=1024, batch_slots=3,
                         prefill_buckets=(512, 1024), fast_forward=8,
                         init_weights=False)
    paged = PagedDecodeEngine(preset="test-tiny", max_len=1024, batch_slots=3,
                              prefill_buckets=(512, 1024), fast_forward=8,
                              init_weights=False)
    raw = init_params(dense.cfg, jax.random.PRNGKey(13), dtype=jnp.float32)
    dense.load_params(raw)
    paged.load_params(raw)
    prompts = [render_prompt(u, {}) for u in (
        "search for usb hubs", "scroll down", "extract the table as csv",
    )]
    rd = ContinuousBatcher(dense, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    rp = ContinuousBatcher(paged, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    for d, p in zip(rd, rp):
        assert d.error is None and p.error is None
        assert paged.fsm.walk(p.token_ids) >= 0
        assert d.token_ids == p.token_ids, (d.text[:80], p.text[:80])


def test_batched_ff_paged_pallas_matches_dense_pallas():
    """Layout parity inside the pallas kernel family: the paged frontier-
    read block kernel must be token-identical to the DENSE block kernel at
    batch width (same weights, same streaming-softmax algorithm — only the
    KV layout differs, and layout must never change the stream).

    Pallas-vs-XLA token identity is deliberately NOT asserted on this pair:
    flash-style streaming softmax and the one-shot XLA softmax differ in
    reduction order, and with random tiny weights a near-tie argmax can
    legitimately flip (the kernel itself is pinned to the jnp reference by
    allclose in test_paged/test_ops)."""
    import jax
    import jax.numpy as jnp

    from tpu_voice_agent.models.llama import init_params
    from tpu_voice_agent.serve import DecodeEngine, PagedDecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher
    from tpu_voice_agent.services.prompts import render_prompt

    def mk(cls):
        return cls(preset="test-tiny", max_len=1024, batch_slots=3,
                   prefill_buckets=(512, 1024), fast_forward=8,
                   kernels="pallas", init_weights=False)

    dense, paged = mk(DecodeEngine), mk(PagedDecodeEngine)
    raw = init_params(dense.cfg, jax.random.PRNGKey(15), dtype=jnp.float32)
    dense.load_params(raw)
    paged.load_params(raw)
    prompts = [render_prompt(u, {}) for u in (
        "search for red shoes", "go back", "sort by price low to high",
    )]
    rd = ContinuousBatcher(dense, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    rp = ContinuousBatcher(paged, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    for x, y in zip(rd, rp):
        assert x.error is None and y.error is None
        assert paged.fsm.walk(y.token_ids) >= 0
        assert x.token_ids == y.token_ids, (x.text[:80], y.text[:80])


def test_batched_ff_pp_matches_dense():
    """Round-4 VERDICT weak #4: the pp×tp flagship layout had no
    fast-forward at all — the layout that most needs fewer steps took T=1
    steps through JSON scaffolding. The pipeline forward's positions-
    indexed cache writes + full-mask attend handle (B, 1+W) steps, so
    ff'd pp decode must be token-identical to the ff'd dense engine (same
    f32 weights; chunk_decode_loop and the forced tables are shared code),
    and it must actually multi-emit."""
    import jax
    import jax.numpy as jnp

    from tpu_voice_agent.models.llama import init_params
    from tpu_voice_agent.parallel.pipeline import pp_tp_mesh
    from tpu_voice_agent.serve import DecodeEngine, PPDecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher
    from tpu_voice_agent.services.prompts import render_prompt
    from tpu_voice_agent.utils import get_metrics

    dense = DecodeEngine(preset="test-tiny", max_len=1024, batch_slots=2,
                         prefill_buckets=(512, 1024), fast_forward=8,
                         init_weights=False)
    pp = PPDecodeEngine(preset="test-tiny", mesh=pp_tp_mesh(2, 2),
                        max_len=1024, batch_slots=2,
                        prefill_buckets=(512, 1024), fast_forward=8,
                        init_weights=False)
    raw = init_params(dense.cfg, jax.random.PRNGKey(21), dtype=jnp.float32)
    dense.load_params(raw)
    pp.load_params(raw)
    prompts = [
        render_prompt("search for mechanical keyboards", {}),
        render_prompt("take a screenshot", {"last_query": "keyboards"}),
    ]
    rd = ContinuousBatcher(dense, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    m0 = get_metrics().snapshot()["counters"]
    chunks0 = m0.get("scheduler.chunks", 0)
    toks0 = m0.get("scheduler.tokens_generated", 0)
    rp = ContinuousBatcher(pp, chunk_steps=8, max_new_tokens=160).generate_many(prompts)
    m1 = get_metrics().snapshot()["counters"]
    chunks = m1.get("scheduler.chunks", 0) - chunks0
    toks = m1.get("scheduler.tokens_generated", 0) - toks0
    for d, p in zip(rd, rp):
        assert d.error is None and p.error is None
        assert pp.fsm.walk(p.token_ids) >= 0
        assert d.token_ids == p.token_ids, (d.text[:80], p.text[:80])
    # multi-emission on the pipeline layout, per ROW: a row resident for
    # every chunk gets at most chunks * chunk_steps forwards; without ff
    # that bounds its token count — a row past the bound multi-emitted
    assert max(len(r.token_ids) for r in rp) > chunks * 8, (
        [len(r.token_ids) for r in rp], chunks)
