"""Grammar fast-forward decoding (fsm.forced_tables + the engine's ff loop).

Forced runs — byte paths the grammar admits uniquely (JSON scaffolding
between free choices) — are appended without sampling: one (1+W)-token
forward per iteration instead of 1+W sequential steps. Memory-bound decode
makes the chain tokens nearly free on TPU.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.grammar.fsm import TokenFSM
from tpu_voice_agent.grammar.intent_grammar import build_intent_fsm
from tpu_voice_agent.grammar.regexlang import compile_regex


@pytest.fixture(scope="module")
def intent():
    return build_intent_fsm()


def test_forced_tables_chains_walk_the_fsm(intent):
    tok, fsm = intent
    ff_tokens, ff_len = fsm.forced_tables(width=8)
    n_chains = int((ff_len > 0).sum())
    assert n_chains > 50, "the intent grammar has plenty of forced scaffolding"
    rng = np.random.default_rng(0)
    for s in rng.choice(np.nonzero(ff_len > 0)[0], size=40, replace=False):
        st = int(s)
        for i in range(int(ff_len[s])):
            t = int(ff_tokens[s, i])
            assert t >= 0
            st = fsm.step(st, t)
            assert st >= 0, "forced chain left the grammar"


def test_forced_chain_bytes_match_dfa_run(intent):
    """The chain's byte decoding must be a prefix of the state's unique
    forced byte path (canonical tokenization changes nothing byte-wise)."""
    tok, fsm = intent
    ff_tokens, ff_len = fsm.forced_tables(width=8)
    trans_b = fsm._trans_b
    legal = trans_b >= 0
    forced = (legal.sum(axis=1) == 1) & ~fsm.accepting
    fbyte = np.argmax(legal, axis=1)
    checked = 0
    for s in np.nonzero(ff_len > 0)[0][:40]:
        run, st = bytearray(), int(s)
        while forced[st] and len(run) < 2048:
            run.append(int(fbyte[st]))
            st = int(trans_b[st, fbyte[st]])
        chain_bytes = b"".join(
            tok.token_bytes(int(t)) for t in ff_tokens[s, : int(ff_len[s])])
        assert bytes(run).startswith(chain_bytes)
        assert len(chain_bytes) > 0
        checked += 1
    assert checked > 0


def test_fully_forced_grammar_decodes_exactly():
    """A literal-string grammar is one long forced run: ANY model must emit
    exactly that string, and the ff loop must produce it in far fewer
    forwards than tokens."""
    from tpu_voice_agent.serve import DecodeEngine

    tok, _ = build_intent_fsm()
    lit = '{"version":"1.0","intents":[]}'
    fsm = TokenFSM(compile_regex(lit.replace("{", "\\{").replace("}", "\\}")
                                 .replace("[", "\\[").replace("]", "\\]")
                                 .replace(".", "\\.")), tok)
    eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                       tokenizer=tok, fsm=fsm, fast_forward=8)
    res = eng.generate("go", max_new_tokens=64)
    assert res.text == lit
    assert res.finished


def test_ff_generate_is_grammar_valid_and_multi_emits(intent):
    from tpu_voice_agent.serve import DecodeEngine

    eng = DecodeEngine(preset="test-tiny", max_len=1024,
                       prefill_buckets=(64, 128, 256, 512), fast_forward=8)
    res = eng.generate("search for usb hubs", max_new_tokens=200)
    assert res.steps > 0
    assert eng.fsm.walk(res.token_ids) >= 0
    if res.finished:
        import json

        json.loads(res.text)
    # the point of ff: emitted tokens contain forced chains, so the decoded
    # byte stream must contain the grammar's fixed scaffolding
    assert '"version"' in res.text


def test_ff_unconstrained_path_unchanged():
    """ff tables must not alter unconstrained decoding (the branch is gated
    on `constrained`)."""
    from tpu_voice_agent.serve import DecodeEngine

    a = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                     fast_forward=8)
    b = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,))
    ra = a.generate("same prompt", max_new_tokens=32, constrained=False)
    rb = b.generate("same prompt", max_new_tokens=32, constrained=False)
    assert ra.token_ids == rb.token_ids


def test_ff_respects_byte_budget():
    """The forced chain must stop at the byte budget like the plain path
    does (at most one token of overshoot) — a wide chain previously added
    its whole width of bytes before the stop check (round-2 advisor)."""
    from tpu_voice_agent.serve import DecodeEngine

    tok, _ = build_intent_fsm()
    lit = '{"version":"1.0","intents":[]}'
    fsm = TokenFSM(compile_regex(lit.replace("{", "\\{").replace("}", "\\}")
                                 .replace("[", "\\[").replace("]", "\\]")
                                 .replace(".", "\\.")), tok)
    eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                       tokenizer=tok, fsm=fsm, fast_forward=8)
    budget = 10
    res = eng.generate("go", max_new_tokens=64, byte_budget=budget)
    n = len(res.text.encode())
    assert not res.finished  # truncated by bytes, not EOS
    # overshoot bounded by ONE token's bytes, exactly like the non-ff path
    max_tok_bytes = max(len(tok.token_bytes(t)) for t in res.token_ids)
    assert n < budget + max_tok_bytes
    assert lit.startswith(res.text)
