"""Warm-state handoff (serve.handoff, ISSUE 13) — FAST tier, because the
re-home identity contract gates tier-1.

The non-negotiable contract: a session re-homed with warm state produces
TOKEN-IDENTICAL output to having stayed home — per KV tier (off/int8/int4)
— and every fallback (mid-chain-evicted donor, pool-pressured recipient,
tier mismatch, malformed blob) is CLEAN: the transcript still ships, the
next turn cold-prefills, the tokens still match, the fallback is counted.
Block accounting must balance on both ends (allocator refcounts are the
single source of truth, exactly like the radix plane's own tests).
"""

import pytest

from tpu_voice_agent.serve import PagedDecodeEngine
from tpu_voice_agent.serve import handoff
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import (
    SessionTranscripts,
    install_prompt_prefix,
)
from tpu_voice_agent.utils import get_metrics

BUCKETS = (128, 256, 512, 1024, 2048)
SID = "handoff-session"

TURNS = [
    ("search for wireless headphones", {}),
    ("open the second result", {"last_query": "wireless headphones"}),
]
TURN3 = ("sort these by price from low to high",
         {"last_query": "wireless headphones"})


def _paged(kv_quant=None, **kw):
    eng = PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=2,
                            prefill_buckets=BUCKETS, radix_enable=True,
                            kv_quant=kv_quant, **kw)
    install_prompt_prefix(eng)
    return eng


def _run(eng, prompts, max_new=32):
    return ContinuousBatcher(eng, chunk_steps=16,
                             max_new_tokens=max_new).generate_many(prompts)


def _play(eng, transcripts, turns, sid=SID):
    """Drive turns exactly like the session-aware brain (prompt_for /
    record); returns per-turn GenerationResults."""
    out = []
    for text, ctx in turns:
        prompt = transcripts.prompt_for(sid, text, ctx)
        r = _run(eng, [prompt])[0]
        assert r.error is None, r.error
        transcripts.record(sid, prompt, r.token_ids)
        out.append(r)
    return out


def _counters():
    return get_metrics().snapshot()["counters"]


def _assert_balanced(eng):
    """Every live block is owned by the engine prefix or the radix tree
    (slots are all released): blocks_in_use must equal prefix blocks plus
    the tree's non-pinned nodes — a leak or double-free breaks this."""
    pb = len(eng._prefix_blocks[0])
    nodes = eng.radix[0].nodes
    assert eng.allocator.blocks_in_use == pb + (nodes - pb)


# ------------------------------------------------------------ happy path


@pytest.mark.parametrize("tier", [None, "int8", "int4"])
def test_rehomed_turn_token_identical_per_tier(tier):
    """THE differential: donor plays two turns, ships the session, the
    recipient's turn 3 is token-identical to the donor's own turn 3 —
    with the full transcript chain served from adopted KV (cached_tokens
    match), per storage tier."""
    donor, recip = _paged(tier), _paged(tier)
    tr_d = SessionTranscripts(donor.tokenizer)
    tr_r = SessionTranscripts(recip.tokenizer)
    _play(donor, tr_d, TURNS)
    blob = handoff.export_session(donor, tr_d, SID)
    assert blob is not None
    stay = _play(donor, tr_d, [TURN3])[0]
    adopted = handoff.adopt_session(recip, tr_r, blob)
    P = len(donor.prefix_ids)
    assert adopted > P  # a real chain beyond the static prefix shipped
    moved = _play(recip, tr_r, [TURN3])[0]
    assert moved.token_ids == stay.token_ids
    assert moved.cached_tokens == stay.cached_tokens
    assert moved.cached_tokens >= adopted  # the adopted chain was SERVED
    _assert_balanced(recip)
    _assert_balanced(donor)


def test_adopt_is_idempotent_and_leak_free():
    """Adopting the same blob twice (a retried handoff) must not leak
    blocks or duplicate tree nodes — the duplicate chain's blocks fall
    straight back to the free list."""
    donor, recip = _paged(), _paged()
    tr_d = SessionTranscripts(donor.tokenizer)
    tr_r = SessionTranscripts(recip.tokenizer)
    _play(donor, tr_d, TURNS)
    blob = handoff.export_session(donor, tr_d, SID)
    a1 = handoff.adopt_session(recip, tr_r, blob)
    nodes1 = recip.radix[0].nodes
    used1 = recip.allocator.blocks_in_use
    a2 = handoff.adopt_session(recip, tr_r, blob)
    assert a1 == a2 > 0
    assert recip.radix[0].nodes == nodes1
    assert recip.allocator.blocks_in_use == used1
    _assert_balanced(recip)


def test_pack_unpack_roundtrip_and_malformed_blob():
    import numpy as np

    arrays = {"k": np.arange(12, dtype=np.int8).reshape(3, 4),
              "s": np.ones((2, 2), dtype=np.float32)}
    blob = handoff.pack({"session_id": "x", "ids": [1, 2]}, arrays)
    meta, out = handoff.unpack(blob)
    assert meta["ids"] == [1, 2]
    assert out["k"].tolist() == arrays["k"].tolist()
    assert out["s"].dtype == np.float32
    with pytest.raises(ValueError):
        handoff.unpack(b"not a handoff blob")
    with pytest.raises(ValueError):
        handoff.unpack(blob[:-4])  # truncated array bytes


# ------------------------------------------------------------- fallbacks


def test_mid_chain_evicted_donor_still_ships_transcript_and_matches():
    """The donor's session chain was (partially) evicted before the
    handoff: whatever still matches ships; the transcript always ships;
    the recipient's turn is token-identical either way (the un-shipped
    span just re-prefills)."""
    donor, recip = _paged(), _paged()
    tr_d = SessionTranscripts(donor.tokenizer)
    tr_r = SessionTranscripts(recip.tokenizer)
    _play(donor, tr_d, TURNS)
    # evict EVERYTHING evictable (the whole unreferenced session chain)
    donor.radix[0].evict(10_000)
    blob = handoff.export_session(donor, tr_d, SID)
    assert blob is not None
    stay = _play(donor, tr_d, [TURN3])[0]
    adopted = handoff.adopt_session(recip, tr_r, blob)
    assert adopted == 0  # nothing beyond the static prefix was cached
    moved = _play(recip, tr_r, [TURN3])[0]
    assert moved.token_ids == stay.token_ids  # cold re-prefill, same tokens
    assert moved.cached_tokens >= len(recip.prefix_ids) // recip.block_size \
        * recip.block_size  # its own pinned prefix still serves
    _assert_balanced(recip)


def test_pool_pressured_recipient_falls_back_cold_counted():
    """The recipient's pool cannot take the chain (PoolExhausted even
    after radix eviction): adoption returns 0, the fallback is counted,
    the transcript is still adopted, and the next turn is token-identical
    through a cold prefill."""
    donor, recip = _paged(), _paged()
    tr_d = SessionTranscripts(donor.tokenizer)
    tr_r = SessionTranscripts(recip.tokenizer)
    _play(donor, tr_d, TURNS)
    blob = handoff.export_session(donor, tr_d, SID)
    stay = _play(donor, tr_d, [TURN3])[0]
    # squeeze the recipient's pool: hold every free block so the adoption
    # alloc fails with nothing evictable, then release the squeeze
    hold = recip.allocator.alloc(recip.allocator.free_blocks(0))
    before = _counters().get("handoff.adopt_fallbacks", 0)
    adopted = handoff.adopt_session(recip, tr_r, blob)
    assert adopted == 0
    assert _counters().get("handoff.adopt_fallbacks", 0) == before + 1
    assert tr_r.peek(SID) is not None  # the transcript DID ship
    recip.allocator.free(hold)
    moved = _play(recip, tr_r, [TURN3])[0]
    assert moved.token_ids == stay.token_ids
    _assert_balanced(recip)


def test_capacity_capped_recipient_tree_counts_cold():
    """The recipient's radix tree is at max_nodes with only pinned nodes:
    insert adopts nothing and the blocks fall back to the pool — the
    adoption must report COLD (counted), never a warm re-home that the
    next turn then cold-prefills anyway."""
    donor = _paged()
    tr_d = SessionTranscripts(donor.tokenizer)
    _play(donor, tr_d, TURNS)
    blob = handoff.export_session(donor, tr_d, SID)
    # cap the recipient's tree at exactly its pinned prefix chain
    recip = PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=2,
        prefill_buckets=BUCKETS, radix_enable=True, radix_max_nodes=1)
    install_prompt_prefix(recip)  # pin_root_chain installs regardless
    tr_r = SessionTranscripts(recip.tokenizer)
    used0 = recip.allocator.blocks_in_use
    before = _counters().get("handoff.adopt_fallbacks", 0)
    adopted = handoff.adopt_session(recip, tr_r, blob)
    assert adopted == 0
    assert _counters().get("handoff.adopt_fallbacks", 0) == before + 1
    assert recip.allocator.blocks_in_use == used0  # blocks fell back
    assert tr_r.peek(SID) is not None  # transcript still shipped


def test_tier_mismatch_falls_back_clean():
    """Donor int8, recipient bf16: the KV bytes are not adoptable (the
    stored formats differ) — transcript-only adoption, counted, and the
    recipient still parses the turn without error."""
    donor, recip = _paged("int8"), _paged(None)
    tr_d = SessionTranscripts(donor.tokenizer)
    tr_r = SessionTranscripts(recip.tokenizer)
    _play(donor, tr_d, TURNS)
    blob = handoff.export_session(donor, tr_d, SID)
    before = _counters().get("handoff.adopt_fallbacks", 0)
    adopted = handoff.adopt_session(recip, tr_r, blob)
    assert adopted == 0
    assert _counters().get("handoff.adopt_fallbacks", 0) == before + 1
    moved = _play(recip, tr_r, [TURN3])[0]
    assert moved.error is None and recip.fsm.walk(moved.token_ids) >= 0
    _assert_balanced(recip)


def test_handoff_kv_ablation_ships_transcript_only(monkeypatch):
    """HANDOFF_KV=0 (the cold-re-home baseline the bench measures): the
    blob carries no arrays, adoption is transcript-only, and the turn is
    still token-identical — only the prefill cost differs."""
    donor, recip = _paged(), _paged()
    tr_d = SessionTranscripts(donor.tokenizer)
    tr_r = SessionTranscripts(recip.tokenizer)
    _play(donor, tr_d, TURNS)
    monkeypatch.setenv("HANDOFF_KV", "0")
    blob = handoff.export_session(donor, tr_d, SID)
    monkeypatch.delenv("HANDOFF_KV")
    meta, arrays = handoff.unpack(blob)
    assert not arrays and meta["chain_tokens"] == 0
    stay = _play(donor, tr_d, [TURN3])[0]
    assert handoff.adopt_session(recip, tr_r, blob) == 0
    moved = _play(recip, tr_r, [TURN3])[0]
    assert moved.token_ids == stay.token_ids
    assert moved.cached_tokens < stay.cached_tokens  # cold: prefix only
