"""Multi-model colocation: Whisper + Llama sharing one device/mesh.

SURVEY.md §7 step 6 / hard part (3): two heterogeneous models, bucketed
shapes, interleaved dispatch with STT priority. CPU-only per the test seam
strategy (§4).
"""

import json

import numpy as np
import pytest

from tpu_voice_agent.serve.colocate import ColocatedServing
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.serve.stt import SpeechEngine

def _prompt(utterance: str) -> str:
    # short prompt (the full few-shot prompt overflows the tiny engine's
    # 512-token test bucket; grammar constraint holds regardless)
    import json as _json
    user = _json.dumps({"text": utterance, "context": {}}, separators=(",", ":"))
    return f"<|user|>\n{user}\n<|assistant|>\n"



@pytest.fixture(scope="module")
def stt_engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(100,), max_new_tokens=8)


def _audio(ms: float = 400.0) -> np.ndarray:
    n = int(16_000 * ms / 1000)
    return (0.1 * np.sin(2 * np.pi * 440 * np.arange(n) / 16_000)).astype(np.float32)


def test_colocated_drain_completes_both_lanes(stt_engine, tiny_batch_engine):
    co = ColocatedServing(stt_engine, ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                                                        max_new_tokens=192))
    stt_futs = [co.submit_stt(_audio()) for _ in range(2)]
    parse_futs = [
        co.submit_parse(_prompt(u))
        for u in ("search for shoes", "scroll down")
    ]
    co.drain(timeout_s=300)
    for f in stt_futs:
        res = f.result(timeout=1)
        assert isinstance(res.text, str) and res.n_frames > 0
    for f in parse_futs:
        res = f.result(timeout=1)
        assert res.error is None
        if res.finished:  # truncated decodes may stop mid-JSON
            json.loads(res.text)  # grammar-constrained => must parse
    assert co.stats.stt_jobs == 2 and co.stats.parse_jobs == 2
    assert co.stats.decode_chunks >= 1


def test_stt_preempts_between_decode_chunks(stt_engine, tiny_batch_engine):
    """An STT job submitted mid-decode must run at the next chunk boundary,
    not after the whole decode finishes (bounded queueing delay)."""
    co = ColocatedServing(stt_engine, ContinuousBatcher(tiny_batch_engine, chunk_steps=4,
                                                        max_new_tokens=64))
    parse_fut = co.submit_parse(_prompt("sort by price low to high"))
    assert co.step()  # admit + first decode chunk
    assert not parse_fut.done()
    stt_fut = co.submit_stt(_audio())
    assert co.step()  # STT lane must clear within this single step
    assert stt_fut.done()
    co.drain(timeout_s=300)
    assert parse_fut.result(timeout=1).error is None
    first_stt = co.stats.trace.index("stt")
    last_chunk = len(co.stats.trace) - 1 - co.stats.trace[::-1].index("chunk")
    assert first_stt < last_chunk  # interleaved, not appended at the end


def test_worker_thread_serves_both(stt_engine, tiny_batch_engine):
    co = ColocatedServing(stt_engine, ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                                                        max_new_tokens=48))
    co.start()
    try:
        stt_fut = co.submit_stt(_audio(200))
        parse_fut = co.submit_parse(_prompt("go back"))
        assert stt_fut.result(timeout=300).n_frames > 0
        assert parse_fut.result(timeout=300).error is None
    finally:
        co.stop()


def test_abandon_parse_dequeues_without_racing_worker(tiny_batch_engine):
    """A timed-out request must be dequeued (tombstone applied on the worker
    step path) and its orphaned result purged — overload cannot accumulate
    abandoned work. The surviving request still completes."""
    co = ColocatedServing(None, ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                                                  max_new_tokens=64))
    keep = co.submit_parse(_prompt("search for keyboards"))
    drop = co.submit_parse(_prompt("take a screenshot"))
    co.abandon_parse(drop)
    co.drain(timeout_s=300)
    assert keep.result(timeout=1) is not None
    assert drop.cancelled()
    # nothing left behind: no pending work, no orphaned futures or results
    assert not co.batcher.pending
    assert not co._parse_futs
    assert not co.batcher.results


def test_stt_less_runtime_rejects_stt_jobs(tiny_batch_engine):
    co = ColocatedServing(None, ContinuousBatcher(tiny_batch_engine, chunk_steps=8))
    with pytest.raises(RuntimeError):
        co.submit_stt(_audio())
