"""Intent interpreter tests over the fake page.

Extends the reference's executor test (apps/executor/test/actions.test.ts:
drive runIntents with navigate/wait_for/extract_table against a stub page)
to the FULL 19-intent vocabulary, including the 8 the reference dropped.
"""

import json
from pathlib import Path

import pytest

from tpu_voice_agent.schemas import Intent, Target
from tpu_voice_agent.services.executor import FakePage, run_intents
from tpu_voice_agent.services.executor.page import FakeElement


def rich_page() -> FakePage:
    return FakePage(
        elements=[
            FakeElement("#search", tag="input", etype="search", placeholder="Search products"),
            FakeElement("#add-to-cart", tag="button", text="Add to Cart", role="button", name="Add to Cart"),
            FakeElement("#submit", tag="button", text="Submit", role="button", name="Submit"),
            FakeElement("a.result1", tag="a", text="First result"),
            FakeElement("a.result2", tag="a", text="Second result"),
            FakeElement("#sortsel", tag="select", name="sort", options=["Featured", "Price Low to High", "Price High to Low"]),
            FakeElement("#minprice", tag="input", name="min-price"),
            FakeElement("#maxprice", tag="input", name="max-price"),
            FakeElement("#fileinput", tag="input", etype="file", attrs={"type": "file"}),
            FakeElement(".results", tag="div", text="results container"),
            FakeElement("#sizesel", tag="select", name="size", options=["Small", "Large"]),
        ]
    )


@pytest.fixture()
def page():
    return rich_page()


def run(page, tmp_path, *intents, uploads_dir=None):
    return run_intents(page, tmp_path / "art", list(intents), uploads_dir=uploads_dir)


def test_reference_chain_navigate_wait_extract(page, tmp_path):
    """The reference's own test scenario, but wait_for actually works here."""
    results = run(
        page, tmp_path,
        Intent(type="navigate", args={"url": "shop.example.com"}),
        Intent(type="wait_for", target=Target(strategy="css", value=".results")),
        Intent(type="extract_table", args={"format": "csv"}),
    )
    assert [r.ok for r in results] == [True, True, True]
    assert page.url == "https://shop.example.com"
    assert results[2].data["count"] == 2
    json_path = Path(results[2].data_paths[0])
    assert json.loads(json_path.read_text())[0]["title"] == "Fake Product A"
    assert any(p.endswith(".csv") for p in results[2].data_paths)
    # full-page screenshot after every step (reference actions.ts:37-41)
    assert all(r.screenshot and Path(r.screenshot).exists() for r in results)


def test_search_fills_box_and_presses_enter(page, tmp_path):
    (res,) = run(page, tmp_path, Intent(type="search", args={"query": "laptops"}))
    assert res.ok
    assert ("fill", "#search", "laptops") in page.actions
    assert ("press", "#search", "Enter") in page.actions


def test_click_strategies(page, tmp_path):
    results = run(
        page, tmp_path,
        Intent(type="click", target=Target(strategy="css", value="#add-to-cart")),
        Intent(type="click", target=Target(strategy="text", value="Submit")),
        Intent(type="click", target=Target(strategy="role", role="button", name="Add to Cart")),
        Intent(type="click", args={"index": 2}),  # auto: second analyzed link
        Intent(type="click", args={"text": "Add to Cart"}),  # auto: analyzed text
    )
    assert [r.ok for r in results] == [True] * 5
    assert results[3].data["selector"] == "a.result2"


def test_sort_selects_direction_option(page, tmp_path):
    (res,) = run(page, tmp_path, Intent(type="sort", args={"field": "price", "direction": "asc"}))
    assert res.ok and res.data["option"] == "Price Low to High"
    (res,) = run(page, tmp_path, Intent(type="sort", args={"field": "price", "direction": "desc"}))
    assert res.ok and res.data["option"] == "Price High to Low"


def test_filter_price_lte_fills_max_input(page, tmp_path):
    (res,) = run(
        page, tmp_path,
        Intent(type="filter", args={"field": "price", "op": "lte", "value": 100}),
    )
    assert res.ok
    assert ("fill", "#maxprice", "100") in page.actions


def test_type_select_scroll_back_forward(page, tmp_path):
    results = run(
        page, tmp_path,
        Intent(type="navigate", args={"url": "a.com"}),
        Intent(type="navigate", args={"url": "b.com"}),
        Intent(type="back"),
        Intent(type="forward"),
        Intent(type="scroll", args={"direction": "down", "amount": 2}),
        Intent(type="select", target=Target(strategy="css", value="#sizesel"), args={"label": "Large"}),
        Intent(type="type", target=Target(strategy="css", value="#search"), args={"text": "hi"}),
    )
    assert all(r.ok for r in results), [r.error for r in results]
    assert page.url == "https://b.com"
    assert ("scroll_by", 0, 1600) in page.actions
    assert ("select_option", "#sizesel", "Large") in page.actions


def test_upload_resolves_resume_ref(page, tmp_path):
    uploads = tmp_path / "uploads"
    uploads.mkdir()
    (uploads / "abc123.pdf").write_bytes(b"%PDF fake")
    (res,) = run(
        page, tmp_path,
        Intent(type="upload", args={"fileRef": "resume://abc123"}, requires_confirmation=True),
        uploads_dir=uploads,
    )
    assert res.ok, res.error
    assert res.data["path"].endswith("abc123.pdf")
    assert any(a[0] == "set_input_files" for a in page.actions)


def test_upload_missing_file_fails_cleanly(page, tmp_path):
    (res,) = run(
        page, tmp_path,
        Intent(type="upload", args={"fileRef": "resume://deadbeef0000"}),
        uploads_dir=tmp_path,
    )
    assert not res.ok and "not found" in res.error


def test_upload_rejects_hostile_refs(page, tmp_path):
    for ref in ("resume://../../../etc/passwd", "resume://*", "resume://x"):
        (res,) = run(page, tmp_path, Intent(type="upload", args={"fileRef": ref}), uploads_dir=tmp_path)
        assert not res.ok and "malformed" in res.error, ref


def test_screenshot_summarize_extract_confirm_cancel_unknown(page, tmp_path):
    results = run(
        page, tmp_path,
        Intent(type="screenshot"),
        Intent(type="summarize"),
        Intent(type="extract"),
        Intent(type="confirm"),
        Intent(type="cancel"),
        Intent(type="unknown"),
    )
    oks = [r.ok for r in results]
    assert oks == [True, True, True, True, True, False]
    assert Path(results[0].data["path"]).exists()
    assert results[1].data["word_count"] > 0
    assert "unsupported" in results[5].error


def test_step_errors_do_not_abort_batch(page, tmp_path):
    page.fail_next = "click"
    results = run(
        page, tmp_path,
        Intent(type="click", target=Target(strategy="css", value="#add-to-cart")),
        Intent(type="screenshot"),
    )
    assert not results[0].ok and results[1].ok


def test_retries_recover_from_transient_fault(page, tmp_path):
    page.fail_next = "click"
    (res,) = run(
        page, tmp_path,
        Intent(type="click", target=Target(strategy="css", value="#add-to-cart"), retries=1),
    )
    assert res.ok  # second attempt succeeded


def test_all_19_intent_types_have_an_implementation(tmp_path):
    """No schema-legal intent may hit an 'unsupported' branch except unknown."""
    from tpu_voice_agent.schemas import INTENT_TYPES

    uploads = tmp_path / "up"
    uploads.mkdir()
    (uploads / "abcdef.txt").write_text("x")
    arg_map = {
        "search": {"query": "q"},
        "navigate": {"url": "x.com"},
        "type": {"selector": "#search", "text": "t"},
        "sort": {"field": "price", "direction": "asc"},
        "filter": {"field": "price", "op": "lte", "value": 5},
        "scroll": {},
        "select": {"selector": "#sizesel", "label": "Small"},
        "wait_for": {"selector": ".results"},
        "upload": {"fileRef": "resume://abcdef"},
        "extract_table": {},
        "click": {"text": "Submit"},
    }
    for t in INTENT_TYPES:
        page = rich_page()
        (res,) = run_intents(
            page, tmp_path / f"art_{t}", [Intent(type=t, args=arg_map.get(t, {}))],
            uploads_dir=uploads,
        )
        if t == "unknown":
            assert not res.ok
        else:
            assert res.ok, f"{t} failed: {res.error}"


def test_grounding_failure_is_observable(page, tmp_path):
    """A broken grounder must not silently degrade (round-2 verdict weak #3):
    the fallback text click carries the grounding error and the failure is
    counted in the runtime metrics."""
    from tpu_voice_agent.utils import get_metrics

    def broken_grounder(image, instruction):
        raise RuntimeError("vision tower on fire")

    before = get_metrics().snapshot()["counters"].get("executor.grounding_failed", 0)
    # "Second result" is a link, not in buttons — but IS in links, so use a
    # target that misses every analyzed bucket yet text-clicks fine
    page.elements.append(FakeElement("#odd", tag="span", text="Mystery Widget"))
    (res,) = run_intents(
        page, tmp_path / "art",
        [Intent(type="click", args={"text": "Mystery Widget"})],
        grounder=broken_grounder,
    )
    assert res.ok
    assert res.data["by"] == "text"
    assert "vision tower on fire" in res.data["grounding_error"]
    after = get_metrics().snapshot()["counters"].get("executor.grounding_failed", 0)
    assert after == before + 1


def test_summarize_uses_injected_llm(page, tmp_path):
    calls = []

    def summarizer(title, body):
        calls.append((title, body))
        return "A concise summary."

    (res,) = run_intents(page, tmp_path / "art",
                         [Intent(type="summarize")], summarizer=summarizer)
    assert res.ok
    assert res.data["summary"] == "A concise summary."
    assert res.data["by"] == "llm"
    assert calls and calls[0][0] == "Fake Page"


def test_summarize_falls_back_to_truncation_on_llm_failure(page, tmp_path):
    def summarizer(title, body):
        raise RuntimeError("engine OOM")

    (res,) = run_intents(page, tmp_path / "art",
                         [Intent(type="summarize")], summarizer=summarizer)
    assert res.ok
    assert res.data["by"] == "truncate"
    assert "engine OOM" in res.data["summarizer_error"]
    assert res.data["summary"]  # truncation fallback still summarizes
