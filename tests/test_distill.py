"""In-tree tiny-checkpoint training (round-3 VERDICT next #2): the
train -> checkpoint -> constrained-serve loop produces REAL quality numbers
with zero external weights.

Full-budget training lives in ``python -m tpu_voice_agent.train.make_tiny_
ckpts`` (~10 min CPU) and is scored by benches/bench_quality.py; these tests
run scaled-down budgets that still prove each link of the chain.
"""

import numpy as np
import pytest

from tpu_voice_agent.evals.golden import GoldenCase, score_parser
from tpu_voice_agent.evals.wer import wer
from tpu_voice_agent.train import distill


def test_synth_corpus_disjoint_from_golden():
    """Held-out means held out: no golden utterance may appear in training."""
    from tpu_voice_agent.evals.golden import GOLDEN_INTENT_CASES

    texts = {t for t, _, _ in distill.synth_intent_corpus(800, seed=3)}
    assert not texts & {c.text for c in GOLDEN_INTENT_CASES}


def test_corpus_labels_are_grammar_valid():
    """Every teacher label must be accepted by the decode grammar — a label
    the FSM cannot emit would train mass onto unreachable sequences."""
    from tpu_voice_agent.grammar.intent_grammar import build_intent_fsm

    tokenizer, fsm = build_intent_fsm()
    for text, ctx, resp_json in distill.synth_intent_corpus(60, seed=5):
        ids = tokenizer.encode(resp_json)
        assert fsm.walk(ids) >= 0, f"label left the grammar: {resp_json[:80]}"


@pytest.fixture(scope="module")
def trained_intent():
    """ONE scaled-down training run shared by the serve + ckpt tests (a
    1-core box pays ~0.35 s/step; two separate trainings doubled the
    module's wall-clock for no extra coverage)."""
    # stream=False: the fixture's job is serve/ckpt mechanics, and epoch
    # mode over a small fixed corpus memorizes quickly (reliable EOS)
    # where the same steps of streaming fresh data still truncate. The
    # round-5 corpus is richer (longer phrases, dialogs), so the fixture
    # runs more epochs over fewer examples than the old 260x1000.
    return distill.train_intent_model(steps=500, seq_len=320, batch=16,
                                      corpus_n=500, dialogs_n=40,
                                      stream=False)


def test_dialogs_disjoint_from_golden():
    """No golden utterance — single-turn case OR dialog turn — may appear
    in the training dialogs (a golden dialog's search phrase showing up in
    training would hand the copy task its answer)."""
    from tpu_voice_agent.evals.golden import GOLDEN_DIALOGS, GOLDEN_INTENT_CASES

    golden = {c.text for c in GOLDEN_INTENT_CASES}
    for d in GOLDEN_DIALOGS:
        golden.update(d.turns)
    for turns in distill.synth_intent_dialogs(150, seed=4):
        assert not {t for t, _, _ in turns} & golden


def test_dialog_batches_put_eos_target_at_mid_plan_ends():
    """The position AT a mid-dialog plan's last token must target EOS with
    loss on (that is how a served turn stops decoding) while the
    teacher-forced TRANSCRIPT continues with the next <|user|> segment —
    planner transcripts never contain EOS (serve.planner.plan_many)."""
    from tpu_voice_agent.grammar.intent_grammar import build_intent_fsm

    tok, _ = build_intent_fsm()
    dlg = distill.synth_intent_dialogs(1, seed=2)[0]
    assert len(dlg) >= 2
    toks, tgts, masks = distill.build_intent_batches(
        [], tok, 512, 1, dialogs=[dlg])
    toks, tgts, masks = toks[0, 0], tgts[0, 0], masks[0, 0]
    eos_positions = [i for i in range(len(toks))
                     if tgts[i] == tok.eos_id and masks[i] > 0]
    # one termination target per turn
    assert len(eos_positions) == len(dlg), eos_positions
    for p in eos_positions[:-1]:  # mid-dialog ends
        # the transcript itself continues (teacher-forced input is NOT eos)
        assert toks[p + 1] != tok.eos_id
        # and the next literal tokens open the next user turn
        tail = tok.decode([int(t) for t in toks[p + 1: p + 6]])
        assert tail.startswith("\n<|user|>"), repr(tail)
    # the final plan terminates in-transcript
    assert toks[eos_positions[-1] + 1] == tok.eos_id


@pytest.mark.slow
def test_intent_distillation_learns_and_serves(trained_intent):
    """A scaled-down training run must (a) collapse the loss and (b) yield
    a parser that, through the REAL grammar-constrained engine with the
    short distilled prompt, classifies utterances far above chance."""
    cfg, params, stats = trained_intent
    assert stats["final_loss"] < stats["first_loss"] * 0.1, stats
    parser = distill.intent_engine_from(cfg, params)
    # probe with held-out utterances from the easy families (chance over
    # the 19-type enum would be ~5% per intent; demand well above)
    cases = [
        GoldenCase("scroll down", ("scroll",)),
        GoldenCase("go back", ("back",)),
        GoldenCase("take a screenshot of this page", ("screenshot",)),
        GoldenCase("cancel that", ("cancel",)),
        GoldenCase("summarize this page", ("summarize",)),
        GoldenCase("open the third result", ("click",)),
    ]
    scores = score_parser(parser, cases)
    assert scores["errors"] == 0
    assert scores["type_accuracy"] >= 0.5, scores


@pytest.mark.slow
def test_distilled_weights_serve_through_planner_sessions(trained_intent):
    """The planner-distilled backend shape: distilled cfg/params behind the
    session-keyed planner with the SHORT prompt, a 2-turn session feeding
    the second turn only the transcript (context={}). Scaled-down training
    -> assert structure (valid plans, session reuse), not semantics."""
    from tpu_voice_agent.parallel.ring import sp_mesh
    from tpu_voice_agent.serve import LongSessionPlanner
    from tpu_voice_agent.services.brain import PlannerParser

    cfg, params, _ = trained_intent
    planner = LongSessionPlanner(cfg=cfg, mesh=sp_mesh(1),
                                 ctx_buckets=(512, 1024))
    planner.load_params(params)
    parser = PlannerParser(planner, render=distill.distilled_prompt)
    r1 = parser.parse("search for red shoes", {}, session_id="t")
    r2 = parser.parse("open the second result", {}, session_id="t")
    assert r1.intents and r2.intents  # grammar-valid plans both turns
    assert parser.session_count() == 1  # one session carried both turns


@pytest.mark.slow
def test_whisper_overfit_transcribes_and_roundtrips_ckpt(tmp_path):
    """Overfitting the acoustic-font pairs must push WER far below 1.0 (a
    random decoder scores ~1.0), and the checkpoint must restore through
    orbax into an engine that transcribes identically."""
    texts = distill.WHISPER_EVAL_TEXTS[:4]
    cfg, params, stats = distill.train_whisper_overfit(texts=texts, steps=220)
    assert stats["final_loss"] < stats["first_loss"] * 0.05, stats
    eng = distill.whisper_engine_from(cfg, params)
    errs = [wer(t, eng.transcribe(distill.render_speech(t)).text) for t in texts]
    assert float(np.mean(errs)) < 0.5, list(zip(texts, errs))

    from tpu_voice_agent.models.whisper import WhisperConfig

    distill.save_ckpt(str(tmp_path), distill.WHISPER_CKPT, cfg, params, stats)
    cfg2, params2 = distill.load_ckpt(str(tmp_path), distill.WHISPER_CKPT,
                                      WhisperConfig)
    assert cfg2 == cfg
    eng2 = distill.whisper_engine_from(cfg2, params2)
    for t in texts:
        a = eng.transcribe(distill.render_speech(t)).text
        b = eng2.transcribe(distill.render_speech(t)).text
        assert a == b


@pytest.mark.slow
def test_intent_ckpt_roundtrip_preserves_parses(tmp_path, trained_intent):
    """save_ckpt/load_ckpt through orbax must reproduce the parser's output
    token-for-token (the serve path the bench harness uses)."""
    cfg, params, stats = trained_intent
    from tpu_voice_agent.models.llama import LlamaConfig

    distill.save_ckpt(str(tmp_path), distill.INTENT_CKPT, cfg, params, stats)
    cfg2, params2 = distill.load_ckpt(str(tmp_path), distill.INTENT_CKPT,
                                      LlamaConfig)
    assert cfg2 == cfg
    p1 = distill.intent_engine_from(cfg, params)
    p2 = distill.intent_engine_from(cfg2, params2)
    for text in ("scroll down please", "find quiet fans"):
        r1 = p1.parse(text, {})
        r2 = p2.parse(text, {})
        assert r1.model_dump() == r2.model_dump()
