"""Decode engine: grammar-constrained generation always yields valid intents.

The money test: a RANDOM-weight tiny model (worst-case language model) must
still emit schema-valid ParseResponse JSON under the grammar constraint —
the property that lets the brain service drop the reference's repair loop.
"""

import jax
import pytest

from tpu_voice_agent.schemas import parse_response_from_json
from tpu_voice_agent.serve import DecodeEngine


@pytest.fixture()
def engine(tiny_engine):
    return tiny_engine


def test_constrained_generation_is_always_valid(engine):
    res = engine.generate("parse this: search for shoes", max_new_tokens=400, greedy=True)
    assert res.finished, f"decode should reach EOS, got {res.steps} steps: {res.text[:120]}"
    model, err = parse_response_from_json(res.text)
    assert model is not None, f"constrained output failed validation: {err}"


def test_constrained_sampling_is_always_valid(engine):
    res = engine.generate(
        "anything at all", max_new_tokens=400, greedy=False, temperature=1.5
    )
    assert res.finished
    model, err = parse_response_from_json(res.text)
    assert model is not None, err


def test_engine_is_reusable_across_requests(engine):
    """Cache reuse across requests must not leak previous-request state."""
    r1 = engine.generate("first request with a long utterance to parse", max_new_tokens=300)
    r2 = engine.generate("x", max_new_tokens=300)
    for r in (r1, r2):
        model, err = parse_response_from_json(r.text)
        assert model is not None, err


def test_device_loop_matches_stepwise_greedy(engine):
    """The on-device while_loop generation must produce exactly the host
    stepwise loop's tokens under greedy decoding."""
    prompt = "search for usb hubs then screenshot"
    a = engine.generate(prompt, max_new_tokens=300, greedy=True)
    b = engine.generate_stepwise(prompt, max_new_tokens=300, greedy=True)
    assert a.token_ids == b.token_ids


def test_prompt_too_long_raises(engine):
    with pytest.raises(ValueError):
        engine.generate("word " * 2000)


def test_truncation_reports_unfinished(engine):
    res = engine.generate("truncate me", max_new_tokens=300, byte_budget=25)
    assert not res.finished, "byte-budget truncation must not report finished"


def test_dp_mesh_requires_divisible_batch_slots():
    from tpu_voice_agent.parallel.mesh import make_mesh
    from tpu_voice_agent.serve import DecodeEngine

    with pytest.raises(ValueError, match="divisible"):
        DecodeEngine(preset="test-tiny", mesh=make_mesh(dp=2, tp=1), batch_slots=1)


def test_generation_result_stats(engine):
    res = engine.generate("measure me", max_new_tokens=300)
    assert res.prefill_ms > 0 and res.steps > 0
    assert res.tokens_per_s > 0


@pytest.mark.slow  # each test builds (and compiles) its own quantized engine
class TestQuantizedEngine:
    def test_int8_structure_and_range(self):
        import jax
        import jax.numpy as jnp

        from tpu_voice_agent.models.llama import (
            LlamaConfig, init_params, quantize_params,
        )

        cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, ffn_dim=64, max_seq_len=32)
        q = quantize_params(init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32))
        assert q["layers"]["wq"]["q"].dtype == jnp.int8
        assert q["layers"]["attn_norm"].dtype != jnp.int8  # norms stay raw
        assert q["embed"].ndim == 2  # embedding gather stays raw
        import numpy as np

        assert np.abs(np.asarray(q["lm_head"]["q"])).max() <= 127

    def test_int8_dequant_is_close(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_voice_agent.models.llama import _w, LlamaConfig, init_params, quantize_params

        cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, ffn_dim=64, max_seq_len=32)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        q = quantize_params(params)
        w = np.asarray(params["layers"]["w_gate"], np.float32)
        wq = np.asarray(_w(q["layers"]["w_gate"]), np.float32)
        # per-channel symmetric int8 (error <= scale/2) + bf16 dequant
        # rounding (relative ~2^-8)
        scale = np.abs(w).max(axis=-2, keepdims=True) / 127.0
        assert np.all(np.abs(w - wq) <= scale * 0.75 + np.abs(w) * 2.0**-7 + 1e-6)

    def test_int8_engine_generates_grammar_valid(self):
        import json

        from tpu_voice_agent.serve import DecodeEngine

        eng = DecodeEngine(preset="test-tiny", max_len=512, prefill_buckets=(64,),
                           quant="int8")
        res = eng.generate('<|user|>\ngo back\n<|assistant|>\n', max_new_tokens=192)
        assert res.error is None
        if res.finished:
            json.loads(res.text)  # constrained decode survives quantization

    def test_int8_on_mesh_matches_single_device(self):
        """int8 on a (dp=1, tp=2) mesh: quantized {"q","s"} leaves get real
        shardings (round-2 verdict missing #4) and greedy constrained decode
        stays token-identical to the single-device int8 engine."""
        import jax.numpy as jnp

        from tpu_voice_agent.models.llama import init_params
        from tpu_voice_agent.parallel.mesh import make_mesh

        single = DecodeEngine(preset="test-tiny", max_len=512,
                              prefill_buckets=(64,), quant="int8",
                              init_weights=False)
        meshed = DecodeEngine(preset="test-tiny", max_len=512,
                              prefill_buckets=(64,), quant="int8",
                              mesh=make_mesh(dp=1, tp=2), init_weights=False)
        # identical raw weights; the mesh engine pads vocab to a tp multiple
        # (same padding from_hf applies — pad ids are grammar-dead)
        raw = init_params(single.cfg, jax.random.PRNGKey(7))
        single.load_params(raw)
        pad = meshed.cfg.vocab_size - single.cfg.vocab_size
        padded = dict(raw)
        padded["embed"] = jnp.pad(raw["embed"], ((0, pad), (0, 0)))
        padded["lm_head"] = jnp.pad(raw["lm_head"], ((0, 0), (0, pad)))
        meshed.load_params(padded)
        # sharded scale leaves really exist (not silently replicated raw)
        lm = meshed.params["lm_head"]
        assert set(lm.keys()) == {"q", "s"}
        prompt = "<|user|>\nsearch for usb hubs\n<|assistant|>\n"
        a = single.generate(prompt, max_new_tokens=160)
        b = meshed.generate(prompt, max_new_tokens=160)
        assert a.error is None and b.error is None
        assert a.token_ids == b.token_ids
