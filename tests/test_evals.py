"""Quality eval harness: golden intent scoring + WER math.

SURVEY.md §4 called for a golden-file intent-parse eval on the FEWSHOT
distribution; round-2 VERDICT missing #5 called out that nothing measured
model quality. These tests pin the harness itself (scoring semantics, WER
arithmetic, clean-skip plumbing) so checkpoint runs produce trustworthy
numbers.
"""

import numpy as np
import pytest

from tpu_voice_agent.evals import GOLDEN_INTENT_CASES, score_case, score_parser, wer
from tpu_voice_agent.evals.golden import GoldenCase
from tpu_voice_agent.evals.wer import normalize_words, wer_over_dir
from tpu_voice_agent.schemas import Intent, ParseResponse, Target


def _resp(*intents: Intent) -> ParseResponse:
    return ParseResponse(intents=list(intents), context_updates={}, confidence=0.9)


class TestScoring:
    CASE = GoldenCase(
        "sort by price descending", ("sort",),
        facts=((0, "args.field", "price"), (0, "args.direction", "desc")),
    )

    def test_exact_match_scores_full(self):
        tm, args = score_case(
            self.CASE, _resp(Intent(type="sort", args={"field": "price", "direction": "desc"})))
        assert tm and args == 1.0

    def test_wrong_type_fails_types_but_args_scored_independently(self):
        tm, args = score_case(
            self.CASE, _resp(Intent(type="filter", args={"field": "price", "direction": "desc"})))
        assert not tm and args == 1.0

    def test_partial_args(self):
        tm, args = score_case(
            self.CASE, _resp(Intent(type="sort", args={"field": "price", "direction": "asc"})))
        assert tm and args == 0.5

    def test_string_facts_are_substring_case_insensitive(self):
        case = GoldenCase("click checkout", ("click",),
                          facts=((0, "target.value", "checkout"),))
        tm, args = score_case(
            case, _resp(Intent(type="click", target=Target(strategy="text", value="Checkout now"))))
        assert tm and args == 1.0

    def test_rule_parser_clears_the_golden_bar(self):
        """The deterministic offline parser must stay strong on its own
        distribution — a regression here means the golden set or the rule
        parser drifted."""
        from tpu_voice_agent.services.brain import RuleBasedParser

        scores = score_parser(RuleBasedParser())
        assert scores["errors"] == 0
        assert scores["type_accuracy"] >= 0.8, scores
        assert scores["args_score"] >= 0.8, scores

    def test_rule_parser_clears_the_dialog_bar_stateless(self):
        """Multi-turn dialogs via voice-service context threading: the rule
        parser is stateless, so context_updates from earlier turns merge
        into later turns' context (server.ts:162-170 semantics); final
        turns are all rule-parseable families."""
        from tpu_voice_agent.evals import score_parser_dialogs
        from tpu_voice_agent.services.brain import RuleBasedParser

        scores = score_parser_dialogs(RuleBasedParser())
        assert scores["errors"] == 0
        # two finals are deliberately beyond the rule grammar ("open the
        # fourth link" — ordinals stop at third; the compound click+scroll)
        # — that headroom is exactly what the distilled model trains to
        # take (synth_intent_dialogs covers both families)
        assert scores["type_accuracy"] >= 0.6, scores
        assert scores["args_score"] >= 0.7, scores

    def test_parser_errors_count_as_misses(self):
        class Boom:
            def parse(self, text, context):
                raise RuntimeError("engine down")

        scores = score_parser(Boom(), GOLDEN_INTENT_CASES[:3])
        assert scores == {"cases": 3, "errors": 3,
                          "type_accuracy": 0.0, "args_score": 0.0}


class TestWER:
    def test_perfect(self):
        assert wer("open the pod bay doors", "Open the pod bay doors!") == 0.0

    def test_substitution_deletion_insertion(self):
        assert wer("a b c d", "a x c d") == pytest.approx(0.25)  # 1 sub
        assert wer("a b c d", "a c d") == pytest.approx(0.25)  # 1 del
        assert wer("a b c d", "a b q c d") == pytest.approx(0.25)  # 1 ins

    def test_empty_reference(self):
        assert wer("", "") == 0.0
        assert wer("", "something") == 1.0

    def test_normalization_strips_punctuation_and_case(self):
        assert normalize_words("Hello, World!  it's 5 o'clock") == [
            "hello", "world", "it's", "5", "o'clock"]

    def test_wer_over_dir_corpus_level(self, tmp_path):
        import wave

        for name, text in (("a", "one two three four"), ("b", "five six")):
            with wave.open(str(tmp_path / f"{name}.wav"), "wb") as w:
                w.setnchannels(1)
                w.setsampwidth(2)
                w.setframerate(16000)
                w.writeframes(np.zeros(1600, np.int16).tobytes())
            (tmp_path / f"{name}.txt").write_text(text)
        (tmp_path / "orphan.wav").touch()  # no transcript: ignored

        hyps = {"a": "one two three wrong", "b": "five six"}

        def transcribe(path):
            from pathlib import Path

            return hyps[Path(path).stem]

        out = wer_over_dir(transcribe, tmp_path)
        assert out["pairs"] == 2
        # corpus-level: 1 error / 6 reference words
        assert out["wer"] == pytest.approx(1 / 6)

    def test_wer_over_empty_dir(self, tmp_path):
        out = wer_over_dir(lambda p: "", tmp_path)
        assert out == {"pairs": 0, "wer": None}
