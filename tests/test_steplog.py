"""Engine microscope (ISSUE 9): step ledger, recompilation sentinel, HBM
ledger, and the tooling that rides them.

The executable spec for the device-plane telemetry: the StepTimer's tiling
contract (stages account ≥95% of a real scheduler chunk's wall), the ring's
bounds and flight-recorder freeze integration, cache-miss compile detection
with the warmup fence (an induced post-fence recompile must surface as a
counter + a steplog event + a /health warning within one scrape), the
ledger-on/off token-identity differential, plan-vs-measured HBM
reconciliation, and the stepview/benchdiff tools (stepview --self-test
joins tier-1 here, alongside traceview's in test_observability).
"""

import json
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from tpu_voice_agent.serve import ContinuousBatcher, DecodeEngine
from tpu_voice_agent.utils import get_compile_watcher, get_metrics
from tpu_voice_agent.utils.compilewatch import CompileWatcher, _shape_sig, watch_compiles
from tpu_voice_agent.utils.hbmledger import (
    engine_hbm_plan,
    hbm_report,
    measure_hbm,
    record_hbm_gauges,
)
from tpu_voice_agent.utils.steplog import STAGES, StepLog, get_steplog
from tpu_voice_agent.utils.tracing import FlightRecorder

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "tools"))

import benchdiff  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_global_telemetry():
    """Every test starts with an empty step ring and a disarmed, zeroed
    compile watcher — and leaves them that way (both are process-global;
    a leaked armed fence would tag other modules' compiles post-fence)."""
    get_steplog().clear()
    get_compile_watcher().reset()
    yield
    get_steplog().clear()
    get_compile_watcher().reset()


@pytest.fixture(scope="module")
def scope_engine():
    """Module-private engine with bucket/chunk shapes no other module uses,
    so its traces are cache-cold regardless of suite order (the sentinel
    counts jit-cache misses — a bucket another test already warmed would
    hide the induced compile)."""
    return DecodeEngine(preset="test-tiny", max_len=768, batch_slots=2,
                        prefill_buckets=(96, 192))


def _batcher(engine, **kw):
    kw.setdefault("chunk_steps", 7)
    kw.setdefault("max_new_tokens", 16)
    return ContinuousBatcher(engine, **kw)


# ------------------------------------------------------------ StepLog units


def test_steptimer_stages_tile_the_wall():
    import time

    log = StepLog(max_steps=8, enabled=True)
    t = log.timer()
    time.sleep(0.002)
    t.lap("admit")
    time.sleep(0.005)
    t.lap("decode")
    time.sleep(0.002)
    t.lap("readback")
    t.lap("release")
    rec = t.finish(occupancy=2, tokens=5)
    assert rec["occupancy"] == 2 and rec["tokens"] == 5
    assert set(rec["stages"]) <= set(STAGES)
    # laps are contiguous segments of one perf_counter stream: they tile
    # (each stage and the wall are rounded to 3 decimals independently, so
    # allow half-ulp rounding slack per recorded stage)
    slack = 5e-4 * (len(rec["stages"]) + 1)
    assert sum(rec["stages"].values()) <= rec["wall_ms"] + slack
    assert sum(rec["stages"].values()) >= 0.95 * rec["wall_ms"]


def test_steptimer_carve_moves_subtime_between_stages():
    log = StepLog(max_steps=8, enabled=True)
    t = log.timer()
    t.lap("admit")
    t.stages["admit"] = 10.0
    t.carve("admit", "prefill", 4.0)
    assert t.stages["admit"] == pytest.approx(6.0)
    assert t.stages["prefill"] == pytest.approx(4.0)
    # carving more than the source stage holds clamps (tiling preserved)
    t.carve("admit", "prefill", 100.0)
    assert t.stages["admit"] == 0.0
    assert t.stages["prefill"] == pytest.approx(10.0)


def test_steplog_ring_bounds_and_seq():
    log = StepLog(max_steps=4, enabled=True)
    for _ in range(10):
        log.timer().finish()
    dump = log.dump()
    assert len(dump["steps"]) == 4
    assert dump["recorded"] == 10
    assert [s["seq"] for s in dump["steps"]] == [6, 7, 8, 9]
    assert log.last()["seq"] == 9
    assert len(log.steps(last=2)) == 2


def test_steplog_disabled_records_nothing():
    log = StepLog(max_steps=4, enabled=False)
    log.timer().finish()
    assert log.dump()["steps"] == [] and log.last() is None


def test_flight_freeze_carries_the_step_ring():
    log = get_steplog()
    log.timer().finish(occupancy=1, tokens=3)
    fr = FlightRecorder(max_traces=4)
    assert fr.trigger("test.freeze", detail="steplog ride-along")
    dump = fr.frozen_dump()
    assert dump["reason"] == "test.freeze"
    assert dump["steplog"]["steps"], "freeze must embed the step ring"
    assert dump["steplog"]["steps"][-1]["tokens"] == 3


# ------------------------------------------------- compile sentinel units


def test_watch_compiles_counts_cache_misses_once():
    w = get_compile_watcher()

    @watch_compiles("test.unit_fn")
    @jax.jit
    def f(x):
        return x * 2

    f(jnp.zeros((3,), jnp.float32))  # trace 1
    f(jnp.ones((3,), jnp.float32))   # cache hit — same shape
    f(jnp.zeros((5,), jnp.float32))  # trace 2 — new shape
    st = w.state()
    assert st["compiles"] == 2
    evs = w.events()
    assert [e["site"] for e in evs] == ["test.unit_fn", "test.unit_fn"]
    assert "float32[5]" in evs[-1]["shape"]
    assert st["post_fence_compiles"] == 0 and "warning" not in st


def test_fence_flags_post_fence_compiles_with_warning():
    w = get_compile_watcher()

    @watch_compiles("test.fence_fn")
    @jax.jit
    def g(x):
        return x + 1

    g(jnp.zeros((2,), jnp.float32))
    w.arm_fence("test warm")
    g(jnp.zeros((4,), jnp.float32))  # the post-fence retrace
    st = w.state()
    assert st["fence_armed"] and st["fence_reason"] == "test warm"
    assert st["post_fence_compiles"] == 1
    assert "recompile(s) after the warmup fence" in st["warning"]
    assert "test.fence_fn" in st["warning"]
    # the pending list hands the event to the step ledger exactly once
    pend = w.take_pending()
    assert len(pend) == 2 and pend[-1]["post_fence"]
    assert w.take_pending() == []


def test_shape_sig_compact_and_capped():
    sig = _shape_sig((jnp.zeros((2, 3), jnp.int32), {"a": 1}, [1, 2], 7), {})
    assert "int32[2,3]" in sig and "dict(1)" in sig and "seq(2)" in sig
    many = _shape_sig(tuple(jnp.zeros((i + 1,)) for i in range(10)), {})
    assert many.endswith("…")


# --------------------------------------------- the real scheduler plane


def test_ledger_accounts_chunk_wall_and_occupancy(scope_engine):
    bat = _batcher(scope_engine)
    res = bat.generate_many(["turn on the lights", "play some jazz"])
    assert all(r.error is None for r in res)
    steps = [s for s in get_steplog().steps() if s.get("occupancy")]
    assert steps, "decode chunks must land in the ring"
    for s in steps:
        acct = sum(s["stages"].values()) / s["wall_ms"]
        assert acct >= 0.95, f"only {acct:.1%} of step {s['seq']} accounted"
        assert set(s["stages"]) <= set(STAGES)
    # the per-chunk meta the HUD and stepview render
    assert steps[0]["occupancy"] >= 1
    assert sum(s.get("tokens", 0) for s in steps) >= sum(
        len(r.token_ids) for r in res)
    # engine.step.* metrics exported alongside
    snap = get_metrics().snapshot()
    assert snap["latency_ms"]["engine.step.wall"]["count"] >= len(steps)
    assert "engine.step.occupancy" in snap["gauges"]


def test_induced_post_fence_recompile_surfaces_everywhere(scope_engine):
    """The acceptance drill: warm the 96-bucket, declare serving warm, then
    submit a prompt that forces the cold 192-bucket — the sentinel counter,
    the step ledger's compile event, and the brain's /health warning must
    all fire within one scrape."""
    w = get_compile_watcher()
    bat = _batcher(scope_engine)
    assert all(r.error is None
               for r in bat.generate_many(["turn on the lights"]))
    w.take_pending()
    get_steplog().clear()
    before = w.state()["compiles"]

    w.arm_fence("warmup complete")
    ids = scope_engine.tokenizer.encode("turn on the lights and play jazz",
                                        bos=True)
    long_ids = (ids * ((120 // len(ids)) + 1))[:120]  # 96 < n <= 192
    bat.submit(list(long_ids))
    bat.run_until_done()

    # (1) the counter
    st = w.state()
    assert st["compiles"] > before
    assert st["post_fence_compiles"] >= 1
    assert "warning" in st
    # (2) the steplog event, on the step that paid the trace
    evs = [ev for s in get_steplog().steps() for ev in (s.get("events") or [])]
    assert any(ev["post_fence"] and "prefill" in ev["site"] for ev in evs), evs
    # (3) the /health warning, one scrape
    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app

    import urllib.request

    with AppServer(build_app(RuleBasedParser())) as srv:
        with urllib.request.urlopen(srv.url + "/health", timeout=5) as r:
            body = json.loads(r.read().decode())
    cs = body["compile_sentinel"]
    assert cs["post_fence_compiles"] >= 1
    assert "recompile(s) after the warmup fence" in cs["warning"]
    assert body["last_step"]["stages"], "/health carries the last step"


def test_all_admissions_shed_still_records_a_step(scope_engine):
    """Overload churn — every dequeued admission sheds, nothing decodes —
    must still land in the ring: that admit/shed wall is exactly the time
    an overload autopsy needs accounted."""
    from tpu_voice_agent.utils.resilience import Deadline

    bat = _batcher(scope_engine)
    bat.submit("turn on the lights", deadline=Deadline(0.0))
    bat.step()
    assert bat.results, "expired request must shed at dequeue"
    steps = get_steplog().steps()
    assert steps, "the shed-only step must be recorded"
    assert steps[-1]["occupancy"] == 0 and steps[-1]["tokens"] == 0
    assert "admit" in steps[-1]["stages"]


def test_steplog_off_is_token_identical(scope_engine):
    log = get_steplog()
    bat_on = _batcher(scope_engine)
    on = bat_on.generate_many(["dim the bedroom lights", "what time is it"])
    log.enabled = False
    try:
        bat_off = _batcher(scope_engine)
        off = bat_off.generate_many(["dim the bedroom lights",
                                     "what time is it"])
    finally:
        log.enabled = True
    assert [r.token_ids for r in on] == [r.token_ids for r in off]
    assert all(r.error is None for r in on)


def test_warm_restart_rearms_the_fence(scope_engine):
    w = get_compile_watcher()
    assert not w.fence_armed
    scope_engine.warm_restart()
    assert w.fence_armed
    assert w.state()["fence_reason"] == "warm_restart"


# ------------------------------------------------------------ HBM ledger


def test_hbm_plan_matches_measured_weights_and_kv(scope_engine):
    plan = engine_hbm_plan(scope_engine)
    meas = measure_hbm(scope_engine)
    # the plan is config arithmetic, the measurement sums real nbytes —
    # they must agree on the parts both account (dense engine: exact)
    assert meas["weights_bytes"] == plan["weights_bytes"]
    assert meas["kv_pool_bytes"] == plan["kv_pool_bytes"]
    rep = hbm_report(scope_engine)
    assert abs(rep["drift"]) < 0.02
    assert rep["plan"]["total_bytes"] > 0


def test_hbm_gauges_exported_and_throttled(scope_engine):
    rep = record_hbm_gauges(scope_engine, force=True)
    assert rep is not None
    g = get_metrics().gauges()
    for name in ("hbm.weights_bytes", "hbm.kv_pool_bytes",
                 "hbm.plan_total_bytes", "hbm.plan_drift"):
        assert name in g, name
    assert g["hbm.weights_bytes"] == rep["measured"]["weights_bytes"]
    # throttle: an immediate second call inside the interval is a no-op
    assert record_hbm_gauges(scope_engine, min_interval_s=60.0) is None


# ------------------------------------------------------------ tools


def test_stepview_self_test_passes():
    proc = subprocess.run([sys.executable, str(ROOT / "tools" / "stepview.py"),
                           "--self-test"], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stepview self-test ok" in proc.stdout


def test_stepview_renders_real_ring(scope_engine, tmp_path):
    import stepview

    bat = _batcher(scope_engine)
    bat.generate_many(["turn on the lights"])
    body = get_steplog().dump()
    txt = stepview.render_timeline(body, width=32)
    assert "step ledger:" in txt and "█" in txt
    # flight-dump unwrap: stepview reads the frozen ``steplog`` section
    p = tmp_path / "flight.json"
    p.write_text(json.dumps({"frozen": True, "steplog": body}))
    assert stepview.load_dump(str(p))["recorded"] == body["recorded"]


def _runall_artifact(path, rows):
    path.write_text(json.dumps({
        "quick": True,
        "benches": {"bench_x.py": {"status": "ok", "rows": rows}},
    }))


def test_benchdiff_flags_directional_regressions(tmp_path):
    prev = tmp_path / "BENCH_runall_1.json"
    cur = tmp_path / "BENCH_runall_2.json"
    _runall_artifact(prev, [
        {"metric": "x_p50", "value": 100.0, "unit": "ms"},
        {"metric": "x_tps", "value": 50.0, "unit": "tokens/s"},
        {"metric": "x_count", "value": 3, "unit": "count"},
    ])
    _runall_artifact(cur, [
        {"metric": "x_p50", "value": 125.0, "unit": "ms"},        # +25% BAD
        {"metric": "x_tps", "value": 40.0, "unit": "tokens/s"},   # -20% BAD
        {"metric": "x_count", "value": 30, "unit": "count"},      # not gated
    ])
    regs, changes = benchdiff.diff_rows(benchdiff.load_rows(cur),
                                        benchdiff.load_rows(prev), 0.10)
    assert {r["metric"] for r in regs} == {"x_p50", "x_tps"}
    assert {c["metric"] for c in changes} == {"x_p50", "x_tps", "x_count"}
    # improvements are "moved", never regressions
    _runall_artifact(cur, [{"metric": "x_p50", "value": 50.0, "unit": "ms"}])
    regs, changes = benchdiff.diff_rows(benchdiff.load_rows(cur),
                                        benchdiff.load_rows(prev), 0.10)
    assert regs == [] and len(changes) == 1


def test_benchdiff_never_diffs_quick_against_full(tmp_path):
    """--quick runs trim workloads (capacity caps, token budgets): a quick
    artifact diffed against a full one reads as a huge phantom regression.
    pick_artifacts matches the table kind."""
    full_old = tmp_path / "BENCH_runall_20200101_000000.json"
    full_old.write_text(json.dumps({"benches": {}}))
    quick_old = tmp_path / "BENCH_runall_20200102_000000.json"
    quick_old.write_text(json.dumps({"quick": True, "benches": {}}))
    quick_new = tmp_path / "BENCH_runall_20200103_000000.json"
    quick_new.write_text(json.dumps({"quick": True, "benches": {}}))
    cur, prev = benchdiff.pick_artifacts(tmp_path)
    assert (cur, prev) == (quick_new, quick_old)
    # a full run skips the newer quick artifact back to the last full one
    full_new = tmp_path / "BENCH_runall_20200104_000000.json"
    full_new.write_text(json.dumps({"benches": {}}))
    cur, prev = benchdiff.pick_artifacts(tmp_path)
    assert (cur, prev) == (full_new, full_old)
    # no same-kind predecessor: the trajectory starts, nothing to gate
    quick_old.unlink()
    quick_new.unlink()
    full_old.unlink()
    assert benchdiff.pick_artifacts(tmp_path) == (full_new, None)


def test_benchdiff_gate_exit_codes(tmp_path):
    prev = tmp_path / "BENCH_runall_20200101_000000.json"
    cur = tmp_path / "BENCH_runall_20200102_000000.json"
    _runall_artifact(prev, [{"metric": "y_p50", "value": 100.0, "unit": "ms"}])
    _runall_artifact(cur, [{"metric": "y_p50", "value": 200.0, "unit": "ms"}])
    assert benchdiff.main(["--artifacts", str(tmp_path), "--gate"]) == 1
    # without --gate the diff reports but never fails the caller
    assert benchdiff.main(["--artifacts", str(tmp_path)]) == 0
    # tolerance raised past the move: clean
    assert benchdiff.main(["--artifacts", str(tmp_path), "--gate",
                           "--tolerance", "1.5"]) == 0
    # single artifact: the trajectory starts, no gate to fail
    cur.unlink()
    assert benchdiff.main(["--artifacts", str(tmp_path), "--gate"]) == 0


# ------------------------------------------------------------ services


def test_voice_health_forwards_brain_engine_microscope():
    from tests.http_helper import AppServer
    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.brain import RuleBasedParser
    from tpu_voice_agent.services.brain import build_app as build_brain
    from tpu_voice_agent.services.voice import VoiceConfig
    from tpu_voice_agent.services.voice import build_app as build_voice

    import urllib.request

    get_compile_watcher().arm_fence("test")
    get_steplog().timer().finish(occupancy=1, tokens=2)
    with AppServer(build_brain(RuleBasedParser())) as brain:
        cfg = VoiceConfig(brain_url=brain.url, executor_url="http://127.0.0.1:1",
                          stt_factory=lambda: NullSTT())
        with AppServer(build_voice(cfg)) as voice:
            with urllib.request.urlopen(voice.url + "/health", timeout=5) as r:
                body = json.loads(r.read().decode())
    fwd = body["brain"]
    assert fwd["compile_sentinel"]["fence_armed"]
    assert fwd["last_step"]["tokens"] == 2


def test_brain_debug_steplog_endpoint():
    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app

    import urllib.request

    log = get_steplog()
    for i in range(5):
        log.timer().finish(occupancy=i, tokens=i)
    with AppServer(build_app(RuleBasedParser())) as srv:
        with urllib.request.urlopen(srv.url + "/debug/steplog?last=2",
                                    timeout=5) as r:
            body = json.loads(r.read().decode())
    assert body["service"] == "brain"
    assert len(body["steps"]) == 2 and body["recorded"] == 5
    assert body["steps"][-1]["occupancy"] == 4
