"""Metrics/observability: runtime counters, gauges, /metrics endpoints.

SURVEY.md §5 rebuild notes: counters for tokens/sec and queue depth plus
per-request trace ids — none of which the reference has (its observability
is tagged console.log lines).
"""

import asyncio

import aiohttp

from tpu_voice_agent.utils import Metrics, get_metrics


def _get_json(url: str):
    async def run():
        async with aiohttp.ClientSession() as sess:
            async with sess.get(url) as r:
                return r.status, await r.json()

    return asyncio.run(run())


def test_metrics_counters_gauges_percentiles():
    m = Metrics()
    m.inc("a")
    m.inc("a", 2)
    m.set_gauge("depth", 7)
    for ms in (10, 20, 30, 40):
        m.observe_ms("lat", ms)
    snap = m.snapshot()
    assert snap["counters"]["a"] == 3
    assert snap["gauges"]["depth"] == 7.0
    assert snap["latency_ms"]["lat"]["count"] == 4
    assert 10 <= snap["latency_ms"]["lat"]["p50"] <= 40


def test_engine_generate_records_runtime_metrics(tiny_engine):
    before = get_metrics().snapshot()["counters"].get("engine.tokens_generated", 0)
    res = tiny_engine.generate("<|user|>\nscroll down\n<|assistant|>\n", max_new_tokens=16)
    after = get_metrics().snapshot()["counters"]
    assert after["engine.tokens_generated"] >= before + res.steps
    assert after["engine.requests"] >= 1


def test_interpreter_records_intent_counters(tmp_path):
    from tpu_voice_agent.schemas import Intent
    from tpu_voice_agent.services.executor.actions import run_intents
    from tpu_voice_agent.services.executor.page import FakePage

    before = get_metrics().snapshot()["counters"]
    run_intents(FakePage.demo(), tmp_path,
                [Intent(type="scroll", args={"direction": "down"})],
                screenshot_each_step=False)
    after = get_metrics().snapshot()["counters"]
    assert after["executor.intents_executed"] >= before.get("executor.intents_executed", 0) + 1
    assert after.get("executor.intents.scroll", 0) >= 1


def test_services_expose_metrics_endpoint():
    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.brain import RuleBasedParser, build_app as build_brain
    from tpu_voice_agent.services.executor import build_app as build_executor
    from tpu_voice_agent.services.voice import VoiceConfig, build_app as build_voice
    from tests.http_helper import AppServer

    apps = [
        ("brain", build_brain(RuleBasedParser())),
        ("executor", build_executor()),
        ("voice", build_voice(VoiceConfig(stt_factory=NullSTT))),
    ]
    for name, app in apps:
        with AppServer(app) as srv:
            status, body = _get_json(srv.url + "/metrics")
            assert status == 200
            assert body["service"] == name
            assert "counters" in body["local"] and "counters" in body["runtime"]


def test_perfdiag_audit_flags_materialized_dequant():
    """The audit must catch a materialized dequant in BOTH places it can
    actually appear in the optimized decode HLO — a bare convert inside the
    lax.scan-lowered while BODY (not ENTRY), and an ENTRY-level pure-dequant
    fusion — while ignoring properly-fused dequants (fusion body containing
    the consuming dot) and small ops."""
    from tpu_voice_agent.utils.perfdiag import audit_dequant

    hlo = """\
HloModule jit_forward

%fused_dequant.1 (p0: s8[2048,5632]) -> bf16[2048,5632] {
  %p0 = s8[2048,5632]{1,0} parameter(0)
  ROOT %c = bf16[2048,5632]{1,0} convert(%p0)
}

%fused_scale.4 (p0: bf16[4096,4096], p1: bf16[1,4096]) -> bf16[4096,4096] {
  %p0 = bf16[4096,4096]{1,0} parameter(0)
  %p1 = bf16[1,4096]{1,0} parameter(1)
  %bc = bf16[4096,4096]{1,0} broadcast(%p1)
  ROOT %m = bf16[4096,4096]{1,0} multiply(%p0, %bc)
}

%fused_matmul.2 (p0: s8[2048,5632], p1: bf16[1,2048]) -> bf16[1,5632] {
  %p0 = s8[2048,5632]{1,0} parameter(0)
  %p1 = bf16[1,2048]{1,0} parameter(1)
  %c = bf16[2048,5632]{1,0} convert(%p0)
  ROOT %mm = bf16[1,5632]{1,0} dot(%p1, %c)
}

%while_body.3 (carry: bf16[1,2048]) -> bf16[1,2048] {
  %carry = bf16[1,2048]{1,0} parameter(0)
  %w = s8[2048,2048]{1,0} constant(0)
  %dq2 = bf16[2048,2048]{1,0} convert(%w)
  ROOT %mm2 = bf16[1,2048]{1,0} dot(%carry, %dq2)
}

ENTRY %main (a: s8[2048,5632], b: bf16[1,2048]) -> bf16[1,5632] {
  %a = s8[2048,5632]{1,0} parameter(0)
  %b = bf16[1,2048]{1,0} parameter(1)
  %dqf = bf16[2048,5632]{1,0} fusion(%a), kind=kLoop, calls=%fused_dequant.1
  %w2 = bf16[4096,4096]{1,0} constant(0)
  %s2 = bf16[1,4096]{1,0} constant(0)
  %scf = bf16[4096,4096]{1,0} fusion(%w2, %s2), kind=kLoop, calls=%fused_scale.4
  %small = bf16[1,2048]{1,0} multiply(%b, %b)
  %loop = bf16[1,2048]{1,0} while(%small), body=%while_body.3
  ROOT %mm = bf16[1,5632]{1,0} fusion(%a, %loop), kind=kOutput, calls=%fused_matmul.2
}
"""
    audit = audit_dequant(hlo, min_bytes=1 << 20)
    got = {(op, shape) for op, dtype, shape, mb, comp in audit["findings"]}
    # the while-body bare convert, the ENTRY pure-dequant (convert) fusion,
    # AND the multiply-only scale fusion (convert constant-folded away)
    assert ("convert", (2048, 2048)) in got
    assert ("fusion:dequant", (2048, 5632)) in got
    assert ("fusion:dequant", (4096, 4096)) in got
    # the matmul-containing fusion and the small multiply were NOT flagged
    assert len(audit["findings"]) == 3
    assert audit["scanned_instructions"] >= 6


def test_perfdiag_audit_scale_in_dot_and_tuple_fusions():
    """Round-5 on-chip regression: (a) tuple-rooted fusion instructions
    (``= (f32[..], f32[..]) fusion(...)``) don't parse as instructions, so
    their bodies must still be excluded from the materialized scan (the
    ``calls=`` collection is text-wide); (b) a B=1 matvec lowered as a
    kLoop broadcast-multiply-reduce owns one weight-sized multiply per
    reduce — the dot itself, clean — while an EXTRA weight-sized multiply
    in the same body is a fused dequant scale (~2 surplus VPU ops per
    weight; held decode at 1.69 vs the 1.18 ms/token floor until
    models.llama._qe moved the scale to the dot output)."""
    from tpu_voice_agent.utils.perfdiag import audit_dequant

    clean = """\
HloModule jit_forward

%fused_dot.1 (p0: f32[2048], p1: s8[2048,5632]) -> (f32[5632], f32[5632]) {
  %p0 = f32[2048]{0} parameter(0)
  %bc = f32[2048,5632]{1,0} broadcast(%p0), dimensions={0}
  %p1 = s8[2048,5632]{1,0} parameter(1)
  %cv = f32[2048,5632]{1,0} convert(%p1)
  %m1 = f32[2048,5632]{1,0} multiply(%bc, %cv)
  %r1 = f32[5632]{0} reduce(%m1), dimensions={0}
  %m2 = f32[2048,5632]{1,0} multiply(%bc, %cv)
  %r2 = f32[5632]{0} reduce(%m2), dimensions={0}
  ROOT %t = (f32[5632]{0}, f32[5632]{0}) tuple(%r1, %r2)
}

ENTRY %main (a: f32[2048], b: s8[2048,5632]) -> (f32[5632], f32[5632]) {
  %a = f32[2048]{0} parameter(0)
  %b = s8[2048,5632]{1,0} parameter(1)
  ROOT %f = (f32[5632]{0}, f32[5632]{0}) fusion(%a, %b), kind=kLoop, calls=%fused_dot.1
}
"""
    audit = audit_dequant(clean, min_bytes=1 << 20)
    assert audit["findings"] == []  # the dot's own multiplies are not dequant

    scaled = clean.replace(
        "  %m1 = f32[2048,5632]{1,0} multiply(%bc, %cv)",
        "  %sc = f32[2048,5632]{1,0} multiply(%cv, %cv)\n"
        "  %m1 = f32[2048,5632]{1,0} multiply(%bc, %sc)")
    audit = audit_dequant(scaled, min_bytes=1 << 20)
    assert [f[0] for f in audit["findings"]] == ["fusion:scale-in-dot"]

    # an unrelated SMALL reduce fused into the same body must not mask the
    # scale multiply (operand tracking, not op counting, pairs dots with
    # their multiplies)
    masked = scaled.replace(
        "  ROOT %t = (f32[5632]{0}, f32[5632]{0}) tuple(%r1, %r2)",
        "  %p0s = f32[2048]{0} multiply(%p0, %p0)\n"
        "  %rs = f32[]{} reduce(%p0s)\n"
        "  ROOT %t = (f32[5632]{0}, f32[5632]{0}) tuple(%r1, %r2)")
    audit = audit_dequant(masked, min_bytes=1 << 20)
    assert [f[0] for f in audit["findings"]] == ["fusion:scale-in-dot"]

    # a pure-dequant fusion with a TUPLE root (no reduce/dot) materializes
    # weight-sized buffers even though the ROOT line itself never parses —
    # its operands must be resolved against the body's big converts
    tuple_dequant = """\
HloModule m

%fused_dq.1 (p0: s8[2048,5632]) -> (bf16[2048,5632], bf16[2048,5632]) {
  %p0 = s8[2048,5632]{1,0} parameter(0)
  %cv = bf16[2048,5632]{1,0} convert(%p0)
  ROOT %t = (bf16[2048,5632]{1,0}, bf16[2048,5632]{1,0}) tuple(%cv, %cv)
}

ENTRY %main (a: s8[2048,5632]) -> (bf16[2048,5632], bf16[2048,5632]) {
  %a = s8[2048,5632]{1,0} parameter(0)
  ROOT %f = (bf16[2048,5632]{1,0}, bf16[2048,5632]{1,0}) fusion(%a), kind=kLoop, calls=%fused_dq.1
}
"""
    audit = audit_dequant(tuple_dequant, min_bytes=1 << 20)
    assert [f[0] for f in audit["findings"]] == ["fusion:dequant"]


def test_perfdiag_decode_step_hlo_lowers_int8_engine():
    """decode_step_hlo must lower/compile the real engine's decode forward
    (int8 path included) and return parseable HLO text."""
    from tpu_voice_agent.serve import DecodeEngine
    from tpu_voice_agent.utils.perfdiag import audit_dequant, decode_step_hlo

    eng = DecodeEngine(preset="test-tiny", max_len=256, prefill_buckets=(64,),
                       quant="int8")
    hlo = decode_step_hlo(eng)
    assert "ENTRY" in hlo
    audit = audit_dequant(hlo, min_bytes=1 << 30)  # sanity: parses, no 1GB tensors
    assert audit["scanned_instructions"] > 0
    assert audit["findings"] == []
