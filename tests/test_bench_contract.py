"""The driver contract: ``python bench.py`` must ALWAYS land one parseable
JSON row on stdout (round-2 recorded nothing because the process died;
round-3's row only existed thanks to the CPU re-exec watchdog). This test
runs the real bench as a subprocess the way the driver does and pins the
row's schema, so a bench regression fails CI instead of a round capture."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.slow
def test_bench_emits_one_parseable_row():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # never touch the (flaky) tunnel from CI
    # reuse the suite's compile cache (bench.py doesn't set one itself) so
    # warm runs of this check cost minutes less
    env.setdefault("JAX_COMPILATION_CACHE_DIR", str(ROOT / ".jax_cache"))
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    proc = subprocess.run(
        [sys.executable, str(ROOT / "bench.py")], cwd=ROOT, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be exactly ONE JSON row: {lines}"
    row = json.loads(lines[0])
    assert row["metric"] == "voice_to_intent_p50_e2e"
    assert row["unit"] == "ms"
    assert row["value"] > 0
    assert row["vs_baseline"] > 0
    assert row["backend"] in ("cpu", "tpu")
    assert 0.0 <= row["spec_hit_rate"] <= 1.0
    # the stderr narrative carries the breakdown the JSON can't
    assert "e2e p50" in proc.stderr


@pytest.mark.slow
def test_benches_common_never_hangs_unpinned(tmp_path):
    """VERDICT round-4 weak #1: ``benches/run_all.py --quick`` hung >9.5 min
    for the judge because benches/common.py only honored an explicit CPU
    pin. Now importing common routes the first jax.devices() through the
    same watchdog as bench.py; this runs a minimal bench UNPINNED (the
    judge's exact failure mode) with a short watchdog and asserts it
    completes — either the tunnel answered, or the re-exec landed on CPU."""
    script = tmp_path / "minibench.py"
    script.write_text(
        "import sys\n"
        f"sys.path.insert(0, {str(ROOT)!r})\n"
        "from benches.common import emit, on_tpu\n"
        "emit('watchdog_probe', 1.0, 'ok')\n"
        "print('ON_TPU', on_tpu())\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # unpinned: the judge's failure mode
    # an ambient fail-instead-of-fallback pin would make the re-exec path
    # exit 7 by design; this test asserts the fallback path specifically
    env.pop("BENCH_NO_CPU_FALLBACK", None)
    env["BENCH_INIT_TIMEOUT_S"] = "15"
    proc = subprocess.run(
        [sys.executable, str(script)], cwd=ROOT, env=env,
        capture_output=True, text=True,
        timeout=180,  # the old behavior hangs forever; timeout => FAIL
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert '"metric": "watchdog_probe"' in proc.stdout
    assert "ON_TPU" in proc.stdout
