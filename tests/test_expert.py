"""Expert parallelism: MoE routing semantics + EP shard_map equivalence.

Completes the SURVEY.md §2 parallelism audit (EP row). 8 virtual CPU
devices per the seam strategy (§4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.parallel.expert import (
    MoEConfig,
    _route,
    ep_mesh,
    init_moe_params,
    moe_ffn,
    moe_ffn_ep,
    moe_param_shardings,
)

CFG = MoEConfig(dim=32, ffn_dim=64, n_experts=8, top_k=2, capacity_factor=1.25)


@pytest.fixture(scope="module")
def params():
    return init_moe_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _x(T=24, seed=1):
    return jnp.asarray(np.random.default_rng(seed).standard_normal((T, CFG.dim)), jnp.float32)


def test_routing_gates_renormalize(params):
    x = _x()
    dispatch, combine = _route(params["router"], x, CFG, x.shape[0])
    T, E, C = combine.shape
    assert (E, C) == (CFG.n_experts, CFG.capacity(T))
    # each token occupies at most top_k slots, one per chosen expert
    occ = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert (occ <= CFG.top_k + 1e-6).all()
    # combine weights of non-dropped tokens sum to 1
    w = np.asarray(jnp.sum(combine, axis=(1, 2)))
    kept = occ > 0
    np.testing.assert_allclose(w[kept], 1.0, atol=1e-5)
    # no expert slot double-booked
    slot_use = np.asarray(jnp.sum(dispatch, axis=0))  # (E, C)
    assert (slot_use <= 1 + 1e-6).all()


def test_capacity_drops_overflow():
    tight = MoEConfig(dim=32, ffn_dim=64, n_experts=2, top_k=1, capacity_factor=0.5)
    p = init_moe_params(tight, jax.random.PRNGKey(2), dtype=jnp.float32)
    x = _x(T=16, seed=3)
    dispatch, _ = _route(p["router"], x, tight, 16)
    per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
    assert (per_expert <= tight.capacity(16)).all()
    assert np.asarray(jnp.sum(dispatch)) < 16  # something actually overflowed


def test_moe_output_is_finite_and_shaped(params):
    y = moe_ffn(params, CFG, _x())
    assert y.shape == (24, CFG.dim)
    assert bool(jnp.isfinite(y).all())


def test_ep_matches_dense_reference(params):
    """Expert-sharded shard_map execution must match the single-device
    reference bit-for-bit up to reduction order."""
    mesh = ep_mesh(8)
    sharded = jax.device_put(params, moe_param_shardings(mesh))
    x = _x(T=40, seed=5)
    ref = moe_ffn(params, CFG, x)
    ep = moe_ffn_ep(sharded, CFG, x, mesh)
    np.testing.assert_allclose(np.asarray(ep), np.asarray(ref), atol=1e-5, rtol=1e-4)


def test_ep_mesh_size_validation(params):
    mesh = ep_mesh(4)  # 8 experts / 4 devices = 2 local experts — fine
    x = _x(T=12, seed=7)
    ref = moe_ffn(params, CFG, x)
    out = moe_ffn_ep(jax.device_put(params, moe_param_shardings(mesh)), CFG, x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-4)

    bad = MoEConfig(dim=32, ffn_dim=64, n_experts=6, top_k=2)
    with pytest.raises(ValueError):
        moe_ffn_ep(params, bad, x, ep_mesh(4))
