"""Replicated brain tier (ISSUE 10): session-affine router over N replicas.

Fast-tier coverage for tpu_voice_agent/services/router.py against
lightweight in-process replica apps (plus the real brain/voice services
where the contract crosses them):

- rendezvous session affinity + spread across the ring
- health-probed ejection and in-budget failover retry (re-home accounting)
- graceful drain: new sessions never placed on a draining replica,
  in-flight completes, existing sessions re-home after the eject —
  zero dropped requests
- full outage -> 503 + Retry-After (the shed the voice service maps to
  the RuleBasedParser degraded mode)
- hedged parses: second attempt for slow idempotent parses, first wins
- the race hammer: concurrent submits vs. a racing kill + drain — no
  request lost, none double-SERVED outside a failover retry, none of the
  post-drain new sessions routed to the draining replica
- voice /health forwarding of the router's aggregated replicas shape
- the satellite-6 bugfix e2e: a replica ejected while a session's
  speculative parse is in flight must not poison the final — the final
  re-routes to the new home and the stale spec result is discarded,
  through the real WS path
"""

import asyncio
import json
import threading
import time
import urllib.error
import urllib.request

import pytest
from aiohttp import web

from tests.http_helper import AppServer
from tpu_voice_agent.services.brain import RuleBasedParser
from tpu_voice_agent.services.brain import build_app as build_brain
from tpu_voice_agent.services.router import BrainRouter, _weight
from tpu_voice_agent.services.router import build_app as build_router
from tpu_voice_agent.utils import get_metrics


def _counters() -> dict:
    return get_metrics().snapshot()["counters"]


def _post(url: str, body: dict, timeout: float = 20.0):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read().decode())


def _get(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _fake_replica(name: str, log: list, *, session_aware: bool = False,
                  delay_s: float = 0.0, controls: dict | None = None):
    """Minimal brain-contract stand-in: /parse answers the rule parser's
    plan and logs (name, session_id, speculative, nonce); ``controls``
    flips it dead (abrupt transport close on EVERY request, probes
    included — a crashed process) or slow at runtime."""
    rule = RuleBasedParser()
    controls = controls if controls is not None else {}

    def _drop(request: web.Request):
        if request.transport is not None:
            request.transport.close()
        raise asyncio.CancelledError("fake replica killed")

    async def parse(req: web.Request) -> web.Response:
        if controls.get("dead"):
            _drop(req)
        if controls.get("shed"):
            return web.json_response({"error": "overloaded"}, status=503,
                                     headers={"Retry-After": "1"})
        body = await req.json()
        # log BEFORE the delay so a test can observe an in-flight request
        # and kill the replica while it is still being "decoded"
        log.append((name, body.get("session_id"),
                    bool(body.get("speculative")),
                    (body.get("context") or {}).get("nonce")))
        d = controls.get("delay_s", delay_s)
        if d:
            await asyncio.sleep(d)
        if controls.get("dead"):
            _drop(req)  # killed mid-decode: the response never escapes
        resp = rule.parse(body["text"], body.get("context") or {})
        headers = {}
        if session_aware and body.get("speculative"):
            headers["x-speculation-pending"] = "1"
        return web.json_response(json.loads(resp.model_dump_json()),
                                 headers=headers)

    async def health(req: web.Request) -> web.Response:
        if controls.get("dead"):
            _drop(req)
        body = {"ok": True, "service": "brain"}
        if controls.get("draining"):
            body["draining"] = True
            body["drained"] = True
        if "pressure" in controls:
            body["pressure"] = {"score": controls["pressure"]}
        return web.json_response(body)

    async def admin_drain(req: web.Request) -> web.Response:
        # the real brain's serve-layer latch: sticky until the "restart"
        # (a test popping controls["draining"])
        controls["draining"] = True
        return web.json_response({"ok": True, "draining": True,
                                  "drained": True})

    async def handoff_get(req: web.Request) -> web.Response:
        # the warm-state export surface: controls["warm"] maps session id
        # -> blob bytes (a real brain serializes transcript + KV here)
        blob = (controls.get("warm") or {}).get(req.match_info["session_id"])
        if blob is None:
            return web.json_response({"error": "no_warm_state"}, status=404)
        return web.Response(body=blob,
                            content_type="application/octet-stream")

    async def handoff_post(req: web.Request) -> web.Response:
        blob = await req.read()
        controls.setdefault("adopted", []).append(blob)
        return web.json_response({"ok": True, "adopted_tokens": 7})

    app = web.Application()
    app.router.add_post("/parse", parse)
    app.router.add_get("/health", health)
    app.router.add_post("/admin/drain", admin_drain)
    app.router.add_get("/admin/handoff/{session_id}", handoff_get)
    app.router.add_post("/admin/handoff", handoff_post)
    return app


def _ring(n: int, *, session_aware: bool = False, delays=None, **router_kw):
    """n fake replicas + a router; returns (router_server, replica_servers,
    logs, controls, router_obj)."""
    logs = [[] for _ in range(n)]
    controls = [{} for _ in range(n)]
    servers = [AppServer(_fake_replica(f"r{i}", logs[i],
                                       session_aware=session_aware,
                                       delay_s=(delays or [0] * n)[i],
                                       controls=controls[i])).__enter__()
               for i in range(n)]
    router_kw.setdefault("probe_s", 0.15)
    router_kw.setdefault("probe_fails", 2)
    router_obj = BrainRouter([s.url for s in servers], **router_kw)
    router = AppServer(build_router(router_obj)).__enter__()
    return router, servers, logs, controls, router_obj


def _teardown(router, servers):
    router.__exit__(None, None, None)
    for s in servers:
        try:
            s.__exit__(None, None, None)
        except Exception:
            pass


def _sid_homed_on(router_obj: BrainRouter, idx: int, prefix: str) -> str:
    """A session id whose rendezvous home is replica ``idx``."""
    urls = [r.url for r in router_obj.replicas]
    for i in range(10_000):
        sid = f"{prefix}{i}"
        if max(range(len(urls)),
               key=lambda j: _weight(urls[j], sid)) == idx:
            return sid
    raise AssertionError("no session hashed onto the target replica")


# ----------------------------------------------------------- affinity


def test_session_affinity_and_spread():
    router, servers, logs, _, robj = _ring(3)
    try:
        # one session always lands on one replica
        for _ in range(4):
            st, hdrs, _b = _post(router.url + "/parse",
                                 {"text": "scroll down", "session_id": "aff",
                                  "context": {}})
            assert st == 200
        served = {e[0] for log in logs for e in log if e[1] == "aff"}
        assert len(served) == 1
        # many sessions spread over the ring (rendezvous, not one hot spot)
        for i in range(24):
            _post(router.url + "/parse",
                  {"text": "go back", "session_id": f"spread{i}",
                   "context": {}})
        used = {e[0] for log in logs for e in log}
        assert len(used) == 3
    finally:
        _teardown(router, servers)


# ----------------------------------------------------------- failover


def test_failover_retries_in_flight_and_rehomes():
    """The home dies mid-stream: the in-flight parse is retried once on
    the session's next-highest-weight replica inside the original budget,
    and the move counts router.sessions_rehomed."""
    router, servers, logs, controls, robj = _ring(2)
    try:
        sid = _sid_homed_on(robj, 0, "fo")
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "scroll down", "session_id": sid,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[0].url
        rehomed0 = _counters().get("router.sessions_rehomed", 0)
        retries0 = _counters().get("router.retries", 0)
        controls[0]["dead"] = True  # crash: every request drops abruptly
        st, hdrs, body = _post(router.url + "/parse",
                               {"text": "scroll down", "session_id": sid,
                                "context": {}})
        assert st == 200
        assert hdrs["x-router-replica"] == robj.replicas[1].url
        assert body["intents"][0]["type"] == "scroll"
        c = _counters()
        assert c.get("router.retries", 0) == retries0 + 1
        assert c.get("router.sessions_rehomed", 0) == rehomed0 + 1
        # and the session STAYS on its new home (sticky residence)
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "go back", "session_id": sid,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[1].url
    finally:
        _teardown(router, servers)


def test_probe_ejects_dead_replica_and_recovery_rejoins():
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1)
    try:
        controls[0]["dead"] = True
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "down":
            assert time.monotonic() < deadline, "prober never ejected"
            time.sleep(0.05)
        h = _get(router.url + "/health")
        assert h["replicas"] == {"total": 2, "healthy": 1, "draining": 0,
                                 "gray": 0}
        assert h["status"] == "degraded"
        # recovery: probes succeed again -> the replica rejoins the ring
        controls[0]["dead"] = False
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "up":
            assert time.monotonic() < deadline, "recovered replica never rejoined"
            time.sleep(0.05)
        assert _get(router.url + "/health")["status"] == "ok"
    finally:
        _teardown(router, servers)


# -------------------------------------------------------------- drain


def test_drain_is_zero_drop():
    """Drain a replica while one of its sessions has a parse in flight:
    the in-flight request completes (zero drop), new sessions avoid the
    draining replica immediately, and once in-flight hits zero the
    replica is ejected and its sessions re-home."""
    router, servers, logs, controls, robj = _ring(2)
    try:
        sid = _sid_homed_on(robj, 0, "dr")
        _post(router.url + "/parse", {"text": "go back", "session_id": sid,
                                      "context": {}})
        controls[0]["delay_s"] = 0.6  # the in-flight straggler
        results = {}

        def straggler():
            results["straggler"] = _post(
                router.url + "/parse",
                {"text": "scroll down", "session_id": sid, "context": {}})

        t = threading.Thread(target=straggler)
        t.start()
        time.sleep(0.2)  # request is in flight on replica 0
        st, _h, ack = _post(router.url + "/admin/drain",
                            {"replica": robj.replicas[0].url})
        assert ack["state"] == "draining"  # in-flight pending: NOT ejected
        # new sessions placed while draining must all avoid replica 0
        for i in range(8):
            st, hdrs, _b = _post(router.url + "/parse",
                                 {"text": "go back",
                                  "session_id": f"post-drain-{i}",
                                  "context": {}})
            assert hdrs["x-router-replica"] == robj.replicas[1].url
        t.join(timeout=10)
        st, hdrs, body = results["straggler"]
        assert st == 200 and hdrs["x-router-replica"] == robj.replicas[0].url
        # in-flight done -> ejected; the session re-homes on its next turn
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "drained":
            assert time.monotonic() < deadline, "drain never completed"
            time.sleep(0.05)
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "go back", "session_id": sid,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[1].url
        assert _counters().get("router.drains", 0) >= 1
    finally:
        _teardown(router, servers)


def test_drained_replica_rejoins_after_fast_restart():
    """A rolling restart faster than probe_fails consecutive probe windows
    never reads 'down' — the rejoin evidence is the serve-layer drain
    latch (seen by probes while drained) disappearing from /health, which
    only a fresh process does. Until it clears, the replica stays drained
    (a latch-less replica must hold router-side drain forever)."""
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1)
    try:
        _post(router.url + "/admin/drain", {"replica": robj.replicas[0].url})
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "drained":
            assert time.monotonic() < deadline, "drain never completed"
            time.sleep(0.05)
        # probes keep seeing the OLD process's latch: never rejoins
        time.sleep(0.35)
        assert robj.replicas[0].state == "drained"
        assert robj.replicas[0].drain_latched
        # the restart: a fresh process no longer reports the latch
        controls[0].pop("draining", None)
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "up":
            assert time.monotonic() < deadline, "drained replica never rejoined"
            time.sleep(0.05)
        assert _counters().get("router.replicas_recovered", 0) >= 1
        # and new sessions flow there again by rendezvous weight
        sid = _sid_homed_on(robj, 0, "rr")
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "go back", "session_id": sid,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[0].url
    finally:
        _teardown(router, servers)


def test_full_outage_sheds_503_with_retry_after():
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1,
                                                  probe_fails=1)
    try:
        controls[0]["dead"] = controls[1]["dead"] = True
        deadline = time.monotonic() + 5
        while any(r.state != "down" for r in robj.replicas):
            assert time.monotonic() < deadline
            time.sleep(0.05)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(router.url + "/parse",
                  {"text": "x", "session_id": "s", "context": {}})
        assert exc.value.code == 503
        assert exc.value.headers.get("Retry-After") is not None
        body = json.loads(exc.value.read().decode())
        assert body["error"] == "overloaded"  # the shed contract voice maps
        with pytest.raises(urllib.error.HTTPError) as hexc:
            _get(router.url + "/health")
        assert hexc.value.code == 503
    finally:
        _teardown(router, servers)


# ------------------------------------------------------------- hedging


def test_hedged_parse_first_wins_and_counts():
    """An idempotent (speculative) parse on a slow home is hedged to the
    next-best replica after ROUTER_HEDGE_MS; the fast answer wins."""
    router, servers, logs, controls, robj = _ring(2, hedge_ms=80)
    try:
        sid = _sid_homed_on(robj, 0, "he")
        controls[0]["delay_s"] = 1.0
        fired0 = _counters().get("router.hedges_fired", 0)
        won0 = _counters().get("router.hedges_won", 0)
        t0 = time.monotonic()
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "scroll down", "session_id": sid,
                              "context": {}, "speculative": True})
        dt = time.monotonic() - t0
        assert st == 200
        assert hdrs["x-router-replica"] == robj.replicas[1].url
        assert dt < 0.9  # did not wait out the slow home
        c = _counters()
        assert c.get("router.hedges_fired", 0) == fired0 + 1
        assert c.get("router.hedges_won", 0) == won0 + 1
        # the hedge never re-homed the session: the next (non-hedged,
        # session-committing) parse still goes to the slow home
        controls[0]["delay_s"] = 0.0
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "go back", "session_id": sid,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[0].url
    finally:
        _teardown(router, servers)


def test_hedge_error_answer_does_not_beat_running_primary():
    """The hedge replica shedding an instant 503 must not win the race
    over the slow-but-healthy home: first USABLE answer wins, and an
    error answer is only returned once no attempt is still running."""
    router, servers, logs, controls, robj = _ring(2, hedge_ms=50)
    try:
        sid = _sid_homed_on(robj, 0, "hshed")
        controls[0]["delay_s"] = 0.4  # slow enough to fire the hedge
        controls[1]["shed"] = True    # the alt sheds instantly
        fired0 = _counters().get("router.hedges_fired", 0)
        won0 = _counters().get("router.hedges_won", 0)
        st, hdrs, body = _post(router.url + "/parse",
                               {"text": "scroll down", "session_id": sid,
                                "context": {}, "speculative": True})
        assert st == 200
        assert hdrs["x-router-replica"] == robj.replicas[0].url
        assert body["intents"][0]["type"] == "scroll"
        c = _counters()
        assert c.get("router.hedges_fired", 0) == fired0 + 1
        assert c.get("router.hedges_won", 0) == won0  # the 503 never won
    finally:
        _teardown(router, servers)


# ---------------------------------------------------------- race hammer


def test_router_races_submit_vs_eject_and_drain():
    """Concurrent submits race a replica kill AND a drain: no request is
    lost (every one answers 200), no request is double-SERVED outside a
    failover retry (a nonce appears at most twice, and only when its
    first serving replica was the killed/drained one), and no post-drain
    NEW session ever lands on the draining replica."""
    router, servers, logs, controls, robj = _ring(3, probe_s=0.1,
                                                  parse_timeout_s=15.0)
    try:
        n_threads, per_thread = 6, 8
        barrier = threading.Barrier(n_threads + 1)
        errors: list = []
        statuses: list = []
        lock = threading.Lock()
        drain_acked = threading.Event()

        def worker(t: int):
            try:
                barrier.wait(timeout=30)
                for i in range(per_thread):
                    nonce = f"{t}-{i}"
                    phase = "post" if drain_acked.is_set() else "pre"
                    st, hdrs, _b = _post(
                        router.url + "/parse",
                        {"text": "scroll down",
                         "session_id": f"{phase}-hammer-{nonce}",
                         "context": {"nonce": nonce}}, timeout=30)
                    with lock:
                        statuses.append(st)
            except Exception as e:  # pragma: no cover - failure reporting
                with lock:
                    errors.append(e)

        def chaos_monkey():
            barrier.wait(timeout=30)
            time.sleep(0.15)
            controls[0]["dead"] = True  # kill r0 mid-hammer
            time.sleep(0.1)
            _post(router.url + "/admin/drain",
                  {"replica": robj.replicas[1].url})  # drain r1 mid-hammer
            drain_acked.set()

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        monkey = threading.Thread(target=chaos_monkey)
        for th in threads + [monkey]:
            th.start()
        for th in threads + [monkey]:
            th.join(timeout=60)
            assert not th.is_alive(), "hammer worker hung"
        assert not errors, f"hammer worker raised: {errors[0]!r}"
        # no request lost: every submit answered 200 (failover is a retry,
        # never an error, while at least one replica is up)
        assert len(statuses) == n_threads * per_thread
        assert all(st == 200 for st in statuses)
        # double-send audit: a nonce served twice must have been a
        # failover retry off the killed/drained replica, never a
        # same-replica repeat or a healthy-replica duplicate
        by_nonce: dict = {}
        for ri, log in enumerate(logs):
            for name, sid, spec, nonce in log:
                by_nonce.setdefault(nonce, []).append(ri)
        suspect = {robj.replicas[0].url, robj.replicas[1].url}
        for nonce, where in by_nonce.items():
            assert len(where) <= 2, f"nonce {nonce} sent {len(where)} times"
            if len(where) == 2:
                assert robj.replicas[where[0]].url in suspect, \
                    f"nonce {nonce} duplicated off a healthy replica"
                assert where[0] != where[1], \
                    f"nonce {nonce} re-sent to the same replica"
        # drain containment: NEW sessions placed after the drain ack never
        # landed on the draining replica
        post_drain_on_r1 = [e for e in logs[1]
                            if (e[1] or "").startswith("post-hammer-")]
        assert not post_drain_on_r1, post_drain_on_r1
    finally:
        _teardown(router, servers)


# ------------------------------------------ warm-state handoff (ISSUE 13)


def test_drain_rehome_ships_warm_state_and_counts_warm():
    """A drained home is still alive: the re-home ships the session's warm
    state (GET old /admin/handoff/{sid} -> POST new /admin/handoff) before
    the first forwarded parse, and the move counts sessions_rehomed_warm."""
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1,
                                                  handoff_enable=True)
    try:
        sid = _sid_homed_on(robj, 0, "wh")
        _post(router.url + "/parse",
              {"text": "go back", "session_id": sid, "context": {}})
        controls[0]["warm"] = {sid: b"warm-session-blob"}
        warm0 = _counters().get("router.sessions_rehomed_warm", 0)
        _post(router.url + "/admin/drain", {"replica": robj.replicas[0].url})
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "drained":
            assert time.monotonic() < deadline, "drain never completed"
            time.sleep(0.05)
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "scroll down", "session_id": sid,
                              "context": {}})
        assert st == 200
        assert hdrs["x-router-replica"] == robj.replicas[1].url
        # the blob crossed replicas verbatim
        assert controls[1].get("adopted") == [b"warm-session-blob"]
        c = _counters()
        assert c.get("router.sessions_rehomed_warm", 0) == warm0 + 1
    finally:
        _teardown(router, servers)


def test_crash_rehome_counts_cold():
    """A crashed home cannot ship anything: the failover retry re-homes
    the session and the move counts sessions_rehomed_cold — the PR 10
    behavior, now explicitly accounted."""
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1,
                                                  handoff_enable=True)
    try:
        sid = _sid_homed_on(robj, 0, "ch")
        _post(router.url + "/parse",
              {"text": "go back", "session_id": sid, "context": {}})
        cold0 = _counters().get("router.sessions_rehomed_cold", 0)
        controls[0]["dead"] = True
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "scroll down", "session_id": sid,
                              "context": {}})
        assert st == 200
        assert hdrs["x-router-replica"] == robj.replicas[1].url
        assert _counters().get("router.sessions_rehomed_cold", 0) == cold0 + 1
        assert not controls[1].get("adopted")  # nothing was shipped
    finally:
        _teardown(router, servers)


def test_handoff_disabled_counts_cold_and_ships_nothing():
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1)
    try:
        sid = _sid_homed_on(robj, 0, "hd")
        _post(router.url + "/parse",
              {"text": "go back", "session_id": sid, "context": {}})
        controls[0]["warm"] = {sid: b"blob"}
        cold0 = _counters().get("router.sessions_rehomed_cold", 0)
        controls[0]["dead"] = True
        deadline = time.monotonic() + 5
        while robj.replicas[0].state != "down":
            assert time.monotonic() < deadline
            time.sleep(0.05)
        _post(router.url + "/parse",
              {"text": "scroll down", "session_id": sid, "context": {}})
        assert _counters().get("router.sessions_rehomed_cold", 0) == cold0 + 1
        assert not controls[1].get("adopted")
    finally:
        _teardown(router, servers)


# --------------------------------------- gauge-driven shedding (ISSUE 13)


def test_pressure_sheds_new_sessions_but_not_sticky_ones():
    """A replica reporting pressure >= ROUTER_SHED_PRESSURE stops
    receiving NEW sessions (they redirect, counted) while its existing
    sessions stay home; with EVERY replica over, placement falls back to
    plain rendezvous instead of erroring."""
    router, servers, logs, controls, robj = _ring(2, probe_s=0.1,
                                                  shed_pressure=0.9)
    try:
        sticky = _sid_homed_on(robj, 0, "ps")
        _post(router.url + "/parse",
              {"text": "go back", "session_id": sticky, "context": {}})
        controls[0]["pressure"] = 0.97
        deadline = time.monotonic() + 5
        while robj.replicas[0].pressure < 0.9:
            assert time.monotonic() < deadline, "probe never saw pressure"
            time.sleep(0.05)
        shed0 = _counters().get("router.shed_pressure", 0)
        fresh = _sid_homed_on(robj, 0, "ps-new")
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "scroll down", "session_id": fresh,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[1].url
        assert _counters().get("router.shed_pressure", 0) == shed0 + 1
        # sticky sessions never move for pressure
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "go back", "session_id": sticky,
                              "context": {}})
        assert hdrs["x-router-replica"] == robj.replicas[0].url
        # every replica over: degrade placement quality, never error
        controls[1]["pressure"] = 0.99
        deadline = time.monotonic() + 5
        while robj.replicas[1].pressure < 0.9:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        both = _sid_homed_on(robj, 0, "ps-full")
        st, hdrs, _b = _post(router.url + "/parse",
                             {"text": "go back", "session_id": both,
                              "context": {}})
        assert st == 200
        assert hdrs["x-router-replica"] == robj.replicas[0].url  # rendezvous
    finally:
        _teardown(router, servers)


# ------------------------------------------- voice /health forwarding


def test_voice_health_forwards_router_replicas(tmp_path):
    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.voice import VoiceConfig
    from tpu_voice_agent.services.voice import build_app as build_voice

    router, servers, logs, controls, robj = _ring(2, probe_s=0.1)
    voice = AppServer(build_voice(VoiceConfig(
        brain_url=router.url, executor_url="http://127.0.0.1:1",
        stt_factory=lambda: NullSTT()))).__enter__()
    try:
        h = _get(voice.url + "/health")
        assert h["brain"]["replicas"] == {"total": 2, "healthy": 2,
                                          "draining": 0, "gray": 0}
    finally:
        voice.__exit__(None, None, None)
        _teardown(router, servers)


# ------------------------------------- satellite 6: spec-in-flight kill


def test_replica_killed_during_speculative_parse_does_not_poison_final(tmp_path):
    """E2e through the real WS path: the session's home replica dies while
    its SPECULATIVE parse is in flight. The stale spec result must be
    discarded (never replayed on the new home), the final must re-route
    and deliver the correct intent — token-identical to a cold parse —
    with no error event and the session alive."""
    import aiohttp

    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.voice import VoiceConfig
    from tpu_voice_agent.services.voice import build_app as build_voice

    router, servers, logs, controls, robj = _ring(
        2, session_aware=True, probe_s=0.1)
    # the spec parse must still be IN FLIGHT when the kill lands
    for c in controls:
        c["delay_s"] = 0.5
    scripted = [("spec_final", "search for usb hubs"),
                ("final", "search for usb hubs")]
    voice = AppServer(build_voice(VoiceConfig(
        brain_url=router.url, executor_url="http://127.0.0.1:1",
        stt_factory=lambda: NullSTT(scripted=list(scripted)),
        parse_timeout_s=10.0))).__enter__()
    pcm = b"\x00\x00" * 1600

    async def run():
        events = []
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(
                    voice.url.replace("http", "ws") + "/stream") as ws:
                await ws.send_bytes(pcm)  # -> spec_final -> speculate()
                # wait for the speculative parse to REACH a replica, then
                # kill exactly that one while the parse is in flight
                deadline = time.monotonic() + 5
                victim = None
                while victim is None:
                    assert time.monotonic() < deadline, "spec never fired"
                    for i, log in enumerate(logs):
                        if any(spec for (_n, _s, spec, _x) in log):
                            victim = i
                            break
                    await asyncio.sleep(0.02)
                controls[victim]["dead"] = True
                survivor = 1 - victim
                controls[survivor]["delay_s"] = 0.0
                await ws.send_bytes(pcm)  # -> transcript_final
                end = time.monotonic() + 15
                while time.monotonic() < end:
                    try:
                        msg = await ws.receive(timeout=1.0)
                    except asyncio.TimeoutError:
                        continue
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        break
                    ev = json.loads(msg.data)
                    events.append(ev)
                    if ev["type"] in ("intent", "error"):
                        break
        return events, victim, survivor

    try:
        events, victim, survivor = asyncio.run(run())
        types = [e["type"] for e in events]
        assert "error" not in types, events
        intent_ev = next(e for e in events if e["type"] == "intent")
        # token-identical to the cold parse of the same text (warmth is a
        # latency property, never a correctness one)
        cold = RuleBasedParser().parse("search for usb hubs", {})
        assert intent_ev["data"] == json.loads(cold.model_dump_json())
        # the final was served FRESH by the survivor (the stale spec result
        # from the dead replica was discarded, not delivered)
        finals = [e for e in logs[survivor] if not e[2]]
        assert finals, f"survivor never served the final: {logs}"
        # the degraded fallback was not needed: the parse itself re-routed
        assert not intent_ev.get("degraded"), intent_ev
    finally:
        voice.__exit__(None, None, None)
        _teardown(router, servers)
