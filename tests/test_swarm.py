"""Capacity observatory (ISSUE 6): the scenario swarm end-to-end at tiny N.

Executable spec for tools/swarm.py + the overload flight recorder: a real
voice→brain→executor stack on sockets, 2-3 concurrent WS sessions through
the scenario mix, the capacity binary search's artifact schema, aborted
WS teardown landing in SLO error accounting, and a deliberately induced
overload (SLO target pinned below achievable latency — the swarm's own
load violates it) freezing a flight-recorder dump that
``GET /debug/flightrecorder`` serves and ``tools/traceview.py --flight``
renders. All CPU, no models — fast tier.
"""

import json
import pathlib
import sys
import urllib.request

import pytest

from tpu_voice_agent.utils import get_flight_recorder, get_metrics

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "tools"))

import swarm  # noqa: E402
import traceview  # noqa: E402


@pytest.fixture()
def stack(tmp_path):
    # earlier tests in this process may have tripped breakers / violated
    # SLOs (both freeze the process-global recorder): start armed
    get_flight_recorder().rearm()
    urls, servers = swarm.build_local_stack(str(tmp_path))
    yield urls
    for srv in servers:
        srv.__exit__(None, None, None)


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


# ------------------------------------------------------------- swarm runs


def test_swarm_tiny_run_full_mix_end_to_end(stack):
    """3 concurrent sessions spanning typed, audio, garbage and barge-in
    scenarios against real services: every scenario answers, the verdict
    dict carries the SLO evaluation, per-scenario stage splits, and the
    saturation attribution."""
    r = swarm.run_swarm(
        stack["voice"], 3, utterances=2, think_s=0.01,
        mix={"single_shot": 1, "paced_audio": 1, "barge_in": 1},
        sample_urls=list(stack.values()))
    assert r["n_sessions"] == 3
    assert r["sessions_crashed"] == 0
    assert set(r["scenarios"]) == {"single_shot", "paced_audio", "barge_in"}
    for name, sc in r["scenarios"].items():
        assert sc["utterances"] >= 2, (name, sc)
        assert sc["errors"] == 0, (name, sc)
        assert sc["lat_p50_ms"] > 0 and sc["lat_p99_ms"] >= sc["lat_p50_ms"]
        # server-side stage splits rode the latency_budget events
        assert "parse_ms" in sc["stages"] and "total_ms" in sc["stages"]
        assert sc["stages"]["parse_ms"]["p50"] >= 0
    # the audio path went through real binary ingest -> STT finalize
    assert "stt_finalize_ms" in r["scenarios"]["paced_audio"]["stages"]
    # SLO verdict: utils/slo.py evaluation shape, all samples accounted
    slo = r["slo"]
    assert slo["state"] in ("ok", "at_risk", "violated")
    assert slo["samples"] == r["utterances"] >= 6
    assert slo["errors"] == 0
    # saturation attribution ran over a live gauge timeline
    sat = r["saturation"]
    assert sat["samples"] >= 1
    assert "peak_fractions" in sat and "first_saturated" in sat


def test_swarm_garbage_and_multi_turn_sessions_survive(stack):
    r = swarm.run_swarm(stack["voice"], 2, utterances=2, think_s=0.01,
                        mix={"garbage": 1, "multi_turn": 1},
                        sample_urls=[stack["voice"]])
    # garbage frames warned (bad PCM + bad control) but the session kept
    # parsing afterwards — no errors, no crashed sessions
    assert r["client_warns"] >= 2
    assert r["sessions_crashed"] == 0
    assert r["scenarios"]["garbage"]["errors"] == 0
    assert r["scenarios"]["multi_turn"]["utterances"] == 2


def test_ws_teardown_mid_utterance_costs_slo_error_budget(stack):
    """The aborted-utterance accounting (the satellite): a client that arms
    an utterance and vanishes before ``final`` must land in slo.voice.* as
    an error sample and in voice.utterances_aborted — churn is not free."""
    before = get_metrics().snapshot()["counters"].get(
        "voice.utterances_aborted", 0.0)
    r = swarm.run_swarm(stack["voice"], 2, utterances=1, think_s=0.01,
                        mix={"abort": 1}, sample_urls=[stack["voice"]])
    assert r["aborted_sessions"] == 2
    snap = get_metrics().snapshot()
    assert snap["counters"]["voice.utterances_aborted"] == before + 2
    # the error samples reached the voice service's own SLO window
    health = _get_json(stack["voice"] + "/health")
    m = _get_json(stack["voice"] + "/metrics")
    assert m["slo"]["errors"] >= 2
    assert health["sessions"] == 0  # teardown decremented the live count


def test_health_reports_live_sessions_and_capacity(stack, monkeypatch):
    h = _get_json(stack["voice"] + "/health")
    assert h["sessions"] == 0
    assert "capacity_sessions" in h


def test_capacity_binary_search_verdict_schema(stack):
    out = swarm.binary_search_capacity(stack["voice"], max_n=2, utterances=2,
                                       think_s=0.01,
                                       mix={"single_shot": 1},
                                       sample_urls=[stack["voice"]])
    assert out["max_n"] == 2
    assert 0 <= out["capacity_sessions"] <= 2
    assert out["probes"] and all(
        {"n", "state", "p50_ms", "p99_ms", "error_rate"} <= set(p)
        for p in out["probes"])
    assert isinstance(out["saturated"], bool)
    if out["capacity_sessions"]:
        at_cap = out["at_capacity"]
        assert at_cap["slo"]["state"] == "ok"
        assert at_cap["saturation"]["samples"] >= 1


def test_scenario_deal_is_diverse_and_proportional():
    dealt = swarm._deal_scenarios(8, swarm.DEFAULT_MIX)
    assert len(dealt) == 8
    # small probes still mix behaviors (the old deck deal gave the first 8
    # sessions nothing but the two heaviest scenarios)
    assert len(set(dealt)) >= 6
    heavy = swarm._deal_scenarios(100, {"single_shot": 3, "abort": 1})
    assert heavy.count("single_shot") == 75 and heavy.count("abort") == 25
    with pytest.raises(ValueError):
        swarm._deal_scenarios(4, {"nope": 1})


# --------------------------------------------------- overload -> flight dump


def test_induced_overload_freezes_flight_recorder(tmp_path, monkeypatch):
    """The acceptance drill: pin the SLO target below anything the stack
    can serve, swarm it, and the ok->violated transition freezes a flight
    dump — retrievable at /debug/flightrecorder, renderable by
    ``tools/traceview.py --flight``, and re-armable."""
    monkeypatch.setenv("SLO_TARGET_P50_MS", "0.01")
    monkeypatch.setenv("SLO_MIN_SAMPLES", "2")
    get_flight_recorder().rearm()
    urls, servers = swarm.build_local_stack(str(tmp_path))
    try:
        # armed before the incident
        pre = _get_json(urls["voice"] + "/debug/flightrecorder")
        assert pre["frozen"] is False and pre["armed"] is True
        assert pre["service"] == "voice"
        r = swarm.run_swarm(urls["voice"], 2, utterances=3, think_s=0.01,
                            mix={"single_shot": 1},
                            sample_urls=[urls["voice"]])
        assert r["slo"]["state"] == "violated"  # the pinned target is unmeetable
        # the service detects the transition itself, on either of its two
        # surfaces: record()'s once-a-second auto-eval (a sustained
        # overload) or any /health evaluation. This burst is sub-second,
        # so poll /health — the swarm's sampler deliberately reads the
        # side-effect-free /debug/timeseries ring and cannot do it for us.
        _get_json(urls["voice"] + "/health")
        dump = _get_json(urls["voice"] + "/debug/flightrecorder")
        assert dump["frozen"] is True
        # the freeze must come from the SERVICES' own detection (the
        # swarm's verdict tracker is passive and cannot trigger it)
        assert dump["reason"].startswith(
            ("slo.voice.", "slo.brain.", "slo.executor.", "breaker."))
        assert dump["traces"], "the dump must retain utterance traces"
        assert dump["metric_snapshots"], "the dump must carry the gauge timeline"
        spans = [sp for tr in dump["traces"] for sp in tr["spans"]]
        assert any(sp["svc"] == "brain" and sp["span"] == "parse"
                   for sp in spans), "cross-service spans belong in the dump"
        # every service serves the same process-global dump
        assert _get_json(urls["brain"] + "/debug/flightrecorder")["frozen"]

        # traceview --flight renders the frozen window as gantts
        path = tmp_path / "flight.json"
        path.write_text(json.dumps(dump))
        text = traceview.render_flight(dump, last=2)
        assert dump["reason"] in text and "█" in text
        rc = traceview.main(["--flight", str(path), "--last", "2"])
        assert rc == 0

        # retrieval + rearm in one roundtrip; the next GET is armed again
        again = _get_json(urls["voice"] + "/debug/flightrecorder?rearm=1")
        assert again["frozen"] is True and again["rearmed"] is True
        assert _get_json(urls["voice"] + "/debug/flightrecorder")["frozen"] is False
    finally:
        for srv in servers:
            srv.__exit__(None, None, None)
        get_flight_recorder().rearm()


def test_bench_swarm_artifact_schema(tmp_path):
    """benches/bench_swarm.py at its smallest settings: the emitted rows
    and the ``BENCH_swarm_*`` artifact carry the capacity verdict, the
    per-scenario breakdown, and the saturation attribution that
    run_all.py merges."""
    import os
    import subprocess
    import sys as _sys

    art_dir = ROOT / "bench_artifacts"
    before = set(art_dir.glob("BENCH_swarm_*.json")) if art_dir.exists() else set()
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_SWARM_MAX_N="2",
               BENCH_SWARM_UTTERANCES="2", BENCH_SWARM_THINK_S="0.01")
    proc = subprocess.run([_sys.executable, str(ROOT / "benches" / "bench_swarm.py")],
                          capture_output=True, text=True, timeout=300, env=env,
                          cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.startswith("{")]
    metrics = {r["metric"] for r in rows}
    assert "swarm_capacity_sessions" in metrics
    assert "swarm_probes" in metrics

    new = sorted(set(art_dir.glob("BENCH_swarm_*.json")) - before)
    assert new, "bench must write a BENCH_swarm_* artifact"
    art = json.loads(new[-1].read_text())
    try:
        assert art["bench"] == "bench_swarm"
        sw = art["swarm"]
        assert {"capacity_sessions", "saturated", "probes", "at_capacity",
                "first_saturated", "flight_recorder"} <= set(sw)
        at = sw["at_capacity"] or sw["knee"]
        assert at["scenarios"], "per-scenario breakdown missing"
        for sc in at["scenarios"].values():
            assert {"utterances", "lat_p50_ms", "lat_p99_ms", "stages"} <= set(sc)
        assert "peak_fractions" in at["saturation"]
    finally:
        for p in new:
            p.unlink()  # tests must not litter the artifact trajectory
