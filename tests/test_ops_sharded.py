"""shard_map'd Pallas kernels on a dp×tp mesh (VERDICT round-1 next #4).

Round 1's kernels were bare pallas_calls: on a mesh GSPMD replicated their
operands, so the v5e-8 target couldn't use them. These tests hold the
sharded wrappers to bit-level agreement with the single-device kernels on
8 virtual CPU devices (kernels run under interpret=True on CPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.ops import (
    decode_attention,
    flash_attention,
    masked_argmax,
    sharded_decode_attention,
    sharded_flash_attention,
    sharded_masked_argmax,
)
from tpu_voice_agent.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_mesh(dp=2, tp=2)


def test_sharded_decode_attention_matches_single_device(mesh):
    B, S, nq, nkv, hd = 4, 64, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, nkv, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, nkv, hd), jnp.float32)
    kv_len = jnp.asarray([5, 17, 33, 64], jnp.int32)
    ref = decode_attention(q, k, v, kv_len)
    out = sharded_decode_attention(mesh, q, k, v, kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_sharded_decode_attention_tp_indivisible_heads_replicates(mesh):
    # nkv=3 not divisible by tp=2: heads fall back to replicated (dp only)
    B, S, nq, nkv, hd = 2, 32, 6, 3, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, nkv, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, nkv, hd), jnp.float32)
    kv_len = jnp.asarray([10, 32], jnp.int32)
    ref = decode_attention(q, k, v, kv_len)
    out = sharded_decode_attention(mesh, q, k, v, kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_sharded_flash_attention_matches_single_device(mesh):
    B, T, nq, nkv, hd = 2, 32, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, T, nq, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, nkv, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, nkv, hd), jnp.float32)
    ref = flash_attention(q, k, v, causal=True)
    out = sharded_flash_attention(mesh, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_sharded_masked_argmax_matches_single_device(mesh):
    B, V, S = 4, 512, 7
    key = jax.random.PRNGKey(3)
    logits = jax.random.normal(key, (B, V), jnp.float32)
    mask = jax.random.bernoulli(jax.random.PRNGKey(4), 0.3, (S, V))
    mask = mask.at[:, 0].set(True)  # every state keeps >= 1 legal token
    state = jnp.asarray([0, 2, 5, 6], jnp.int32)
    ref = masked_argmax(logits, state, mask)
    out = sharded_masked_argmax(mesh, logits, state, mask)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_mesh_engine_accepts_pallas_kernels(mesh):
    """kernels='pallas' on a dp×tp mesh compiles and produces grammar-valid
    output (round 1 raised ValueError here)."""
    from tpu_voice_agent.serve import DecodeEngine

    eng = DecodeEngine(preset="test-tiny", mesh=mesh, batch_slots=2, max_len=1024,
                       prefill_buckets=(512, 1024), kernels="pallas")
    res = eng_generate_one(eng)
    state = eng.fsm.walk(res.token_ids)
    assert state >= 0, "mesh+pallas decode left the grammar"


def eng_generate_one(eng):
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher

    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=48)
    return b.generate_many(["<|user|>\nsearch for mice\n<|assistant|>\n"])[0]


def test_sharded_decode_block_attention_matches_single_device(mesh):
    """The batched-ff block kernel under shard_map on the dp×tp mesh must
    agree with the single-device kernel (batch over dp, heads over tp)."""
    from tpu_voice_agent.ops import (
        decode_block_attention_layer,
        sharded_decode_block_attention_layer,
    )

    L, B, T, nq, nkv, hd, S = 2, 4, 3, 8, 4, 32, 128
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (B, T, nq, hd), jnp.float32)
    kc = jax.random.normal(ks[1], (L, B, S, nkv, hd), jnp.float32)
    vc = jax.random.normal(ks[2], (L, B, S, nkv, hd), jnp.float32)
    q_pos = jnp.asarray([[5, 6, 7], [0, 0, 0], [40, 41, 42], [99, 100, 101]],
                        jnp.int32)
    for li in range(L):
        ref = decode_block_attention_layer(q, kc, vc, q_pos, jnp.int32(li))
        out = sharded_decode_block_attention_layer(
            mesh, q, kc, vc, q_pos, jnp.int32(li))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
