"""Cost & efficiency observatory (ISSUE 17) — FAST tier.

The conservation contract (utils/costmodel.py): every ledger quantity is
a Python int, and the scheduler folds the SAME ints into the per-request
slot ledger and the engine meter's totals — so ``sum(per-request
ledgers) == engine totals`` holds EXACTLY, including errored rows
(poisoned, cancelled: the hardware did the work, the ledger bills it).
The differential contract: the cost lanes are host arithmetic over
readbacks the chunk already pays for — token streams identical with the
lanes on or off, zero recompiles past the warmup fence with them on.

Surfaces covered here: ``GET /debug/costs`` on brain (meter + session
attribution) and voice (STT share), the flight-recorder dump's ``costs``
section, and the SessionCostLedger LRU semantics.
"""

import json
import time
import urllib.request

import pytest

from tpu_voice_agent.serve import DecodeEngine, PagedDecodeEngine, SpecConfig
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import (
    SessionTranscripts,
    install_prompt_prefix,
)
from tpu_voice_agent.services.prompts import render_prompt
from tpu_voice_agent.utils import chaos, get_metrics
from tpu_voice_agent.utils.costmodel import (
    LEDGER_KEYS,
    CostModel,
    SessionCostLedger,
    decode_flops,
    device_peak,
    llm_attn_flops_per_ctx,
    llm_token_flops,
    prefill_flops,
    spec_verify_flops,
    whisper_decoder_flops,
    whisper_encoder_flops,
    zero_ledger,
)

BUCKETS = (128, 256, 512, 1024, 2048)
MAXTOK = 32


def _sum_costs(results) -> dict:
    out = zero_ledger()
    for r in results:
        assert r.cost is not None, f"request missing its ledger: {r.error}"
        for k in LEDGER_KEYS:
            out[k] += r.cost[k]
    return out


def _assert_conserved(batcher, results) -> None:
    summed = _sum_costs(results)
    totals = batcher.costs.totals
    for k in LEDGER_KEYS:
        assert summed[k] == totals[k], (
            f"{k}: sum(requests)={summed[k]} != engine={totals[k]} "
            f"(delta {summed[k] - totals[k]:+d})")
        assert isinstance(totals[k], int) and isinstance(summed[k], int)


# ------------------------------------------------------------- unit model


@pytest.fixture(scope="module")
def tiny_cfg():
    return DecodeEngine(preset="test-tiny", max_len=128, prefill_buckets=(64,),
                        init_weights=False).cfg


def test_zero_ledger_keys(tiny_cfg):
    z = zero_ledger()
    assert tuple(z) == LEDGER_KEYS
    assert all(v == 0 and isinstance(v, int) for v in z.values())
    assert isinstance(llm_token_flops(tiny_cfg), int)
    assert isinstance(llm_attn_flops_per_ctx(tiny_cfg), int)


def test_prefill_split_exact_partition(tiny_cfg):
    """computed + cached == the full cold-prompt cost, exactly, for any
    cache depth — the split is a partition, not an approximation."""
    model = CostModel(tiny_cfg)
    for n, c in ((100, 0), (100, 37), (100, 100), (7, 3), (1, 0)):
        computed, cached = model.prefill_split(n, c)
        assert computed + cached == prefill_flops(tiny_cfg, n, n)
        assert cached == prefill_flops(tiny_cfg, c, c)
        assert computed >= 0 and cached >= 0
    # cached beyond the prompt clamps (radix can only match the prompt)
    assert model.prefill_split(10, 99) == (0, prefill_flops(tiny_cfg, 10, 10))
    assert model.prefill_split(0, 0) == (0, 0)


def test_decode_and_spec_verify_flops(tiny_cfg):
    tok = llm_token_flops(tiny_cfg)
    att = llm_attn_flops_per_ctx(tiny_cfg)
    assert decode_flops(tiny_cfg, 3, 100) == 3 * (tok + 100 * att)
    # a verify forward computes 1 + K positions whether drafts survive
    assert spec_verify_flops(tiny_cfg, 200, 4) == decode_flops(tiny_cfg, 5, 200)
    model = CostModel(tiny_cfg)
    fl, by = model.decode_row(2, 50)
    assert fl == decode_flops(tiny_cfg, 2, 50)
    assert by == 2 * model.kv_pos_bytes * 51  # reads over ctx + the write


def test_whisper_flops_shape():
    from tpu_voice_agent.models.whisper import WhisperConfig

    cfg = WhisperConfig()
    e1 = whisper_encoder_flops(cfg, 500)
    e2 = whisper_encoder_flops(cfg, 1000)
    assert isinstance(e1, int) and e1 > 0
    assert e2 > 2 * e1  # self-attention term is quadratic in frames
    d1 = whisper_decoder_flops(cfg, 10, 250)
    assert isinstance(d1, int) and d1 > 0
    assert whisper_decoder_flops(cfg, 20, 250) == 2 * d1  # linear in tokens
    assert whisper_decoder_flops(cfg, 0, 250) == 0


def test_device_peak_knob_override(monkeypatch):
    monkeypatch.setenv("COST_PEAK_TFLOPS", "100")
    monkeypatch.setenv("COST_PEAK_GBPS", "1000")
    p = device_peak()
    assert p["flops_per_s"] == pytest.approx(100e12)
    assert p["bytes_per_s"] == pytest.approx(1000e9)
    assert p["source"] == "knob"
    monkeypatch.delenv("COST_PEAK_TFLOPS")
    monkeypatch.delenv("COST_PEAK_GBPS")
    p = device_peak()  # CPU harness: the documented proxy, finite and > 0
    assert p["flops_per_s"] > 0 and p["bytes_per_s"] > 0
    assert p["source"] in ("table", "cpu-proxy")


# ------------------------------------------------------- dense conservation


def test_dense_conservation_exact(tiny_batch_engine):
    b = ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                          max_new_tokens=MAXTOK)
    assert b.costs is not None, "COST_ENABLE defaults on"
    prompts = [f"search for item {i} and sort by price" for i in range(5)]
    res = b.generate_many(prompts)
    assert all(r.error is None for r in res)
    _assert_conserved(b, res)
    t = b.costs.totals
    assert t["prefill_flops"] > 0 and t["decode_flops"] > 0
    assert t["decode_bytes"] > 0 and t["kv_block_us"] > 0
    assert t["wasted_draft_flops"] == 0  # no drafts on the plain loop
    assert t["prefill_cached_flops"] == 0  # dense engine, no prefix cache
    # the meter reconciled measured walls into live gauges + counters
    snap = get_metrics().snapshot()
    assert snap["gauges"]["engine.mfu"] > 0
    assert snap["gauges"]["engine.mbu"] > 0
    assert snap["gauges"]["engine.mfu_prefill"] > 0
    assert snap["counters"]["cost.decode_flops"] > 0
    assert snap["counters"]["cost.decode_bytes"] > 0
    assert b.costs.engine["chunks"] > 0
    assert b.costs.engine["weights_stream_bytes"] > 0
    assert get_metrics().collisions() == []


def test_cost_lanes_token_identity_and_quiet_sentinel(tiny_batch_engine,
                                                      monkeypatch):
    from tpu_voice_agent.utils.compilewatch import get_compile_watcher

    prompts = ["dim the bedroom lights", "what time is it"]
    on = ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                           max_new_tokens=MAXTOK).generate_many(prompts)
    monkeypatch.setenv("COST_ENABLE", "0")
    b_off = ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                              max_new_tokens=MAXTOK)
    assert b_off.costs is None
    off = b_off.generate_many(prompts)
    monkeypatch.delenv("COST_ENABLE")
    assert [r.token_ids for r in on] == [r.token_ids for r in off]
    assert all(r.cost is not None for r in on)
    assert all(r.cost is None for r in off)  # off = no ledgers at all
    # zero recompiles past the fence with the lanes ON (host arithmetic
    # only — the cost plane must never perturb the jitted decode path)
    w = get_compile_watcher()
    w.arm_fence("cost lanes warmed")
    before = w.state()["post_fence_compiles"]
    again = ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                              max_new_tokens=MAXTOK).generate_many(prompts)
    assert [r.token_ids for r in again] == [r.token_ids for r in on]
    assert w.state()["post_fence_compiles"] == before


# ------------------------------------------------------- paged mixed batch


@pytest.mark.parametrize("tier", [None, "int8", "int4"])
def test_paged_mixed_batch_conservation(tier):
    """The acceptance drill: ONE meter over a mixed workload — radix warm
    hits, spec accepts/rejects, a chaos-poisoned row, a mid-decode
    cancellation — reconciles exactly, errored rows still billing the
    work they spent before eviction."""
    eng = PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=2,
        prefill_buckets=BUCKETS, radix_enable=True,
        spec=SpecConfig(k=4, drafter="fsm,prompt"), kv_quant=tier or "off")
    install_prompt_prefix(eng)
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=MAXTOK)
    assert b.costs is not None
    tok = eng.tokenizer
    P = len(eng.prefix_ids)
    seen = []  # every result this meter's batcher produced (generate_many
    # POPS results out of batcher.results — collect as they return)

    # two session turns: turn 2 admits warm off the radix chain
    st = SessionTranscripts(tok)
    turn_res = []
    for text in ("search for wireless headphones", "open the second result"):
        prompt = st.prompt_for("sess", text, {})
        ids = (tok.encode(prompt, bos=True) if isinstance(prompt, str)
               else list(prompt))
        r = b.generate_many([ids])[0]
        assert r.error is None, r.error
        turn_res.append(r)
        seen.append(r)
        st.record("sess", ids, r.token_ids)
    assert turn_res[0].cached_tokens == P
    assert turn_res[1].cached_tokens > P  # radix warm hit
    # the warm turn's avoided work is priced, not dropped
    assert turn_res[1].cost["prefill_cached_flops"] > \
        turn_res[0].cost["prefill_cached_flops"] > 0

    # a poisoned row: 2nd admission NaN-fenced mid-decode, evicted alone
    chaos.configure("nan_logits@2")
    try:
        pois = b.generate_many([render_prompt("scroll down", {}),
                                render_prompt("go back", {})])
    finally:
        chaos.reset()
    seen += pois
    assert pois[1].error is not None and \
        pois[1].error.startswith("poisoned: non-finite"), pois[1].error
    assert pois[0].error is None
    # the evicted row rode out with the cost it spent before the fence
    assert pois[1].cost is not None
    assert pois[1].cost["kv_block_us"] > 0

    # a mid-decode cancellation: evicts at the next chunk boundary
    rid = b.submit(render_prompt("search for mechanical keyboards", {}))
    b.step()
    assert b.cancel(rid, "client gone")
    b.run_until_done()
    cancelled = b.results[rid]
    seen.append(cancelled)
    assert cancelled.error is not None and "cancel" in cancelled.error
    assert cancelled.cost is not None
    assert cancelled.cost["kv_block_us"] > 0

    # EXACT reconciliation over every request this meter ever saw
    _assert_conserved(b, seen)
    t = b.costs.totals
    # spec ran: drafts were paid for, rejected ones show up as waste — a
    # subset of decode_flops, never more
    assert eng.spec.stats()["accepted"] > 0
    assert 0 <= t["wasted_draft_flops"] <= t["decode_flops"]
    # paged rows hold real block-time (owned + shared x chunk walls)
    assert t["kv_block_us"] > 0


# ------------------------------------------------------------- attribution


def test_session_cost_ledger_lru_and_top():
    led = SessionCostLedger(cap=2)
    led.fold(None, None)  # no cost -> no entry
    assert len(led) == 0
    cost_a = dict(zero_ledger(), prefill_flops=100, decode_flops=50)
    cost_b = dict(zero_ledger(), prefill_flops=10, decode_flops=5)
    led.fold("a", cost_a)
    led.fold("a", cost_a)  # accumulates, same session
    led.fold("b", cost_b)
    top = led.top()
    assert top[0]["session"] == "a"
    assert top[0]["prefill_flops"] == 200 and top[0]["utterances"] == 2
    assert top[0]["last_s"] <= time.time() + 1
    led.fold(None, cost_b)  # stateless bucket
    assert len(led) == 2  # cap=2: oldest ("a") evicted
    sessions = {e["session"] for e in led.top(8)}
    assert sessions == {"b", "_stateless"}
    assert led.top(1) and len(led.top(1)) == 1


def test_brain_debug_costs_endpoint(tiny_engine):
    # tiny_engine, not tiny_batch_engine: the rendered brain prompt is
    # ~900 tokens and needs the 1024 prefill bucket
    from tests.http_helper import AppServer
    from tpu_voice_agent.services.brain import BatchedEngineParser, build_app

    from tpu_voice_agent.services.brain import ParserError

    parser = BatchedEngineParser(tiny_engine, chunk_steps=8,
                                 max_new_tokens=300)
    try:
        for text in ("turn on the lights", "turn off the lights"):
            try:
                parser.parse(text, {}, session_id="s1")
            except ParserError:
                pass  # random-weight truncation raises AFTER the cost
                # fold — attribution covers errored requests by contract
        with AppServer(build_app(parser)) as srv:
            with urllib.request.urlopen(srv.url + "/debug/costs?top=4",
                                        timeout=10) as r:
                body = json.loads(r.read().decode())
    finally:
        parser.close()
    assert body["service"] == "brain" and body["enabled"]
    assert body["totals"]["decode_flops"] > 0
    assert set(LEDGER_KEYS) <= set(body["totals"])
    assert body["engine"]["chunks"] > 0
    assert "mfu" in body and "mbu" in body and body["peak"]["flops_per_s"] > 0
    assert body["model"]["token_flops"] > 0
    assert body["sessions"] >= 1
    top = body["top_sessions"]
    assert top and top[0]["session"] == "s1" and top[0]["utterances"] == 2


def test_voice_debug_costs_carries_stt_share():
    from tests.http_helper import AppServer
    from tpu_voice_agent.serve.stt import NullSTT
    from tpu_voice_agent.services.voice import VoiceConfig
    from tpu_voice_agent.services.voice import build_app as build_voice
    from tpu_voice_agent.utils.costmodel import (
        register_stt_engine,
        stt_cost_summary,
    )

    class _FakeSTT:
        cost_totals = {"encoder_flops": 1000, "decoder_flops": 200,
                       "encoded_frames": 300, "decoded_tokens": 12}

    fake = _FakeSTT()  # keep a strong ref: the registry is weak
    register_stt_engine(fake)
    s = stt_cost_summary()
    assert s is not None and s["encoder_flops"] >= 1000
    cfg = VoiceConfig(brain_url="http://127.0.0.1:1",
                      executor_url="http://127.0.0.1:1",
                      stt_factory=lambda: NullSTT())
    with AppServer(build_voice(cfg)) as voice:
        with urllib.request.urlopen(voice.url + "/debug/costs",
                                    timeout=10) as r:
            body = json.loads(r.read().decode())
    assert body["service"] == "voice" and body["enabled"]
    assert body["stt"]["encoder_flops"] >= 1000
    assert body["stt"]["engines"] >= 1


def test_flight_dump_carries_cost_snapshot(tiny_batch_engine):
    """The incident autopsy must carry the spend picture: a meter fed by
    a real run lands in the frozen flight dump under ``costs``."""
    from tpu_voice_agent.utils import get_flight_recorder

    b = ContinuousBatcher(tiny_batch_engine, chunk_steps=8,
                          max_new_tokens=MAXTOK)
    b.generate_many(["search for usb hubs"])
    rec = get_flight_recorder()
    rec.rearm()
    rec.trigger("test", "cost snapshot drill")
    dump = rec.frozen_dump()
    assert dump is not None
    costs = dump.get("costs")
    assert costs is not None and "llm" in costs
    assert costs["llm"]["totals"]["decode_flops"] > 0
    rec.rearm()
