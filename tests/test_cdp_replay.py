"""Hermetic CDP driver coverage: a scripted fake-Chrome websocket endpoint.

Round-2 VERDICT weak #4: services/executor/cdp.py (the hand-rolled DevTools
protocol client replacing the reference's Playwright, apps/executor/src/
session.ts:35-53) was only covered by the CDP_URL-gated live smoke test, so
protocol rot would pass CI. Here a scripted CDP server speaks the protocol
over a REAL websocket — `_CDPConn`'s connection thread, request/response
correlation, event buffering, and every `CDPPage` wrapper run for real; only
Chrome itself is scripted. The `CDP_URL` smoke test remains the live canary.
"""

from __future__ import annotations

import base64
import json

import pytest
from aiohttp import web

from tests.http_helper import AppServer
from tpu_voice_agent.services.executor.cdp import CDPError, CDPPage, _CDPConn

_PNG_1PX = base64.b64encode(bytes.fromhex(
    "89504e470d0a1a0a0000000d4948445200000001000000010802000000907753de"
    "0000000c49444154789c63606060000000040001f61738550000000049454e44ae426082"
)).decode()


class FakeChrome:
    """Scripted CDP endpoint: canned per-method responses + a transcript of
    every request (so tests assert the wrappers emit the right protocol).
    Runtime.evaluate answers by substring, FakePage-style — the driver's JS
    is not executed, only its protocol framing is exercised."""

    def __init__(self):
        self.requests: list[dict] = []  # the transcript
        self.title = "Fake CDP Page"
        self.fail_navigate = False
        self.throw_on_eval: str | None = None  # substring -> exceptionDetails
        # optional scripted DOM (a FakePage): when set, __SCAN__ /
        # __EXTRACT_CARDS__ / innerText evals answer with ITS storefront —
        # the 19-intent replay corpus runs the real interpreter + real CDP
        # framing against it (only Chrome's JS engine is scripted)
        self.dom = None

    def app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/devtools/page/T1", self._ws)
        return app

    async def _ws(self, request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse(max_msg_size=64 * 1024 * 1024)
        await ws.prepare(request)
        async for msg in ws:
            req = json.loads(msg.data)
            self.requests.append(req)
            method, params = req["method"], req.get("params", {})
            events: list[dict] = []
            if method == "Page.navigate":
                if self.fail_navigate:
                    result = {"errorText": "net::ERR_NAME_NOT_RESOLVED"}
                else:
                    result = {"frameId": "F1"}
                    events.append({"method": "Page.loadEventFired",
                                   "params": {"timestamp": 1.0}})
            elif method == "Runtime.evaluate":
                expr = params.get("expression", "")
                if self.throw_on_eval and self.throw_on_eval in expr:
                    result = {"exceptionDetails": {"text": "Uncaught TypeError: boom"}}
                else:
                    result = {"result": {"value": self._eval(expr)}}
            elif method == "DOM.getDocument":
                result = {"root": {"nodeId": 1}}
            elif method == "DOM.querySelector":
                result = {"nodeId": 42 if "file" in params.get("selector", "") else 0}
            elif method == "Page.getNavigationHistory":
                result = {"currentIndex": 1, "entries": [
                    {"id": 10, "url": "https://a.example"},
                    {"id": 11, "url": "https://b.example"},
                    {"id": 12, "url": "https://c.example"},
                ]}
            elif method == "Page.getLayoutMetrics":
                result = {"cssContentSize": {"width": 800, "height": 1600}}
            elif method == "Page.captureScreenshot":
                result = {"data": _PNG_1PX}
            elif method == "Bogus.method":
                await ws.send_str(json.dumps(
                    {"id": req["id"],
                     "error": {"code": -32601, "message": "'Bogus.method' wasn't found"}}))
                continue
            else:  # enables, Input.*, DOM.setFileInputFiles, navigateToHistoryEntry...
                result = {}
            await ws.send_str(json.dumps({"id": req["id"], "result": result}))
            for ev in events:
                await ws.send_str(json.dumps(ev))
        return ws

    def _eval(self, expr: str):
        if self.dom is not None and any(
            marker in expr
            for marker in ("__SCAN__", "__EXTRACT_CARDS__",
                           "document.body.innerText")
        ):
            # delegate to FakePage.evaluate — ONE implementation of the
            # scan-marker wire format (page.py), not a drifting copy here
            return self.dom.evaluate(expr)
        if "document.title" in expr:
            return self.title
        if "getBoundingClientRect" in expr:  # wait_for_selector probe
            return True
        if "el.click()" in expr or "el.value =" in expr or "el.options" in expr:
            return True  # click/fill/select succeed
        if "window.scrollBy" in expr:
            return None
        if "focus()" in expr:
            return None
        return None

    def calls(self, method: str) -> list[dict]:
        return [r for r in self.requests if r["method"] == method]


@pytest.fixture()
def chrome():
    fake = FakeChrome()
    with AppServer(fake.app()) as srv:
        page = CDPPage(_CDPConn(f"ws://127.0.0.1:{srv.port}/devtools/page/T1"))
        yield fake, page
        page.close()


def test_connect_enables_domains(chrome):
    fake, page = chrome
    assert [r["method"] for r in fake.requests[:3]] == [
        "Page.enable", "Runtime.enable", "DOM.enable"]


def test_goto_waits_for_load_event_and_reads_title(chrome):
    fake, page = chrome
    page.goto("https://shop.example", timeout_ms=5000)
    assert page.url == "https://shop.example"
    assert page.title == "Fake CDP Page"
    nav = fake.calls("Page.navigate")
    assert nav and nav[0]["params"]["url"] == "https://shop.example"


def test_goto_failure_raises(chrome):
    fake, page = chrome
    fake.fail_navigate = True
    with pytest.raises(CDPError, match="ERR_NAME_NOT_RESOLVED"):
        page.goto("https://nope.invalid", timeout_ms=2000)


def test_evaluate_returns_value_and_raises_on_js_exception(chrome):
    fake, page = chrome
    assert page.evaluate("document.title") == "Fake CDP Page"
    ev = fake.calls("Runtime.evaluate")[-1]["params"]
    assert ev["returnByValue"] is True and ev["awaitPromise"] is True
    fake.throw_on_eval = "document.title"
    with pytest.raises(CDPError, match="boom"):
        page.evaluate("document.title")


def test_click_fill_press_select_protocol(chrome):
    fake, page = chrome
    page.click_selector("#buy", timeout_ms=2000)
    page.click_text("add to cart", timeout_ms=2000)
    page.click_role("button", "Checkout", timeout_ms=2000)
    page.fill("#q", "usb hubs")
    page.press("#q", "Enter")
    page.select_option("#sort", "Price Low to High")
    evals = [r["params"]["expression"] for r in fake.calls("Runtime.evaluate")]
    assert any("#buy" in e and "el.click()" in e for e in evals)
    assert any("add to cart" in e for e in evals)
    assert any("usb hubs" in e for e in evals)
    # Enter is a trusted Input event triple (rawKeyDown, char, keyUp)
    keys = [r["params"]["type"] for r in fake.calls("Input.dispatchKeyEvent")]
    assert keys == ["rawKeyDown", "char", "keyUp"]


def test_click_at_dispatches_trusted_mouse_events(chrome):
    fake, page = chrome
    page.click_at(120.0, 88.0)
    mouse = fake.calls("Input.dispatchMouseEvent")
    assert [m["params"]["type"] for m in mouse] == ["mousePressed", "mouseReleased"]
    assert mouse[0]["params"]["x"] == 120.0 and mouse[0]["params"]["y"] == 88.0


def test_upload_resolves_node_and_sets_files(chrome):
    fake, page = chrome
    page.set_input_files("input[type=file]", "/tmp/resume.pdf")
    sf = fake.calls("DOM.setFileInputFiles")
    assert sf and sf[0]["params"] == {"files": ["/tmp/resume.pdf"], "nodeId": 42}
    with pytest.raises(CDPError, match="no element"):
        page.set_input_files("#missing", "/tmp/x")


def test_history_navigation_uses_entry_ids(chrome):
    fake, page = chrome
    page.go_back()
    page.go_forward()
    navs = fake.calls("Page.navigateToHistoryEntry")
    assert [n["params"]["entryId"] for n in navs] == [10, 12]
    assert page.url == "https://c.example"


def test_screenshot_full_page_clips_to_content_size(chrome, tmp_path):
    fake, page = chrome
    out = tmp_path / "shot.png"
    page.screenshot(str(out), full_page=True)
    shot = fake.calls("Page.captureScreenshot")[0]["params"]
    assert shot["clip"]["width"] == 800 and shot["clip"]["height"] == 1600
    assert shot["captureBeyondViewport"] is True
    assert out.read_bytes().startswith(b"\x89PNG")


def test_protocol_error_envelope_raises(chrome):
    fake, page = chrome
    with pytest.raises(CDPError, match="wasn't found"):
        page.conn.call("Bogus.method")


def test_stale_load_events_are_cleared_before_navigate(chrome):
    """A buffered loadEventFired from a previous navigation must not satisfy
    the next goto's wait (the clear_events contract)."""
    fake, page = chrome
    page.goto("https://first.example", timeout_ms=5000)
    # park a stale event in the buffer, as an unconsumed load would be
    page.conn._events.append({"method": "Page.loadEventFired", "params": {}})
    page.goto("https://second.example", timeout_ms=5000)
    assert page.url == "https://second.example"
    # the buffer holds no leftover load events (each goto consumed its own)
    assert all(e.get("method") != "Page.loadEventFired" for e in page.conn._events)


def test_nineteen_intent_replay_corpus(chrome, tmp_path):
    """ALL 19 schema intent types through the REAL interpreter and the REAL
    CDP driver against the scripted endpoint (round-3 VERDICT next #7: no
    chromium ships in this image, so the full-protocol replay corpus is the
    evidence that every intent drives the wire correctly end to end)."""
    from tpu_voice_agent.schemas import Intent, Target
    from tpu_voice_agent.services.executor.actions import run_intents
    from tpu_voice_agent.services.executor.page import FakePage

    fake, page = chrome
    fake.dom = FakePage.demo()  # the storefront answers the analyzer scans

    uploads = tmp_path / "uploads"
    uploads.mkdir()
    (uploads / "ab12cd.pdf").write_bytes(b"%PDF-fake")

    intents = [
        Intent(type="navigate", args={"url": "https://demo.local/shop"}),
        Intent(type="search", args={"query": "usb hubs"}),
        Intent(type="wait_for", target=Target(strategy="css", value=".results")),
        Intent(type="click", target=Target(strategy="text", value="Checkout")),
        Intent(type="type", args={"text": "blue"}),
        Intent(type="extract"),
        Intent(type="extract_table", args={"format": "csv"}),
        Intent(type="sort", args={"field": "price", "direction": "asc"}),
        Intent(type="filter", args={"field": "price", "op": "lte", "value": 100}),
        Intent(type="scroll", args={"direction": "down"}),
        Intent(type="back"),
        Intent(type="forward"),
        Intent(type="select", target=Target(strategy="css", value="#sort"),
               args={"label": "Price Low to High"}),
        Intent(type="upload", args={"fileRef": "resume://ab12cd"},
               target=Target(strategy="css", value="#file")),
        Intent(type="screenshot"),
        Intent(type="summarize"),
        Intent(type="confirm"),
        Intent(type="cancel"),
        Intent(type="unknown"),
    ]
    assert len({i.type for i in intents}) == 19  # the whole enum, no dupes

    results = run_intents(page, tmp_path / "art", intents,
                          uploads_dir=uploads,
                          summarizer=lambda title, body: f"summary: {title}")
    by_type = {r.intent.type: r for r in results}

    # every executable type succeeds; 'unknown' must fail CLOSED (the
    # reference's unsupported branch), with the error isolated to its step
    for t, r in by_type.items():
        if t == "unknown":
            assert not r.ok and "unsupported" in (r.error or "")
        else:
            assert r.ok, f"{t}: {r.error}"

    # spot-check the wire: each intent family drove the protocol it should
    methods = [r["method"] for r in fake.requests]
    assert methods.count("Page.navigate") >= 1
    assert "Input.dispatchKeyEvent" in methods        # search pressed Enter
    assert "Page.getNavigationHistory" in methods     # back/forward
    assert "Page.navigateToHistoryEntry" in methods
    assert "DOM.setFileInputFiles" in methods         # upload
    assert "Page.captureScreenshot" in methods
    evals = [r["params"]["expression"] for r in fake.calls("Runtime.evaluate")]
    assert any("__SCAN__" in e for e in evals)        # analyzer ran over CDP
    assert any("__EXTRACT_CARDS__" in e for e in evals)
    assert any("el.options" in e for e in evals)      # select/sort
    # artifacts landed: extract json + table csv + screenshot png
    art = tmp_path / "art"
    assert list(art.glob("extract_*.json"))
    assert list(art.glob("*.csv"))
    assert by_type["screenshot"].data["path"].endswith(".png")
    # summarize used the injected LLM seam
    assert by_type["summarize"].data["by"] == "llm"
