"""Long-context SP prefill + planner serving path (parallel/longctx.py,
serve/planner.py).

The SP prefill must match the single-device dense forward exactly (it is
the same math, resharded), and the planner must produce grammar-valid plans
across warm extends and SP re-anchors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.llama import (
    LlamaConfig, forward, init_kv_cache, init_params,
)
from tpu_voice_agent.parallel.longctx import llama_sp_prefill, sp_pad_len
from tpu_voice_agent.parallel.ring import sp_mesh

CFG = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                  ffn_dim=128, max_seq_len=128)


@pytest.fixture(scope="module")
def fp32_setup():
    params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    mesh = sp_mesh(4)
    return params, mesh


def test_sp_prefill_matches_dense_forward(fp32_setup):
    params, mesh = fp32_setup
    B, T = 2, 64
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab_size, (B, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    cache = init_kv_cache(CFG, B, T, dtype=jnp.float32)
    ref_logits, ref_cache = forward(params, CFG, tokens, positions, cache)

    last = jnp.full((B,), T - 1, jnp.int32)
    sp_logits, sp_cache = llama_sp_prefill(params, CFG, tokens, mesh, last)

    np.testing.assert_allclose(
        np.asarray(sp_logits), np.asarray(ref_logits[:, -1, :]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(sp_cache["k"]), np.asarray(ref_cache["k"]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(sp_cache["v"]), np.asarray(ref_cache["v"]), rtol=2e-4, atol=2e-4)


def test_sp_prefill_padded_rows(fp32_setup):
    """Rows shorter than the bucket: last_index picks each row's own
    frontier logits; the valid cache prefix matches the dense forward."""
    params, mesh = fp32_setup
    B, T = 2, 64
    n = [50, 37]
    rng = np.random.default_rng(1)
    tok = np.zeros((B, T), dtype=np.int32)
    for b in range(B):
        tok[b, : n[b]] = rng.integers(1, CFG.vocab_size, n[b])
    tokens = jnp.asarray(tok)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    cache = init_kv_cache(CFG, B, T, dtype=jnp.float32)
    ref_logits, _ = forward(params, CFG, tokens, positions, cache)

    sp_logits, _ = llama_sp_prefill(
        params, CFG, tokens, mesh, jnp.asarray([x - 1 for x in n], jnp.int32))
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(sp_logits[b]), np.asarray(ref_logits[b, n[b] - 1]),
            rtol=2e-4, atol=2e-4)


def test_sp_pad_len():
    assert sp_pad_len(1, 4) == 4
    assert sp_pad_len(64, 4) == 64
    assert sp_pad_len(65, 4) == 68
    assert sp_pad_len(10, 4, multiple=8) == 32


@pytest.fixture(scope="module")
def planner():
    from tpu_voice_agent.serve.planner import LongSessionPlanner

    return LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(128, 256, 512),
        extend_buckets=(16, 32), max_new_tokens=32,
    )


def test_planner_cold_start_plan_is_grammar_valid(planner):
    sess = planner.start("user: search for usb hubs\n")
    text, ids = planner.plan(sess)
    assert len(ids) > 0
    assert planner.fsm.walk(ids) >= 0, f"plan left the grammar: {text[:80]}"
    assert sess.anchors == 1
    # the plan joined the transcript
    assert sess.ids[-len(ids):] == ids


def test_planner_warm_extend_then_plan(planner):
    sess = planner.start("user: search for laptops\n")
    pos0 = sess.pos
    planner.plan(sess)
    planner.extend(sess, "result: 24 items\nuser: sort by price\n")
    assert sess.pos > pos0
    assert sess.anchors == 1  # warm path: no re-anchor
    text, ids = planner.plan(sess)
    assert planner.fsm.walk(ids) >= 0


def test_planner_reanchors_when_bucket_overflows(planner):
    sess = planner.start("user: open example.com\n")
    assert sess.cache["k"].shape[2] == 128
    # grow the transcript past bucket 128 (max_new 32 forces early spill)
    for i in range(6):
        planner.extend(sess, f"user: now filter results under {i} dollars please\n")
    assert sess.anchors >= 2
    assert sess.cache["k"].shape[2] >= 256
    text, ids = planner.plan(sess)
    assert planner.fsm.walk(ids) >= 0


def test_planner_plan_requires_frontier(planner):
    sess = planner.start("user: screenshot\n")
    planner.plan(sess)
    with pytest.raises(ValueError, match="extend"):
        planner.plan(sess)
