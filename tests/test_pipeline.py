"""Pipeline-parallel llama forward vs single-device forward (8-dev CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.models.llama import LlamaConfig, forward, init_kv_cache, init_params
from tpu_voice_agent.parallel.pipeline import llama_pp_forward, pp_mesh, stage_params


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=8, n_heads=4,
                      n_kv_heads=2, ffn_dim=128, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
    return cfg, params, tokens


class TestPipelineForward:
    @pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 2), (8, 4)])
    def test_matches_single_device(self, setup, pp, n_micro):
        cfg, params, tokens = setup
        mesh = pp_mesh(pp)
        logits_pp = llama_pp_forward(params, cfg, tokens, mesh, n_micro=n_micro)

        B, T = tokens.shape
        cache = init_kv_cache(cfg, B, T, dtype=jnp.float32)
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        logits_ref, _ = forward(params, cfg, tokens, positions, cache)
        np.testing.assert_allclose(
            np.asarray(logits_pp), np.asarray(logits_ref), atol=2e-4, rtol=2e-4
        )

    def test_rejects_indivisible_layers(self, setup):
        cfg, params, _ = setup
        with pytest.raises(ValueError, match="stages"):
            stage_params(params["layers"], 3)

    def test_rejects_indivisible_batch(self, setup):
        cfg, params, tokens = setup
        with pytest.raises(ValueError, match="microbatch"):
            llama_pp_forward(params, cfg, tokens, pp_mesh(2), n_micro=3)


class TestPipelineCachedDecode:
    """KV-cache-aware PP decode (the 70B planner serving layout): prefill a
    prompt block through the pipeline, then greedy-decode step by step, and
    hold every logit to the single-device cached forward."""

    @pytest.mark.parametrize("pp", [2, 4])
    def test_prefill_plus_decode_matches_single_device(self, setup, pp):
        from tpu_voice_agent.parallel.pipeline import (
            init_pp_cache,
            llama_pp_forward_cached,
        )

        cfg, params, tokens = setup
        mesh = pp_mesh(pp)
        B, T = tokens.shape
        max_len = 32

        # reference: single-device cached forward
        ref_cache = init_kv_cache(cfg, B, max_len, dtype=jnp.float32)
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        ref_logits, ref_cache = forward(params, cfg, tokens, positions, ref_cache,
                                        fresh_block=True)

        pp_cache = init_pp_cache(cfg, mesh, B, max_len, dtype=jnp.float32)
        pp_logits, pp_cache = llama_pp_forward_cached(
            params, pp_cache, cfg, tokens, positions, mesh)
        np.testing.assert_allclose(np.asarray(pp_logits), np.asarray(ref_logits),
                                   atol=2e-4, rtol=2e-4)

        # three greedy decode steps, caches advancing in lockstep
        cur_ref = jnp.argmax(ref_logits[:, -1], axis=-1).astype(jnp.int32)
        cur_pp = jnp.argmax(pp_logits[:, -1], axis=-1).astype(jnp.int32)
        for step in range(3):
            pos = jnp.full((B, 1), T + step, jnp.int32)
            ref_logits, ref_cache = forward(
                params, cfg, cur_ref[:, None], pos, ref_cache)
            pp_logits, pp_cache = llama_pp_forward_cached(
                params, pp_cache, cfg, cur_pp[:, None], pos, mesh)
            np.testing.assert_allclose(np.asarray(pp_logits), np.asarray(ref_logits),
                                       atol=2e-4, rtol=2e-4)
            cur_ref = jnp.argmax(ref_logits[:, -1], axis=-1).astype(jnp.int32)
            cur_pp = jnp.argmax(pp_logits[:, -1], axis=-1).astype(jnp.int32)
            np.testing.assert_array_equal(np.asarray(cur_ref), np.asarray(cur_pp))

    def test_cache_rejects_indivisible_layers(self, setup):
        from tpu_voice_agent.parallel.pipeline import init_pp_cache

        cfg, _, _ = setup
        with pytest.raises(ValueError, match="stages"):
            init_pp_cache(cfg, pp_mesh(3), 2, 16)


class TestPPDecodeEngine:
    """TP×PP served decode (round-2 VERDICT missing #2): the pipelined
    engine must be token-identical to the dense single-device engine under
    the continuous batcher."""

    def test_batcher_output_token_identical_to_dense(self):
        from tpu_voice_agent.models.llama import init_params
        from tpu_voice_agent.parallel.pipeline import pp_tp_mesh
        from tpu_voice_agent.serve import DecodeEngine, PPDecodeEngine
        from tpu_voice_agent.serve.scheduler import ContinuousBatcher
        from tpu_voice_agent.services.prompts import render_prompt

        dense = DecodeEngine(preset="test-tiny", max_len=1024, batch_slots=2,
                             prefill_buckets=(512, 1024), init_weights=False)
        pp = PPDecodeEngine(preset="test-tiny", mesh=pp_tp_mesh(2, 2),
                            max_len=1024, batch_slots=2,
                            prefill_buckets=(512, 1024), init_weights=False)
        # identical float32 weights in both: the pipelined block splits its
        # output contractions over tp (two f32 partial sums + psum), whose
        # ulp-level rounding differences flip greedy argmax ties on RANDOM
        # bf16 weights; f32 keeps the margin far above the split-sum noise
        raw = init_params(dense.cfg, jax.random.PRNGKey(11), dtype=jnp.float32)
        dense.load_params(raw)
        pp.load_params(raw)
        prompts = [
            render_prompt("search for mechanical keyboards", {}),
            render_prompt("go back", {"last_query": "keyboards"}),
        ]
        rd = ContinuousBatcher(dense, chunk_steps=16, max_new_tokens=160).generate_many(prompts)
        rp = ContinuousBatcher(pp, chunk_steps=16, max_new_tokens=160).generate_many(prompts)
        for d, p in zip(rd, rp):
            assert d.error is None and p.error is None
            assert pp.fsm.walk(p.token_ids) >= 0
            assert d.token_ids == p.token_ids, (d.text[:80], p.text[:80])

    def test_pp_generate_is_rejected(self):
        from tpu_voice_agent.parallel.pipeline import pp_tp_mesh
        from tpu_voice_agent.serve import PPDecodeEngine

        eng = PPDecodeEngine(preset="test-tiny", mesh=pp_tp_mesh(2, 1),
                             max_len=512, prefill_buckets=(256,))
        import pytest as _pytest

        with _pytest.raises(ValueError, match="batcher"):
            eng.generate("x")

    def test_pp_prefix_cache_matches_dense(self):
        """The pp engine's staged-layout prefix cache (admission = copy
        prefix KV + suffix-only forward) stays token-identical to the dense
        engine with ITS prefix cache installed."""
        from tpu_voice_agent.models.llama import init_params
        from tpu_voice_agent.parallel.pipeline import pp_tp_mesh
        from tpu_voice_agent.serve import DecodeEngine, PPDecodeEngine
        from tpu_voice_agent.serve.scheduler import ContinuousBatcher
        from tpu_voice_agent.services.brain import install_prompt_prefix
        from tpu_voice_agent.services.prompts import render_prompt

        dense = DecodeEngine(preset="test-tiny", max_len=2048, batch_slots=2,
                             prefill_buckets=(512, 1024), init_weights=False)
        pp = PPDecodeEngine(preset="test-tiny", mesh=pp_tp_mesh(2, 2),
                            max_len=2048, batch_slots=2,
                            prefill_buckets=(512, 1024), init_weights=False)
        raw = init_params(dense.cfg, jax.random.PRNGKey(13), dtype=jnp.float32)
        dense.load_params(raw)
        pp.load_params(raw)
        pd = install_prompt_prefix(dense)
        ppfx = install_prompt_prefix(pp)
        assert ppfx == pd > 0  # the pp engine really caches the prefix now
        prompts = [
            render_prompt("filter under two hundred dollars", {}),
            render_prompt("take a screenshot", {"last_query": "filters"}),
        ]
        rd = ContinuousBatcher(dense, chunk_steps=16, max_new_tokens=120).generate_many(prompts)
        rp = ContinuousBatcher(pp, chunk_steps=16, max_new_tokens=120).generate_many(prompts)
        for d, p in zip(rd, rp):
            assert d.error is None and p.error is None
            assert d.token_ids == p.token_ids, (d.text[:80], p.text[:80])
