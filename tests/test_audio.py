"""Audio frontend: mel spectrogram physics + endpointing behavior."""

import numpy as np

from tpu_voice_agent.audio import EnergyEndpointer, MelConfig, log_mel_spectrogram, mel_filterbank
from tpu_voice_agent.audio.mel import pcm16_to_float


def tone(freq_hz: float, dur_s: float, sr: int = 16_000, amp: float = 0.5) -> np.ndarray:
    t = np.arange(int(dur_s * sr)) / sr
    return (amp * np.sin(2 * np.pi * freq_hz * t)).astype(np.float32)


def test_mel_shape_and_range():
    cfg = MelConfig()
    spec = np.asarray(log_mel_spectrogram(tone(440, 1.0), cfg))
    assert spec.shape == (101, 80)  # 1 s @ hop 160 (+1 centered frame)
    assert np.isfinite(spec).all()
    # whisper normalization keeps values in a small band around [-1, 1]
    assert spec.max() <= 1.5 and spec.min() >= -3.0


def test_mel_tone_energy_lands_in_right_band():
    """A 440 Hz tone must peak in a low mel bin; 4 kHz far higher."""
    cfg = MelConfig()
    lo = np.asarray(log_mel_spectrogram(tone(440, 0.5), cfg)).mean(axis=0)
    hi = np.asarray(log_mel_spectrogram(tone(4000, 0.5), cfg)).mean(axis=0)
    assert lo.argmax() < 20
    assert hi.argmax() > 40
    assert hi.argmax() > lo.argmax()


def test_mel_filterbank_covers_spectrum():
    fb = mel_filterbank(MelConfig())
    assert fb.shape == (201, 80)
    # every mel bin has some support; no all-zero filter
    assert (fb.sum(axis=0) > 0).all()


def test_pcm16_roundtrip():
    samples = (np.array([0, 16384, -16384, 32767], dtype="<i2")).tobytes()
    out = pcm16_to_float(samples)
    np.testing.assert_allclose(out, [0.0, 0.5, -0.5, 0.99997], atol=1e-4)


def test_endpointer_finalizes_after_trailing_silence():
    ep = EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=100)
    speech = tone(300, 0.5, amp=0.3)
    silence = np.zeros(16_000 // 2, dtype=np.float32)
    assert not ep.feed(speech)  # still talking
    assert ep.in_speech
    assert ep.feed(silence)  # utterance closed
    assert not ep.in_speech


def test_endpointer_ignores_short_blips():
    ep = EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=300)
    blip = tone(300, 0.05, amp=0.3)  # 50 ms < min_speech
    silence = np.zeros(16_000, dtype=np.float32)
    ep.feed(blip)
    assert not ep.feed(silence)
