"""Checkpoint I/O: orbax round-trip (incl. sharded restore) + HF import."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.ckpt import llama_from_hf_state, restore_params, save_params
from tpu_voice_agent.models.llama import LlamaConfig, forward, init_kv_cache, init_params

CFG = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                  ffn_dim=64, max_seq_len=32)


class TestOrbaxRoundTrip:
    def test_save_restore(self, tmp_path):
        params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
        save_params(tmp_path / "ck", params)
        back = restore_params(tmp_path / "ck")
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, back,
        )

    def test_sharded_restore(self, tmp_path):
        from tpu_voice_agent.parallel.mesh import make_mesh, param_shardings

        params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
        save_params(tmp_path / "ck", params)
        mesh = make_mesh(dp=1, tp=2)
        sh = param_shardings(mesh, CFG.n_kv_heads)
        like = jax.eval_shape(lambda: params)
        back = restore_params(tmp_path / "ck", shardings=sh, params_like=like)
        assert "tp" in str(back["layers"]["wq"].sharding)
        np.testing.assert_array_equal(np.asarray(back["embed"]), np.asarray(params["embed"]))

    def test_restore_with_shardings_requires_like(self, tmp_path):
        params = init_params(CFG, jax.random.PRNGKey(0))
        save_params(tmp_path / "ck", params)
        with pytest.raises(ValueError, match="params_like"):
            restore_params(tmp_path / "ck", shardings={})


def _fake_hf_state(cfg: LlamaConfig, tied: bool, rng) -> dict:
    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    st = {
        "model.embed_tokens.weight": rng.standard_normal((cfg.vocab_size, d), np.float32),
        "model.norm.weight": np.ones(d, np.float32),
    }
    if not tied:
        st["lm_head.weight"] = rng.standard_normal((cfg.vocab_size, d)).astype(np.float32)
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        st[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        st[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
        st[p + "self_attn.q_proj.weight"] = rng.standard_normal((cfg.n_heads * hd, d)).astype(np.float32)
        st[p + "self_attn.k_proj.weight"] = rng.standard_normal((cfg.n_kv_heads * hd, d)).astype(np.float32)
        st[p + "self_attn.v_proj.weight"] = rng.standard_normal((cfg.n_kv_heads * hd, d)).astype(np.float32)
        st[p + "self_attn.o_proj.weight"] = rng.standard_normal((d, cfg.n_heads * hd)).astype(np.float32)
        st[p + "mlp.gate_proj.weight"] = rng.standard_normal((f, d)).astype(np.float32)
        st[p + "mlp.up_proj.weight"] = rng.standard_normal((f, d)).astype(np.float32)
        st[p + "mlp.down_proj.weight"] = rng.standard_normal((d, f)).astype(np.float32)
    return st


class TestHFImport:
    @pytest.mark.parametrize("tied", [False, True])
    def test_import_shapes_and_forward(self, tied):
        rng = np.random.default_rng(0)
        params = llama_from_hf_state(_fake_hf_state(CFG, tied, rng), CFG, dtype=jnp.float32)
        ref = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
        assert jax.tree.structure(params) == jax.tree.structure(ref)
        jax.tree.map(lambda a, b: (_ for _ in ()).throw(AssertionError())
                     if a.shape != b.shape else None, params, ref)
        cache = init_kv_cache(CFG, 1, 16, dtype=jnp.float32)
        toks = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        pos = jnp.arange(4, dtype=jnp.int32)[None]
        logits, _ = forward(params, CFG, toks, pos, cache)
        assert np.isfinite(np.asarray(logits)).all()

    def test_transpose_correctness(self):
        """q_proj row i of HF == column i of our wq (transposed layout)."""
        rng = np.random.default_rng(1)
        st = _fake_hf_state(CFG, False, rng)
        params = llama_from_hf_state(st, CFG, dtype=jnp.float32)
        hf_q0 = st["model.layers.0.self_attn.q_proj.weight"]
        np.testing.assert_array_equal(np.asarray(params["layers"]["wq"][0]), hf_q0.T)

    def test_missing_tensor_raises(self):
        rng = np.random.default_rng(2)
        st = _fake_hf_state(CFG, False, rng)
        del st["model.layers.1.mlp.up_proj.weight"]
        with pytest.raises(KeyError, match="up_proj"):
            llama_from_hf_state(st, CFG)

    def test_shape_mismatch_raises(self):
        rng = np.random.default_rng(3)
        st = _fake_hf_state(CFG, False, rng)
        st["model.norm.weight"] = np.ones(7, np.float32)
        with pytest.raises(ValueError, match="shape"):
            llama_from_hf_state(st, CFG)

    def test_safetensors_dir_round_trip(self, tmp_path):
        from safetensors.numpy import save_file

        rng = np.random.default_rng(4)
        st = _fake_hf_state(CFG, False, rng)
        save_file(st, str(tmp_path / "model.safetensors"))
        params = llama_from_hf_state(str(tmp_path), CFG, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(params["embed"]), st["model.embed_tokens.weight"]
        )
