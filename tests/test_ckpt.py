"""Checkpoint I/O: orbax round-trip (incl. sharded restore) + HF import."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.ckpt import llama_from_hf_state, restore_params, save_params
from tpu_voice_agent.models.llama import LlamaConfig, forward, init_kv_cache, init_params

CFG = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                  ffn_dim=64, max_seq_len=32)


class TestOrbaxRoundTrip:
    def test_save_restore(self, tmp_path):
        params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
        save_params(tmp_path / "ck", params)
        back = restore_params(tmp_path / "ck")
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, back,
        )

    def test_sharded_restore(self, tmp_path):
        from tpu_voice_agent.parallel.mesh import make_mesh, param_shardings

        params = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
        save_params(tmp_path / "ck", params)
        mesh = make_mesh(dp=1, tp=2)
        sh = param_shardings(mesh, CFG.n_kv_heads)
        like = jax.eval_shape(lambda: params)
        back = restore_params(tmp_path / "ck", shardings=sh, params_like=like)
        assert "tp" in str(back["layers"]["wq"].sharding)
        np.testing.assert_array_equal(np.asarray(back["embed"]), np.asarray(params["embed"]))

    def test_restore_with_shardings_requires_like(self, tmp_path):
        params = init_params(CFG, jax.random.PRNGKey(0))
        save_params(tmp_path / "ck", params)
        with pytest.raises(ValueError, match="params_like"):
            restore_params(tmp_path / "ck", shardings={})


def _fake_hf_state(cfg: LlamaConfig, tied: bool, rng) -> dict:
    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    st = {
        "model.embed_tokens.weight": rng.standard_normal((cfg.vocab_size, d), np.float32),
        "model.norm.weight": np.ones(d, np.float32),
    }
    if not tied:
        st["lm_head.weight"] = rng.standard_normal((cfg.vocab_size, d)).astype(np.float32)
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        st[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        st[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
        st[p + "self_attn.q_proj.weight"] = rng.standard_normal((cfg.n_heads * hd, d)).astype(np.float32)
        st[p + "self_attn.k_proj.weight"] = rng.standard_normal((cfg.n_kv_heads * hd, d)).astype(np.float32)
        st[p + "self_attn.v_proj.weight"] = rng.standard_normal((cfg.n_kv_heads * hd, d)).astype(np.float32)
        st[p + "self_attn.o_proj.weight"] = rng.standard_normal((d, cfg.n_heads * hd)).astype(np.float32)
        st[p + "mlp.gate_proj.weight"] = rng.standard_normal((f, d)).astype(np.float32)
        st[p + "mlp.up_proj.weight"] = rng.standard_normal((f, d)).astype(np.float32)
        st[p + "mlp.down_proj.weight"] = rng.standard_normal((d, f)).astype(np.float32)
    return st


class TestHFImport:
    @pytest.mark.parametrize("tied", [False, True])
    def test_import_shapes_and_forward(self, tied):
        rng = np.random.default_rng(0)
        params = llama_from_hf_state(_fake_hf_state(CFG, tied, rng), CFG, dtype=jnp.float32)
        ref = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
        assert jax.tree.structure(params) == jax.tree.structure(ref)
        jax.tree.map(lambda a, b: (_ for _ in ()).throw(AssertionError())
                     if a.shape != b.shape else None, params, ref)
        cache = init_kv_cache(CFG, 1, 16, dtype=jnp.float32)
        toks = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        pos = jnp.arange(4, dtype=jnp.int32)[None]
        logits, _ = forward(params, CFG, toks, pos, cache)
        assert np.isfinite(np.asarray(logits)).all()

    def test_transpose_correctness(self):
        """q_proj row i of HF == column i of our wq (transposed layout)."""
        rng = np.random.default_rng(1)
        st = _fake_hf_state(CFG, False, rng)
        params = llama_from_hf_state(st, CFG, dtype=jnp.float32)
        hf_q0 = st["model.layers.0.self_attn.q_proj.weight"]
        np.testing.assert_array_equal(np.asarray(params["layers"]["wq"][0]), hf_q0.T)

    def test_missing_tensor_raises(self):
        rng = np.random.default_rng(2)
        st = _fake_hf_state(CFG, False, rng)
        del st["model.layers.1.mlp.up_proj.weight"]
        with pytest.raises(KeyError, match="up_proj"):
            llama_from_hf_state(st, CFG)

    def test_shape_mismatch_raises(self):
        rng = np.random.default_rng(3)
        st = _fake_hf_state(CFG, False, rng)
        st["model.norm.weight"] = np.ones(7, np.float32)
        with pytest.raises(ValueError, match="shape"):
            llama_from_hf_state(st, CFG)

    def test_safetensors_dir_round_trip(self, tmp_path):
        from safetensors.numpy import save_file

        rng = np.random.default_rng(4)
        st = _fake_hf_state(CFG, False, rng)
        save_file(st, str(tmp_path / "model.safetensors"))
        params = llama_from_hf_state(str(tmp_path), CFG, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(params["embed"]), st["model.embed_tokens.weight"]
        )


# ---------------------------------------------------------------- whisper/vl


def _tree_shapes(t, prefix=""):
    if isinstance(t, dict):
        out = {}
        for k, v in t.items():
            out.update(_tree_shapes(v, f"{prefix}{k}."))
        return out
    return {prefix[:-1]: tuple(t.shape)}


class TestWhisperImport:
    def test_synthetic_roundtrip_matches_init_tree(self):
        from tpu_voice_agent.ckpt.hf_import import whisper_from_hf_state
        from tpu_voice_agent.models.whisper import PRESETS, init_params

        cfg = PRESETS["whisper-test"]
        rng = np.random.default_rng(0)
        d, f = cfg.d_model, cfg.ffn_dim
        st = {}

        def lin(name, o, i, bias=True):
            st[name + ".weight"] = rng.standard_normal((o, i)).astype(np.float32)
            if bias:
                st[name + ".bias"] = rng.standard_normal((o,)).astype(np.float32)

        def norm(name, n):
            st[name + ".weight"] = np.ones(n, np.float32)
            st[name + ".bias"] = np.zeros(n, np.float32)

        st["model.encoder.conv1.weight"] = rng.standard_normal((d, cfg.n_mels, 3)).astype(np.float32)
        st["model.encoder.conv1.bias"] = np.zeros(d, np.float32)
        st["model.encoder.conv2.weight"] = rng.standard_normal((d, d, 3)).astype(np.float32)
        st["model.encoder.conv2.bias"] = np.zeros(d, np.float32)
        norm("model.encoder.layer_norm", d)
        for n in range(cfg.enc_layers):
            p = f"model.encoder.layers.{n}"
            norm(p + ".self_attn_layer_norm", d)
            norm(p + ".final_layer_norm", d)
            for proj in ("q_proj", "v_proj", "out_proj"):
                lin(f"{p}.self_attn.{proj}", d, d)
            lin(f"{p}.self_attn.k_proj", d, d, bias=False)
            lin(p + ".fc1", f, d)
            lin(p + ".fc2", d, f)
        st["model.decoder.embed_tokens.weight"] = rng.standard_normal(
            (cfg.vocab_size, d)).astype(np.float32)
        st["model.decoder.embed_positions.weight"] = rng.standard_normal(
            (cfg.max_text_len, d)).astype(np.float32)
        norm("model.decoder.layer_norm", d)
        for n in range(cfg.dec_layers):
            p = f"model.decoder.layers.{n}"
            for ln_name in (".self_attn_layer_norm", ".encoder_attn_layer_norm",
                            ".final_layer_norm"):
                norm(p + ln_name, d)
            for attn in (".self_attn", ".encoder_attn"):
                for proj in ("q_proj", "v_proj", "out_proj"):
                    lin(f"{p}{attn}.{proj}", d, d)
                lin(f"{p}{attn}.k_proj", d, d, bias=False)
            lin(p + ".fc1", f, d)
            lin(p + ".fc2", d, f)

        params = whisper_from_hf_state(st, cfg, dtype=jnp.float32)
        want = _tree_shapes(init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32))
        got = _tree_shapes(params)
        # imported tree must slot exactly where the random-init tree goes —
        # same keys, same shapes (a misnamed leaf KeyErrors at serving time)
        assert set(got) == set(want), set(got) ^ set(want)
        for k, shape in got.items():
            assert want[k] == shape, k

        from tpu_voice_agent.models.whisper import (
            compute_cross_kv, decoder_forward, encoder_forward, init_self_cache,
        )

        mel = jnp.asarray(rng.standard_normal((1, 100, cfg.n_mels)), jnp.float32)
        enc = encoder_forward(params, cfg, mel)
        assert np.isfinite(np.asarray(enc)).all()

        cross = compute_cross_kv(params, cfg, enc)
        cache = init_self_cache(cfg, 1, dtype=jnp.float32)
        toks = jnp.asarray([[3, 4, 5]], jnp.int32)
        pos = jnp.arange(3, dtype=jnp.int32)[None]
        enc_mask = jnp.ones((1, enc.shape[1]), bool)
        logits, _ = decoder_forward(params, cfg, toks, pos, cache, cross, enc_mask)
        assert logits.shape == (1, 3, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()


class TestQwen2VLImport:
    def test_synthetic_roundtrip_forward(self):
        from tpu_voice_agent.ckpt.hf_import import qwen2vl_from_hf_state
        from tpu_voice_agent.models.qwen2vl import (
            PRESETS, forward_embeds, init_kv_cache, text_positions3, vision_forward,
        )

        cfg = PRESETS["qwen2vl-test"]
        v = cfg.vision
        rng = np.random.default_rng(1)
        st = {}
        dv, fv = v.d_model, v.ffn_dim
        st["visual.patch_embed.proj.weight"] = rng.standard_normal(
            (dv, 3, 2, v.patch_size, v.patch_size)).astype(np.float32)
        for n in range(v.n_layers):
            p = f"visual.blocks.{n}."
            st[p + "norm1.weight"] = np.ones(dv, np.float32)
            st[p + "norm1.bias"] = np.zeros(dv, np.float32)
            st[p + "norm2.weight"] = np.ones(dv, np.float32)
            st[p + "norm2.bias"] = np.zeros(dv, np.float32)
            st[p + "attn.qkv.weight"] = rng.standard_normal((3 * dv, dv)).astype(np.float32)
            st[p + "attn.qkv.bias"] = np.zeros(3 * dv, np.float32)
            st[p + "attn.proj.weight"] = rng.standard_normal((dv, dv)).astype(np.float32)
            st[p + "attn.proj.bias"] = np.zeros(dv, np.float32)
            st[p + "mlp.fc1.weight"] = rng.standard_normal((fv, dv)).astype(np.float32)
            st[p + "mlp.fc1.bias"] = np.zeros(fv, np.float32)
            st[p + "mlp.fc2.weight"] = rng.standard_normal((dv, fv)).astype(np.float32)
            st[p + "mlp.fc2.bias"] = np.zeros(dv, np.float32)
        mi = v.merge_size * v.merge_size * dv
        st["visual.merger.ln_q.weight"] = np.ones(dv, np.float32)
        st["visual.merger.ln_q.bias"] = np.zeros(dv, np.float32)
        st["visual.merger.mlp.0.weight"] = rng.standard_normal((mi, mi)).astype(np.float32)
        st["visual.merger.mlp.0.bias"] = np.zeros(mi, np.float32)
        st["visual.merger.mlp.2.weight"] = rng.standard_normal((cfg.dim, mi)).astype(np.float32)
        st["visual.merger.mlp.2.bias"] = np.zeros(cfg.dim, np.float32)

        d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
        nq, nkv = cfg.n_heads, cfg.n_kv_heads
        st["model.embed_tokens.weight"] = rng.standard_normal(
            (cfg.vocab_size, d)).astype(np.float32)
        st["model.norm.weight"] = np.ones(d, np.float32)
        for n in range(cfg.n_layers):
            p = f"model.layers.{n}."
            st[p + "input_layernorm.weight"] = np.ones(d, np.float32)
            st[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
            for proj, o in (("q_proj", nq * hd), ("k_proj", nkv * hd), ("v_proj", nkv * hd)):
                st[p + f"self_attn.{proj}.weight"] = rng.standard_normal((o, d)).astype(np.float32)
                st[p + f"self_attn.{proj}.bias"] = np.zeros(o, np.float32)
            st[p + "self_attn.o_proj.weight"] = rng.standard_normal((d, nq * hd)).astype(np.float32)
            st[p + "mlp.gate_proj.weight"] = rng.standard_normal((f, d)).astype(np.float32)
            st[p + "mlp.up_proj.weight"] = rng.standard_normal((f, d)).astype(np.float32)
            st[p + "mlp.down_proj.weight"] = rng.standard_normal((d, f)).astype(np.float32)
        # no lm_head -> tied embeddings path

        params = qwen2vl_from_hf_state(st, cfg, dtype=jnp.float32)
        img = jnp.asarray(rng.random((1, v.img_size, v.img_size, 3)), jnp.float32)
        vis = vision_forward(params["vision"], v, img)
        assert vis.shape == (1, v.n_tokens, cfg.dim)

        T = 4
        emb = params["embed"][jnp.asarray(rng.integers(3, cfg.vocab_size, (1, T)), jnp.int32)]
        cache = init_kv_cache(cfg, 1, 16, dtype=jnp.float32)
        logits, _ = forward_embeds(params, cfg, emb, jnp.arange(T, dtype=jnp.int32)[None],
                                   text_positions3(0, T), cache)
        assert logits.shape == (1, T, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()
