"""Zero-egress NEURAL end-to-end: every model in the loop is an in-tree
TRAINED network (VERDICT round-4 next #5 — the committed checkpoints had
only ever been scored as disconnected bench rows).

Path under test, one WS, three real services on real sockets:
acoustic-font audio -> voice WS -> whisper-tiny checkpoint STT (real
StreamingSTT incremental/endpoint path) -> distilled intent checkpoint
through the grammar-constrained engine (EngineParser has no rule fallback
by construction; a decode failure is a 4xx, never a silent rule parse) ->
fake-page executor actions. Matches (hermetically) the reference's only
e2e claim: the manual Deepgram+OpenAI run in README.md:197.
"""

import asyncio
import json

import aiohttp
import numpy as np
import pytest

from tpu_voice_agent.audio.endpoint import EnergyEndpointer
from tpu_voice_agent.models.llama import LlamaConfig
from tpu_voice_agent.models.whisper import WhisperConfig
from tpu_voice_agent.serve.stt import StreamingSTT
from tpu_voice_agent.services.brain import build_app as build_brain
from tpu_voice_agent.services.executor import SessionManager, build_app as build_executor
from tpu_voice_agent.services.executor.page import FakePage
from tpu_voice_agent.services.voice import VoiceConfig, build_app as build_voice
from tpu_voice_agent.train import distill
from tests.http_helper import AppServer
from tests.test_voice import ws_session

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def neural_ckpts():
    intent = distill.load_ckpt("checkpoints", distill.INTENT_CKPT, LlamaConfig)
    whisper = distill.load_ckpt("checkpoints", distill.WHISPER_CKPT,
                                WhisperConfig)
    if intent is None or whisper is None:
        pytest.skip("trained checkpoints not present (run "
                    "python -m tpu_voice_agent.train.make_tiny_ckpts)")
    return intent, whisper


def pcm16_frames(audio: np.ndarray, frame_ms: int = 60):
    """Float audio -> 60 ms PCM16 frames, exactly like the web client."""
    pcm = (np.clip(audio, -1, 1) * 32767).astype("<i2").tobytes()
    step = 16_000 * frame_ms // 1000 * 2
    return [("binary", pcm[i:i + step]) for i in range(0, len(pcm), step)]


def ws_collect_until(voice_url, inbound, done, timeout_s=120.0):
    """Like tests.test_voice.ws_session but with a predicate over the
    accumulated event list (ws_session can only wait on type presence,
    not counts)."""

    async def run():
        events = []
        async with aiohttp.ClientSession() as sess:
            async with sess.ws_connect(
                    voice_url.replace("http", "ws") + "/stream") as ws:
                for kind, payload in inbound:
                    if kind == "binary":
                        await ws.send_bytes(payload)
                    else:
                        await ws.send_json(payload)
                end = asyncio.get_event_loop().time() + timeout_s
                while asyncio.get_event_loop().time() < end:
                    try:
                        msg = await ws.receive(timeout=1.0)
                    except asyncio.TimeoutError:
                        continue
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        break
                    events.append(json.loads(msg.data))
                    if done(events):
                        break
        return events

    return asyncio.run(run())


def test_neural_pipeline_all_three_services(tmp_path, neural_ckpts):
    (icfg, iparams), (wcfg, wparams) = neural_ckpts

    whisper_eng = distill.whisper_engine_from(wcfg, wparams)

    def stt_factory():
        return StreamingSTT(
            whisper_eng,
            endpointer=EnergyEndpointer(spec_silence_ms=120),
            early_close_ms=240.0,
        )

    brain = AppServer(
        build_brain(distill.intent_engine_from(icfg, iparams))).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=stt_factory))
    ).__enter__()
    try:
        utterance = "search for red shoes"
        audio = np.concatenate([
            distill.render_speech(utterance),
            np.zeros(16_000, dtype=np.float32),  # endpoint closes in here
        ])
        events = ws_session(voice.url, pcm16_frames(audio),
                            ["execution_result"], timeout_s=120)
        by_type = {}
        for ev in events:
            by_type.setdefault(ev["type"], []).append(ev)

        # the trained whisper read the acoustic font exactly
        finals = [e["text"] for e in by_type.get("transcript_final", [])]
        assert finals == [utterance], events

        # the distilled parser produced the semantically correct intent
        intents = by_type["intent"][0]["data"]["intents"]
        assert intents[0]["type"] == "search"
        assert intents[0]["args"]["query"] == "red shoes"

        # ...and the executor actually ran it against the fake page
        result = by_type["execution_result"][0]["data"]
        assert result["results"], result
        assert all(r.get("ok") for r in result["results"]), result
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)


def test_neural_pipeline_second_utterance_and_screenshot(tmp_path, neural_ckpts):
    """Two utterances over one WS: session context threads through, and a
    screenshot intent produces an artifact — all through trained weights."""
    (icfg, iparams), (wcfg, wparams) = neural_ckpts
    whisper_eng = distill.whisper_engine_from(wcfg, wparams)

    def stt_factory():
        return StreamingSTT(
            whisper_eng,
            endpointer=EnergyEndpointer(spec_silence_ms=120),
            early_close_ms=240.0,
        )

    brain = AppServer(
        build_brain(distill.intent_engine_from(icfg, iparams))).__enter__()
    manager = SessionManager(
        page_factory=FakePage.demo,
        artifacts_root=str(tmp_path / "art"),
        uploads_dir=str(tmp_path / "up"),
    )
    executor = AppServer(build_executor(manager)).__enter__()
    voice = AppServer(
        build_voice(VoiceConfig(brain_url=brain.url, executor_url=executor.url,
                                stt_factory=stt_factory))
    ).__enter__()
    try:
        sil = np.zeros(16_000, dtype=np.float32)
        audio = np.concatenate([
            distill.render_speech("scroll down"), sil,
            distill.render_speech("take a screenshot"), sil,
        ])
        events = ws_collect_until(
            voice.url, pcm16_frames(audio),
            lambda evs: sum(e["type"] == "execution_result" for e in evs) >= 2,
            timeout_s=180)
        finals = [e["text"] for e in events if e["type"] == "transcript_final"]
        assert finals == ["scroll down", "take a screenshot"], finals
        types = [e["data"]["intents"][0]["type"] for e in events
                 if e["type"] == "intent"]
        assert types == ["scroll", "screenshot"]
        results = [e for e in events if e["type"] == "execution_result"]
        assert len(results) == 2
    finally:
        for srv in (voice, executor, brain):
            srv.__exit__(None, None, None)
