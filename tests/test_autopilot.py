"""Fleet autopilot (ISSUE 16): closed-loop elastic capacity.

Fast-tier coverage for tpu_voice_agent/services/autopilot.py and the ring
machinery it leans on:

- scale-up joins pre-warmed: spawn -> joining -> pack/adopt via the
  ``serve.handoff`` wire -> admit, with ``adopted_tokens`` recorded and
  fresh gray/pressure state on the admitted member
- respawn hygiene (satellite 1): ``add_member`` at a reused key and
  ``admit`` both produce clean gray/outlier/pressure carry-forwards
- JOINING members are probe-invisible: failing probes never eject them,
  ok probes never auto-admit them cold
- the manual-drain-vs-join slot race: an operator ``POST /admin/drain``
  landing mid-pre-warm always wins — the controller aborts the join and
  never admits the claimed member
- join-stall containment (satellite 2's controller half): a pre-warm
  that outlives ``AUTOPILOT_JOIN_TIMEOUT_S`` retires the stuck member
  and retries WITHOUT dropping the target or admitting cold
- the ``replica_join_stall`` chaos point wiring in the real brain app:
  the adopt POST stalls for CHAOS_HANG_S on the armed event, exactly once
- starved signals hold: a controller that cannot read a single fresh
  time-series sample moves nothing, in either direction
- cooldown blocks are decisions: an earned streak inside the cooldown
  window lands a ``hold``/``cooldown`` entry and a counter, not a commit
- scale-down is zero-drop: drain -> proactive warm ship -> repoint ->
  eject at inflight==0 -> retire, with the shipped session still
  answering 200 on its new home
- the STT tier rides the same band controller through ``resize``
- the race hammer (satellite 3): ramp decisions racing manual drains,
  probe ejects and gray demotions on fake replicas — zero lost sessions,
  cooldown spacing holds in the decision log, the manual drain's slot is
  never re-admitted
"""

import asyncio
import json
import os
import threading
import time
import urllib.error
import urllib.request

from aiohttp import web

from tests.http_helper import AppServer
from tpu_voice_agent.services.autopilot import AutopilotController
from tpu_voice_agent.services.brain import RuleBasedParser
from tpu_voice_agent.services.brain import build_app as build_brain
from tpu_voice_agent.services.router import BrainRouter, _weight
from tpu_voice_agent.services.router import build_app as build_router
from tpu_voice_agent.utils import chaos as chaos_mod
from tpu_voice_agent.utils import get_metrics


def _get(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _post(url: str, body: dict, timeout: float = 20.0):
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, {}


def _post_raw(url: str, data: bytes, timeout: float = 10.0):
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/octet-stream"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def _counters() -> dict:
    return dict(get_metrics().snapshot()["counters"])


def _delta(before: dict, name: str) -> float:
    return _counters().get(name, 0.0) - before.get(name, 0.0)


# ------------------------------------------------------------------ fixtures


def _fake_member(name: str, log: list, controls: dict):
    """Brain-contract stand-in (the test_fleet fake plus the handoff
    wire): ``controls["parse_ms"]`` drives the busy signal its
    /debug/timeseries reports (busy = parse_ms x 5 req/s / 1000);
    ``controls["pack_tokens"]`` is what its handoff pack claims to carry;
    ``controls["adopt_stall_s"]`` wedges the adopt POST (the join-stall
    window); ``controls["mute_ts"]`` blinds the telemetry surface."""
    rule = RuleBasedParser()
    seq = {"n": 0}

    async def parse(req: web.Request) -> web.Response:
        body = await req.json()
        log.append((name, body.get("session_id")))
        resp = rule.parse(body["text"], body.get("context") or {})
        return web.json_response(json.loads(resp.model_dump_json()))

    async def health(_req: web.Request) -> web.Response:
        return web.json_response({"ok": True, "service": "brain"})

    async def timeseries(_req: web.Request) -> web.Response:
        if controls.get("mute_ts"):
            raise web.HTTPNotFound()
        # one fresh sample per scrape: deterministic windows
        s = {"seq": seq["n"], "t_s": time.time(), "dt_s": 0.1,
             "gauges": {}, "rates": {},
             "hist": {"brain.parse": {"ms_per": controls.get("parse_ms", 10.0),
                                      "per_s": 5.0}}}
        seq["n"] += 1
        return web.json_response({
            "service": "brain", "interval_s": 0.1, "max_samples": 240,
            "now_s": time.time(), "next_seq": seq["n"], "samples": [s]})

    async def handoff_pack(req: web.Request) -> web.Response:
        payload = json.dumps({"from": name, "sid": req.match_info["sid"],
                              "tokens": int(controls.get("pack_tokens", 7))})
        return web.Response(body=payload.encode(),
                            content_type="application/octet-stream")

    async def handoff_adopt(req: web.Request) -> web.Response:
        raw = await req.read()
        stall = float(controls.get("adopt_stall_s", 0.0))
        if stall > 0:
            await asyncio.sleep(stall)
        try:
            tokens = int(json.loads(raw.decode()).get("tokens", 0))
        except (ValueError, AttributeError):
            tokens = 0
        return web.json_response({"ok": True, "adopted_tokens": tokens})

    app = web.Application()
    app.router.add_post("/parse", parse)
    app.router.add_get("/health", health)
    app.router.add_get("/debug/timeseries", timeseries)
    app.router.add_get("/admin/handoff/{sid}", handoff_pack)
    app.router.add_post("/admin/handoff", handoff_adopt)
    return app


def _ring(n: int, **router_kw):
    logs = [[] for _ in range(n)]
    controls = [{"parse_ms": 10.0} for _ in range(n)]
    servers = [AppServer(_fake_member(f"r{i}", logs[i], controls[i])).__enter__()
               for i in range(n)]
    router_kw.setdefault("probe_s", 0.1)
    router_kw.setdefault("fleet_windows", 2)
    router_kw.setdefault("fleet_min_peers", 3)
    robj = BrainRouter([s.url for s in servers], **router_kw)
    router = AppServer(build_router(robj)).__enter__()
    return router, servers, logs, controls, robj


def _teardown(router, servers):
    router.__exit__(None, None, None)
    for s in servers:
        try:
            s.__exit__(None, None, None)
        except Exception:
            pass


def _sid_homed_on(robj: BrainRouter, idx: int, prefix: str) -> str:
    urls = [r.url for r in robj.replicas]
    for i in range(10_000):
        sid = f"{prefix}{i}"
        if max(range(len(urls)), key=lambda j: _weight(urls[j], sid)) == idx:
            return sid
    raise AssertionError("no session hashed onto the target replica")


def _wait(pred, timeout_s: float = 10.0, step_s: float = 0.05):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(step_s)
    return False


class _Spawner:
    """The duck-typed spawner over in-process fake members: each spawn
    boots a fresh AppServer whose controls start from ``template`` (so a
    test can pre-arm an adopt stall on the NEXT member to join)."""

    def __init__(self, template: dict | None = None):
        self.template = dict(template or {})
        self.servers: dict[str, AppServer] = {}
        self.logs: dict[str, list] = {}
        self.controls: dict[str, dict] = {}
        self.spawns = 0
        self.retired: list[str] = []

    async def spawn(self) -> str:
        loop = asyncio.get_running_loop()
        log: list = []
        controls = dict(self.template)
        name = f"spawn{self.spawns}"
        self.spawns += 1
        srv = await loop.run_in_executor(
            None,
            lambda: AppServer(_fake_member(name, log, controls)).__enter__())
        self.servers[srv.url] = srv
        self.logs[srv.url] = log
        self.controls[srv.url] = controls
        return srv.url

    async def retire(self, url: str) -> None:
        self.retired.append(url)
        srv = self.servers.pop(url, None)
        if srv is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, lambda: srv.__exit__(None, None, None))

    def close(self) -> None:
        for srv in list(self.servers.values()):
            try:
                srv.__exit__(None, None, None)
            except Exception:
                pass
        self.servers.clear()


def _mk_ap(robj, spawner, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("interval_s", 0.1)
    kw.setdefault("target_util", 0.5)
    kw.setdefault("up_windows", 2)
    kw.setdefault("down_windows", 3)
    kw.setdefault("cooldown_s", 0.05)
    kw.setdefault("join_timeout_s", 5.0)
    kw.setdefault("forecast_lead_s", 0.3)
    return AutopilotController(robj, spawner, **kw)


def _tick(router_srv, ap, timeout_s: float = 30.0) -> dict:
    return asyncio.run_coroutine_threadsafe(
        ap.tick_once(), router_srv._loop).result(timeout_s)


def _on_loop(router_srv, coro, timeout_s: float = 30.0):
    return asyncio.run_coroutine_threadsafe(coro, router_srv._loop).result(
        timeout_s)


# ------------------------------------------------------------ join pipeline


def test_scale_up_prewarms_then_admits_fresh():
    router, servers, logs, controls, robj = _ring(1)
    spawner = _Spawner()
    try:
        # a sticky session gives the pre-warm a donor; the donor's pack
        # payload is what the joiner adopts
        controls[0]["pack_tokens"] = 9
        st, _ = _post(router.url + "/parse",
                      {"text": "scroll down", "session_id": "warmsrc",
                       "context": {}})
        assert st == 200
        ap = _mk_ap(robj, spawner)
        c0 = _counters()
        controls[0]["parse_ms"] = 300.0  # busy 1.5 -> desired 3 of max 3
        _tick(router, ap)                # streak 1: no commit yet
        desc = _tick(router, ap)         # streak 2: commit +1, join inline
        assert desc["brain"]["target"] == 2
        assert desc["brain"]["actual"] == 2, desc
        join = [d for d in ap.decisions if d["action"] == "join"]
        assert join and join[-1]["reason"] == "prewarmed"
        assert join[-1]["adopted_tokens"] == 9
        assert _delta(c0, "autopilot.scale_ups") == 1
        assert _delta(c0, "autopilot.joins_prewarmed") == 1
        assert _delta(c0, "autopilot.joins_cold") == 0
        # the admitted member carries zero fleet-state (satellite 1)
        new = next(r for r in robj.replicas if r.url in spawner.servers)
        assert new.state == "up" and not new.gray
        assert new.pressure == 0.0 and new.gray_streak == 0
    finally:
        _teardown(router, servers)
        spawner.close()


def test_respawn_and_admit_reset_gray_and_pressure():
    router, servers, logs, controls, robj = _ring(3)
    try:
        # drift r0 into gray against its peers
        controls[0]["parse_ms"] = 300.0
        assert _wait(lambda: robj.replicas[0].gray, 10.0), "never went gray"
        victim = robj.replicas[0]
        victim.pressure = 0.8  # a saturation carry-forward to shed

        async def respawn():
            old_idx = victim.idx
            robj.start_drain(victim)
            robj.remove_member(victim.url)
            fresh = robj.add_member(victim.url, joining=True)
            return old_idx, fresh

        old_idx, fresh = _on_loop(router, respawn())
        # a reused key is a brand-new member: no verdict survives the
        # process it described (satellite 1)
        assert fresh.idx != old_idx
        assert not fresh.gray and fresh.pressure == 0.0
        assert fresh.outlier_score == 0.0 and fresh.signals == {}
        # and admit() itself wipes state stamped while joining
        fresh.pressure = 0.5
        fresh.gray_streak = 2
        _on_loop(router, asyncio.sleep(0))  # settle the prober's slice
        robj.admit(fresh)
        assert fresh.state == "up" and fresh.pressure == 0.0
        assert fresh.gray_streak == 0
    finally:
        _teardown(router, servers)


def test_joining_member_is_probe_invisible():
    router, servers, logs, controls, robj = _ring(
        1, probe_s=0.05, probe_fails=2)
    try:
        async def add_dead():
            return robj.add_member("http://127.0.0.1:9", joining=True)

        r = _on_loop(router, add_dead())
        # every probe of the dead url fails, yet probe_fails x probe_s
        # later the member is still the controller's: joining, not down
        time.sleep(0.5)
        assert r.state == "joining"
        assert robj._by_url.get(r.url) is r
        # and it never took placement: an anonymous parse routes around it
        st, _ = _post(router.url + "/parse", {"text": "scroll down",
                                              "context": {}})
        assert st == 200 and logs[0]
        _on_loop(router, asyncio.sleep(0))
        robj.remove_member(r.url)
    finally:
        _teardown(router, servers)


def test_manual_drain_wins_join_race():
    router, servers, logs, controls, robj = _ring(1)
    spawner = _Spawner({"adopt_stall_s": 0.4, "pack_tokens": 5})
    try:
        st, _ = _post(router.url + "/parse",
                      {"text": "scroll down", "session_id": "racewarm",
                       "context": {}})
        assert st == 200
        ap = _mk_ap(robj, spawner, down_windows=100)
        c0 = _counters()

        async def drive() -> str:
            ap.target = 2  # reconcile must join on the next tick
            t = asyncio.ensure_future(ap.tick_once())
            loop = asyncio.get_running_loop()
            end = loop.time() + 5.0
            while not any(r.state == "joining" for r in robj.replicas):
                assert loop.time() < end, "join never started"
                await asyncio.sleep(0.01)
            j = next(r for r in robj.replicas if r.state == "joining")
            # the operator's POST /admin/drain lands mid-pre-warm
            assert robj.start_drain(j)
            await t
            return j.url

        claimed = _on_loop(router, drive(), 15.0)
        aborted = [d for d in ap.decisions if d["action"] == "join_aborted"]
        assert aborted and aborted[-1]["reason"] == "manual_drain"
        assert aborted[-1]["replica"] == claimed
        assert _delta(c0, "autopilot.joins_prewarmed") == 0
        assert _delta(c0, "autopilot.joins_cold") == 0
        # the next tick retires the claimed member and joins a NEW one —
        # the drained slot is never recycled into capacity
        assert _wait(lambda: (_tick(router, ap)["brain"]["actual"] == 2
                              and claimed not in robj._by_url), 15.0)
        assert claimed in spawner.retired
        joins = [d for d in ap.decisions if d["action"] == "join"]
        assert joins and all(d["replica"] != claimed for d in joins)
    finally:
        _teardown(router, servers)
        spawner.close()


def test_join_stall_times_out_retires_and_retries():
    router, servers, logs, controls, robj = _ring(1)
    spawner = _Spawner({"adopt_stall_s": 3.0})
    try:
        controls[0]["pack_tokens"] = 6  # the donor side of the pre-warm
        st, _ = _post(router.url + "/parse",
                      {"text": "scroll down", "session_id": "stallwarm",
                       "context": {}})
        assert st == 200
        ap = _mk_ap(robj, spawner, down_windows=100, join_timeout_s=0.4)
        c0 = _counters()

        async def arm():
            ap.target = 2

        _on_loop(router, arm())
        desc = _tick(router, ap)  # the join wedges in the adopt POST
        assert _delta(c0, "autopilot.join_timeouts") == 1
        assert _delta(c0, "autopilot.joins_cold") == 0, \
            "a stalled join must never be admitted cold"
        aborted = [d for d in ap.decisions if d["action"] == "join_aborted"]
        assert aborted and aborted[-1]["reason"] == "join_timeout"
        stuck = aborted[-1]["replica"]
        assert stuck not in robj._by_url and stuck in spawner.retired
        assert ap.target == 2, "a stuck join must not drop the target"
        assert desc["brain"]["actual"] == 1
        # next tick retries against a healthy joiner and pre-warms it
        spawner.template["adopt_stall_s"] = 0.0
        desc = _tick(router, ap)
        assert desc["brain"]["actual"] == 2
        joins = [d for d in ap.decisions if d["action"] == "join"]
        assert joins and joins[-1]["reason"] == "prewarmed"
        assert joins[-1]["adopted_tokens"] == 6
    finally:
        _teardown(router, servers)
        spawner.close()


def test_chaos_replica_join_stall_point_fires_in_brain():
    """The chaos wiring itself (satellite 2's brain half): the armed
    event's adopt POST stalls for CHAOS_HANG_S, exactly once, and counts
    under ``chaos.replica_join_stall``. The full engine-backed drill
    (timeout -> retire -> retry -> warm admit) runs in bench_autopilot."""
    os.environ["CHAOS_HANG_S"] = "0.4"
    chaos_mod.configure("replica_join_stall@1", seed=7)
    try:
        with AppServer(build_brain(RuleBasedParser())) as srv:
            c0 = _counters()
            t0 = time.monotonic()
            _post_raw(srv.url + "/admin/handoff", b"{}")
            stalled = time.monotonic() - t0
            assert stalled >= 0.35, f"stall never injected ({stalled:.3f}s)"
            assert _delta(c0, "chaos.replica_join_stall") == 1
            t0 = time.monotonic()
            _post_raw(srv.url + "/admin/handoff", b"{}")
            assert time.monotonic() - t0 < 0.3, "@1 fired more than once"
            assert _delta(c0, "chaos.replica_join_stall") == 1
    finally:
        chaos_mod.reset()
        os.environ.pop("CHAOS_HANG_S", None)


# --------------------------------------------------------- band discipline


def test_starved_signals_hold_everything():
    router, servers, logs, controls, robj = _ring(1)
    spawner = _Spawner()
    try:
        controls[0]["mute_ts"] = True    # telemetry plane dark
        controls[0]["parse_ms"] = 500.0  # real load the controller can't see
        ap = _mk_ap(robj, spawner)
        c0 = _counters()
        for _ in range(4):
            desc = _tick(router, ap)
        assert _delta(c0, "autopilot.holds_starved") == 4
        assert desc["brain"]["target"] == 1
        assert spawner.spawns == 0, "a blind controller must not act"
        holds = [d for d in ap.decisions if d["action"] == "hold"]
        assert holds and holds[-1]["reason"] == "starved"
    finally:
        _teardown(router, servers)
        spawner.close()


def test_cooldown_block_is_counted_and_logged():
    router, servers, logs, controls, robj = _ring(1)
    spawner = _Spawner()
    try:
        ap = _mk_ap(robj, spawner, up_windows=1, cooldown_s=60.0)
        c0 = _counters()
        controls[0]["parse_ms"] = 300.0
        _tick(router, ap)  # commits +1 and arms the cooldown
        assert ap.target == 2
        desc = _tick(router, ap)  # streak earned again, cooldown holds it
        assert ap.target == 2
        assert _delta(c0, "autopilot.cooldown_blocks") >= 1
        holds = [d for d in ap.decisions
                 if d["action"] == "hold" and d["reason"] == "cooldown"]
        assert holds and holds[-1]["cooldown_remaining_s"] > 0
        assert desc["brain"]["cooldown_remaining_s"] > 50.0
    finally:
        _teardown(router, servers)
        spawner.close()


def test_scale_down_ships_warm_and_drops_nothing():
    router, servers, logs, controls, robj = _ring(3)
    spawner = _Spawner()
    try:
        # two sessions each on r0/r1, one on r2: r2 is the cheapest exit
        sids = [_sid_homed_on(robj, 0, "a"), _sid_homed_on(robj, 0, "b"),
                _sid_homed_on(robj, 1, "c"), _sid_homed_on(robj, 1, "d"),
                _sid_homed_on(robj, 2, "v")]
        for sid in sids:
            st, _ = _post(router.url + "/parse",
                          {"text": "scroll down", "session_id": sid,
                           "context": {}})
            assert st == 200
        victim_sid, victim_url = sids[-1], robj.replicas[2].url
        ap = _mk_ap(robj, spawner, min_replicas=2, max_replicas=3,
                    down_windows=2)
        assert ap.target == 3
        c0 = _counters()
        _tick(router, ap)         # idle fleet: down streak 1
        _tick(router, ap)         # streak 2: commit -1, drain + ship inline
        assert ap.target == 2
        assert _delta(c0, "autopilot.scale_downs") == 1
        drains = [d for d in ap.decisions if d["action"] == "drain"]
        assert drains and drains[-1]["replica"] == victim_url
        # the sticky session was shipped warm and repointed before eject
        assert _delta(c0, "autopilot.sessions_shipped") == 1
        new_home = robj._sessions[victim_sid]
        assert new_home != victim_url
        # zero-drop: the shipped session still answers, on its new home
        st, _ = _post(router.url + "/parse",
                      {"text": "go back", "session_id": victim_sid,
                       "context": {}})
        assert st == 200
        served = next(i for i, s in enumerate(servers) if s.url == new_home)
        assert any(e[1] == victim_sid for e in logs[served])
        # the retirement tail: out of the ring only at inflight == 0
        assert _wait(lambda: (_tick(router, ap)
                              and victim_url not in robj._by_url), 10.0)
        assert _delta(c0, "autopilot.retired") == 1
        assert victim_url in spawner.retired
        assert sum(1 for r in robj.replicas if r.state == "up") == 2
    finally:
        _teardown(router, servers)
        spawner.close()


def test_stt_tier_rides_the_band():
    class _FakeSTT:
        def __init__(self):
            self.pressure = 0.0

        def servable(self):
            return True

    class _FakeTier:
        def __init__(self, n):
            self.replicas = [_FakeSTT() for _ in range(n)]
            self.resizes: list[int] = []

        def resize(self, n):
            self.resizes.append(n)
            while len(self.replicas) < n:
                self.replicas.append(_FakeSTT())
            del self.replicas[n:]

    router, servers, logs, controls, robj = _ring(1)
    spawner = _Spawner()
    tier = _FakeTier(1)
    try:
        ap = _mk_ap(robj, spawner, stt_tier=tier, up_windows=2,
                    down_windows=2, cooldown_s=0.05)
        for r in tier.replicas:
            r.pressure = 0.9  # sustained over target_util
        _tick(router, ap)
        _tick(router, ap)
        assert ap.stt_target == 2 and tier.resizes == [2]
        ups = [d for d in ap.decisions
               if d["tier"] == "stt" and d["action"] == "scale_up"]
        assert ups and ups[-1]["reason"] == "pressure"
        for r in tier.replicas:
            r.pressure = 0.05  # deep under the band
        time.sleep(0.1)  # let the cooldown lapse
        _tick(router, ap)
        _tick(router, ap)
        assert ap.stt_target == 1 and tier.resizes == [2, 1]
        assert len(tier.replicas) == 1
    finally:
        _teardown(router, servers)
        spawner.close()


# ------------------------------------------------------------- race hammer


def test_autopilot_race_hammer():
    """Satellite 3: the control loop at full tick rate racing live
    traffic, a manual drain, a gray demotion and a cold replica kill.
    Invariants: every client parse answers 200 (zero lost sessions),
    committed scale actions respect the cooldown spacing in the decision
    log, and the operator's drained slot is never readmitted."""
    router, servers, logs, controls, robj = _ring(3, probe_s=0.1,
                                                  probe_fails=2)
    spawner = _Spawner({"pack_tokens": 4})
    ap = AutopilotController(robj, spawner, min_replicas=1, max_replicas=6,
                             interval_s=0.1, target_util=0.5, up_windows=2,
                             down_windows=3, cooldown_s=0.6,
                             join_timeout_s=5.0, forecast_lead_s=0.3)
    _on_loop(router, ap.start())
    statuses: list = []
    stop = threading.Event()

    def client():
        i = 0
        while not stop.is_set():
            sid = f"ham{i % 6}"
            try:
                st, _ = _post(router.url + "/parse",
                              {"text": "scroll down", "session_id": sid,
                               "context": {}}, timeout=10.0)
            except Exception as e:  # a transport-level loss IS a lost turn
                st = f"exc:{type(e).__name__}"
            statuses.append(st)
            i += 1
            time.sleep(0.03)

    th = threading.Thread(target=client, daemon=True)
    th.start()
    drained_url = None
    try:
        # phase 1: sustained high load — the controller ramps, and every
        # streak earned inside a cooldown window lands a hold/cooldown
        for c in controls:
            c["parse_ms"] = 400.0
        time.sleep(1.6)
        # phase 2: the operator drains an up member mid-ramp
        victim = next(r for r in robj.replicas if r.state == "up")
        drained_url = victim.url
        st, body = _post(router.url + "/admin/drain",
                         {"replica": drained_url})
        assert st == 200 and body["ok"]
        # phase 3: a seed member drifts into gray under the same ramp
        seed_urls = [s.url for s in servers]
        gray_url = next(u for u in seed_urls
                        if u != drained_url and u in robj._by_url
                        and robj._by_url[u].state == "up")
        controls[seed_urls.index(gray_url)]["parse_ms"] = 4000.0
        assert _wait(lambda: (gray_url not in robj._by_url
                              or robj._by_url[gray_url].gray), 5.0), \
            "outlier never demoted"
        # phase 4: a spawned member dies cold — probes must eject it while
        # its sessions fail over
        for url, srv in list(spawner.servers.items()):
            spawner.servers.pop(url)
            srv.__exit__(None, None, None)
            break
        time.sleep(0.8)
        # phase 5: the load collapses — the controller shrinks back
        for c in controls:
            c["parse_ms"] = 10.0
        time.sleep(2.0)
    finally:
        stop.set()
        th.join(10.0)
        _on_loop(router, ap.stop(), 15.0)
        _teardown(router, servers)
        spawner.close()
    # zero lost sessions: every turn of every session answered 200 —
    # through the ramp, the drain, the gray demotion and the kill
    assert statuses and all(st == 200 for st in statuses), \
        [st for st in statuses if st != 200][:5]
    # the loop both grew and shrank capacity under the hammer
    acts = [d for d in ap.decisions if d["tier"] == "brain"]
    commits = [d for d in acts if d["action"] in ("scale_up", "scale_down")]
    assert any(d["action"] == "scale_up" for d in commits)
    assert any(d["action"] == "join" for d in acts)
    # cooldown honored: consecutive commits are spaced by >= cooldown_s
    for a, b in zip(commits, commits[1:]):
        assert b["t"] - a["t"] >= 0.6 - 0.1, (a, b)
    assert any(d["action"] == "hold" and d["reason"] == "cooldown"
               for d in acts), "no cooldown block ever logged"
    # the manual drain always wins its slot: never readmitted, never the
    # target of a later join
    r = robj._by_url.get(drained_url)
    assert r is None or r.state in ("draining", "drained")
    assert all(d.get("replica") != drained_url
               for d in acts if d["action"] == "join")
