"""Multi-host bring-up (parallel/multihost.py) — single-process paths.

Real DCN needs multiple processes; what CAN be pinned here: the no-op
single-process contract, the ICI-first mesh layout rules, and that the
resulting mesh drives the same sharded forward as make_mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.parallel.multihost import (
    init_multihost, multihost_mesh, process_info,
)


def test_init_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_multihost() is False  # no coordinator -> clean no-op


def test_mesh_layout_and_forward():
    mesh = multihost_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "tp": 4}
    # tp groups must be host-contiguous: all same process here, but the
    # ordering contract (process_index-major) still holds
    procs = [d.process_index for d in mesh.devices.flatten()]
    assert procs == sorted(procs)

    from tpu_voice_agent.models.llama import (
        LlamaConfig, forward, init_kv_cache, init_params,
    )
    from tpu_voice_agent.parallel.mesh import (
        default_rules, kv_cache_shardings, param_shardings,
    )

    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=4, n_kv_heads=4,
                      ffn_dim=64, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    sh = jax.device_put(params, param_shardings(mesh, cfg.n_kv_heads))
    cache = jax.device_put(init_kv_cache(cfg, 2, 32, dtype=jnp.float32),
                           kv_cache_shardings(mesh, cfg.n_kv_heads))
    toks = jnp.zeros((2, 4), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32)[None], (2, 4))
    logits, _ = forward(sh, cfg, toks, pos, cache,
                        default_rules(mesh, cfg.n_kv_heads, cfg.n_heads))
    assert np.isfinite(np.asarray(logits)).all()


def test_mesh_too_big_raises():
    with pytest.raises(ValueError, match="needs"):
        multihost_mesh(dp=4, tp=4)


def test_uneven_hosts_straddling_tp_group_refused():
    """{6, 4} local devices, dp=2 tp=4: the second tp group would span both
    hosts — the guard must catch it (a min-per-host check would not)."""
    from types import SimpleNamespace

    fakes = [SimpleNamespace(process_index=0, id=i) for i in range(6)] + [
        SimpleNamespace(process_index=1, id=i) for i in range(4)
    ]
    with pytest.raises(ValueError, match="straddles"):
        multihost_mesh(dp=2, tp=4, devices=fakes)


def test_process_info_shape():
    info = process_info()
    assert info["process_count"] == 1
    assert info["global_devices"] == 8
