"""Multi-host bring-up (parallel/multihost.py) — single-process paths.

Real DCN needs multiple processes; what CAN be pinned here: the no-op
single-process contract, the ICI-first mesh layout rules, and that the
resulting mesh drives the same sharded forward as make_mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.parallel.multihost import (
    init_multihost, multihost_mesh, process_info,
)


def test_init_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_multihost() is False  # no coordinator -> clean no-op


def test_mesh_layout_and_forward():
    mesh = multihost_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "tp": 4}
    # tp groups must be host-contiguous: all same process here, but the
    # ordering contract (process_index-major) still holds
    procs = [d.process_index for d in mesh.devices.flatten()]
    assert procs == sorted(procs)

    from tpu_voice_agent.models.llama import (
        LlamaConfig, forward, init_kv_cache, init_params,
    )
    from tpu_voice_agent.parallel.mesh import (
        default_rules, kv_cache_shardings, param_shardings,
    )

    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=4, n_kv_heads=4,
                      ffn_dim=64, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    sh = jax.device_put(params, param_shardings(mesh, cfg.n_kv_heads))
    cache = jax.device_put(init_kv_cache(cfg, 2, 32, dtype=jnp.float32),
                           kv_cache_shardings(mesh, cfg.n_kv_heads))
    toks = jnp.zeros((2, 4), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32)[None], (2, 4))
    logits, _ = forward(sh, cfg, toks, pos, cache,
                        default_rules(mesh, cfg.n_kv_heads, cfg.n_heads))
    assert np.isfinite(np.asarray(logits)).all()


def test_mesh_too_big_raises():
    with pytest.raises(ValueError, match="needs"):
        multihost_mesh(dp=4, tp=4)


def test_uneven_hosts_straddling_tp_group_refused():
    """{6, 4} local devices, dp=2 tp=4: the second tp group would span both
    hosts — the guard must catch it (a min-per-host check would not)."""
    from types import SimpleNamespace

    fakes = [SimpleNamespace(process_index=0, id=i) for i in range(6)] + [
        SimpleNamespace(process_index=1, id=i) for i in range(4)
    ]
    with pytest.raises(ValueError, match="straddles"):
        multihost_mesh(dp=2, tp=4, devices=fakes)


def test_mesh_equivalent_to_make_mesh_single_host():
    """Single-host degeneracy (ISSUE 20): with every device on one process,
    multihost_mesh must produce the SAME device grid as parallel.mesh's
    make_mesh — same axis names, same device at every (dp, tp) coordinate —
    so call sites can swap one for the other without resharding anything."""
    from tpu_voice_agent.parallel.mesh import make_mesh

    mh = multihost_mesh(dp=2, tp=4)
    base = make_mesh(dp=2, tp=4)
    assert mh.shape == base.shape
    assert mh.axis_names == base.axis_names
    assert [d.id for d in mh.devices.flatten()] == \
        [d.id for d in base.devices.flatten()]


def test_mesh_dp_over_hosts_tp_inside_host_layout():
    """The layout math with 2 fake hosts x 4 devices: dp must cross hosts
    (one dp row per host, host-pure) and tp must stay inside a host, even
    when the input device list arrives shuffled."""
    import random

    class _Dev:  # hashable (Mesh keys on device identity; SimpleNamespace
        def __init__(self, process_index, id):  # defines __eq__ and is not)
            self.process_index, self.id = process_index, id

    fakes = [_Dev(h, i) for h in range(2) for i in range(4)]
    random.Random(7).shuffle(fakes)  # ordering must come from the sort
    mesh = multihost_mesh(dp=2, tp=4, devices=fakes)
    assert mesh.shape == {"dp": 2, "tp": 4}
    grid = mesh.devices
    # each tp row is host-pure, and dp row h holds host h's devices
    for h, row in enumerate(grid):
        assert {d.process_index for d in row} == {h}
        assert [d.id for d in row] == [0, 1, 2, 3]  # local order kept
    # dp=4 tp=2 also works: two tp groups per host, still host-pure
    grid2 = multihost_mesh(dp=4, tp=2, devices=fakes).devices
    for row in grid2:
        assert len({d.process_index for d in row}) == 1
    assert [r[0].process_index for r in grid2] == [0, 0, 1, 1]


def test_process_info_shape():
    info = process_info()
    assert info["process_count"] == 1
    assert info["global_devices"] == 8


@pytest.mark.slow
def test_two_process_dcn_collective(tmp_path):
    """THE missing bring-up test (round-3 VERDICT next #5): two real OS
    processes join one jax.distributed job over a local coordinator (the
    DCN path), build the host-aware multihost_mesh, and run an actual
    cross-process collective whose result both processes must agree on.
    Closes the only 'partial' rows in the round-3 coverage table."""
    import pathlib
    import socket
    import subprocess
    import sys
    import textwrap

    ROOT = pathlib.Path(__file__).resolve().parents[1]

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    child = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        # strip any inherited device-count flag, then pin 4 per process
        flags = " ".join(f for f in flags.split()
                         if "host_platform_device_count" not in f)
        os.environ["XLA_FLAGS"] = (flags +
            " --xla_force_host_platform_device_count=4").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {repr(str(ROOT))})
        pid = int(sys.argv[1])
        from tpu_voice_agent.parallel.multihost import (
            init_multihost, multihost_mesh, process_info)
        assert init_multihost("127.0.0.1:{port}", 2, pid) is True
        info = process_info()
        assert info["process_count"] == 2, info
        assert info["global_devices"] == 8, info
        assert info["local_devices"] == 4, info

        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = multihost_mesh(dp=2, tp=4)
        # tp groups must stay inside one host: this process's devices form
        # whole rows of the (dp, tp) array
        for row in mesh.devices:
            assert len({{d.process_index for d in row}}) == 1

        # one real cross-process collective: a (8, 4) global array sharded
        # (dp, tp); each process supplies its local (4, 4) block with value
        # process_id + 1, and a shard_map psum over BOTH axes must see the
        # other host's data: total = 16 * 1 + 16 * 2 = 48.
        local = np.full((4, 4), pid + 1, np.float32)
        sharding = NamedSharding(mesh, P("dp", "tp"))
        garr = jax.make_array_from_process_local_data(sharding, local, (8, 4))
        total = jax.jit(jax.shard_map(
            lambda x: jax.lax.psum(jnp.sum(x), ("dp", "tp")),
            mesh=mesh, in_specs=P("dp", "tp"), out_specs=P(),
        ))(garr)
        got = float(np.asarray(total))
        assert got == 48.0, got
        print(f"OK {{pid}} total={{got}}", flush=True)
    """)

    import os

    script = tmp_path / "dcn_child.py"
    script.write_text(child)
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env.pop("JAX_PROCESS_ID", None)
    procs = [
        subprocess.Popen([sys.executable, str(script), str(i)], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("two-process DCN job hung (coordinator never formed?)")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
    assert any("OK 0 total=48.0" in out for _, out, _ in outs)
    assert any("OK 1 total=48.0" in out for _, out, _ in outs)
