"""Opt-in real-browser smoke test for the in-tree CDP driver.

Round-1 VERDICT weak #7: ``cdp.py`` had zero tests against a real browser
(this image has no Chrome, so everything runs FakePage). This suite is the
protocol-rot canary: point ``CDP_URL`` at any running Chrome's devtools
endpoint (``chrome --remote-debugging-port=9222`` ->
``CDP_URL=http://127.0.0.1:9222``) and it drives navigate / evaluate /
fill / click / screenshot through the real wire protocol. Skipped cleanly
when CDP_URL is unset — mirroring the reference's seam of a cloud browser
behind an env knob (apps/executor/src/session.ts:35-44).
"""

import os

import pytest

CDP_URL = os.environ.get("CDP_URL")

pytestmark = pytest.mark.skipif(
    not CDP_URL, reason="CDP_URL not set (opt-in real-browser smoke test)")

# a data: URL keeps the smoke test hermetic — no network egress needed
PAGE = (
    "data:text/html,<title>cdp-smoke</title>"
    "<input id='q' placeholder='Search'>"
    "<button id='go' onclick=\"document.title='clicked'\">Go</button>"
)


@pytest.fixture(scope="module")
def page():
    from tpu_voice_agent.services.executor.cdp import CDPPage

    p = CDPPage.connect(cdp_url=CDP_URL)
    yield p
    p.close()


def test_navigate_and_evaluate(page):
    page.goto(PAGE)
    assert page.evaluate("document.title") == "cdp-smoke"


def test_fill_and_read_back(page):
    page.goto(PAGE)
    page.fill("#q", "usb hubs")
    assert page.evaluate("document.querySelector('#q').value") == "usb hubs"


def test_click_selector_fires_handler(page):
    page.goto(PAGE)
    page.click_selector("#go")
    assert page.evaluate("document.title") == "clicked"


def test_screenshot_writes_png(page, tmp_path):
    page.goto(PAGE)
    out = tmp_path / "shot.png"
    page.screenshot(str(out), full_page=False)
    data = out.read_bytes()
    assert data[:8] == b"\x89PNG\r\n\x1a\n" and len(data) > 100


def test_run_intents_against_real_chrome(page, tmp_path):
    """The executor interpreter end-to-end on a live browser: the same
    entry the /execute service drives (actions.run_intents)."""
    from tpu_voice_agent.schemas.intents import Intent
    from tpu_voice_agent.services.executor.actions import run_intents

    intents = [
        Intent(type="navigate", args={"url": PAGE}),
        Intent(type="type", target={"strategy": "css", "value": "#q"},
               args={"text": "smoke"}),
        Intent(type="screenshot"),
    ]
    results = run_intents(page, str(tmp_path), intents)
    assert all(r.ok for r in results), [r.error for r in results]
