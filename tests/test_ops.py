"""Pallas kernels vs their pure-jnp reference twins (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.ops import (
    attention_reference,
    decode_attention,
    decode_attention_reference,
    flash_attention,
    masked_argmax,
    masked_argmax_reference,
)


def _qkv(key, B, T, S, nq, nkv, hd, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, nq, hd), dtype)
    k = jax.random.normal(kk, (B, S, nkv, hd), dtype)
    v = jax.random.normal(kv, (B, S, nkv, hd), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 64, 8, 4, 32)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_kv_len_masks_padded_keys(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), 1, 32, 48, 4, 4, 16)
        out = flash_attention(q, k, v, causal=False, kv_len=40, block_q=16, block_k=16)
        ref = attention_reference(q[:, :, :, :], k[:, :40], v[:, :40], causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_ragged_blocks(self):
        # T, S not multiples of the block sizes
        q, k, v = _qkv(jax.random.PRNGKey(2), 1, 50, 50, 4, 2, 32)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_bf16_io(self):
        q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 32, 4, 4, 32, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        assert out.dtype == jnp.bfloat16
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
        )


class TestDecodeAttention:
    def test_matches_reference_ragged_lengths(self):
        key = jax.random.PRNGKey(4)
        B, S, nq, nkv, hd = 3, 64, 8, 2, 32
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, nq, hd))
        kc = jax.random.normal(kk, (B, S, nkv, hd))
        vc = jax.random.normal(kv, (B, S, nkv, hd))
        kv_len = jnp.asarray([1, 17, 64], jnp.int32)  # per-row frontiers
        out = decode_attention(q, kc, vc, kv_len, block_k=16)
        ref = decode_attention_reference(q, kc, vc, kv_len)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_single_row_single_key(self):
        q = jnp.ones((1, 4, 16))
        kc = jnp.ones((1, 32, 4, 16))
        vc = jnp.full((1, 32, 4, 16), 2.0)
        out = decode_attention(q, kc, vc, jnp.asarray([1], jnp.int32), block_k=16)
        # only one valid key -> output == its value
        np.testing.assert_allclose(np.asarray(out), 2.0, atol=1e-6)


class TestMaskedArgmax:
    def test_matches_reference(self):
        key = jax.random.PRNGKey(5)
        B, V, S = 4, 300, 7
        logits = jax.random.normal(key, (B, V))
        mask = jax.random.bernoulli(jax.random.PRNGKey(6), 0.3, (S, V))
        mask = mask.at[:, 0].set(True)  # no all-masked state
        state = jnp.asarray([0, 3, 6, 2], jnp.int32)
        out = masked_argmax(logits, state, mask)
        ref = masked_argmax_reference(logits, state, mask)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_mask_forces_choice(self):
        logits = jnp.asarray([[0.0, 100.0, 1.0, 2.0]])
        mask = jnp.asarray([[True, False, False, True]])  # best unmasked is idx 3
        out = masked_argmax(logits, jnp.zeros((1,), jnp.int32), mask)
        assert int(out[0]) == 3

    def test_engine_fsm_tables(self, tiny_engine):
        """The real intent-grammar tables round-trip through the kernel."""
        eng = tiny_engine
        V = eng.tokenizer.vocab_size
        logits = jax.random.normal(jax.random.PRNGKey(7), (2, V))
        state = jnp.asarray([eng.fsm.start, eng.fsm.start], jnp.int32)
        out = masked_argmax(logits, state, eng.tables.dense_mask)
        ref = masked_argmax_reference(logits, state, eng.tables.dense_mask)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_decode_block_attention_matches_reference():
    """(B, T) query blocks against per-row frontiers: parity with the jnp
    twin incl. intra-block causality, idle rows parked at slot 0, and an
    odd cache length exercising the pad path."""
    from tpu_voice_agent.ops import (
        decode_block_attention,
        decode_block_attention_reference,
    )

    B, T, nq, nkv, hd, S = 4, 5, 8, 4, 32, 96  # 96 % 64 != 0 -> pad path
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, T, nq, hd), jnp.float32)
    kc = jax.random.normal(ks[1], (B, S, nkv, hd), jnp.float32)
    vc = jax.random.normal(ks[2], (B, S, nkv, hd), jnp.float32)
    q_pos = jnp.asarray([
        [10, 11, 12, 13, 14],   # mid-sequence chain
        [0, 0, 0, 0, 0],        # idle row parked at slot 0
        [90, 91, 92, 93, 94],   # frontier near the odd end
        [3, 4, 5, 5, 5],        # truncated chain duplicates its tail
    ], jnp.int32)
    ref = decode_block_attention_reference(q, kc, vc, q_pos)
    out = decode_block_attention(q, kc, vc, q_pos, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_decode_block_attention_layer_matches_plain():
    """The stacked-cache layer variant must equal the plain kernel on the
    selected plane (scalar-prefetched layer indexing)."""
    from tpu_voice_agent.ops import (
        decode_block_attention,
        decode_block_attention_layer,
    )

    L, B, T, nq, nkv, hd, S = 3, 2, 4, 8, 4, 32, 128
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(ks[0], (B, T, nq, hd), jnp.float32)
    kc = jax.random.normal(ks[1], (L, B, S, nkv, hd), jnp.float32)
    vc = jax.random.normal(ks[2], (L, B, S, nkv, hd), jnp.float32)
    q_pos = jnp.asarray([[20, 21, 22, 23], [7, 8, 9, 9]], jnp.int32)
    for li in range(L):
        plain = decode_block_attention(q, kc[li], vc[li], q_pos, block_k=64)
        stacked = decode_block_attention_layer(q, kc, vc, q_pos,
                                               jnp.int32(li), block_k=64)
        np.testing.assert_allclose(np.asarray(stacked), np.asarray(plain),
                                   rtol=1e-6, atol=1e-6)
