"""Quantized paged KV (KV_QUANT=int8|int4) + fused grammar-mask→sample
decode tail (ISSUE 12) — FAST tier.

The storage contract (ops/kvquant.py): the paged pool stores per-(position,
kv_head) scaled int8 (or packed int4) values, quantized ONCE at write time
(deterministic rowwise math shared by the in-forward scatter and the host
prefix/tail scatter), with the bf16 scale planes pool-indexed by block id —
so radix sharing, spec rollback, and the warm-restart reserve path all
carry scales with the block for free. ``KV_QUANT`` unset keeps the bf16
pool byte-identical, differentially tested like ``RADIX_ENABLE`` /
``SPEC_ENABLE`` before it.

The accuracy contract is the golden differential (evals/golden.py
``kv_quant_differential``): int8 token-identical on the golden set with the
distilled checkpoint, int4 held to a pinned intent-type-agreement floor,
both grammar-valid always.

The fused decode tail (ops/grammar_mask.py): grammar mask + argmax + FSM
advance in ONE Pallas call (``masked_argmax_advance``), and the spec
verify block's per-position masked argmax in one call
(``masked_argmax_block``) — parity-tested against the XLA reference path
they replace.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.grammar.fsm import fsm_advance
from tpu_voice_agent.serve import DecodeEngine, PagedDecodeEngine, SpecConfig
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import (
    SessionTranscripts,
    install_prompt_prefix,
)
from tpu_voice_agent.services.prompts import render_prompt
from tpu_voice_agent.utils import chaos, get_metrics
from tpu_voice_agent.utils.costmodel import decode_step_bytes
from tpu_voice_agent.utils.hbmledger import (
    engine_hbm_plan,
    measure_hbm,
)

BUCKETS = (128, 256, 512, 1024, 2048)
PROMPT_TEXTS = ["search for usb hubs", "scroll down"]
MAXTOK = 48


def _paged(kv_quant, radix=False, spec=None, **kw):
    eng = PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=2,
        prefill_buckets=BUCKETS, radix_enable=radix, spec=spec,
        kv_quant=kv_quant, **kw)
    install_prompt_prefix(eng)
    return eng


def _run(eng, prompts, max_new=MAXTOK):
    return ContinuousBatcher(eng, chunk_steps=8,
                             max_new_tokens=max_new).generate_many(prompts)


@pytest.fixture(scope="module")
def prompts():
    return [render_prompt(t, {}) for t in PROMPT_TEXTS]


@pytest.fixture(scope="module")
def eng_int8():
    return _paged("int8")


@pytest.fixture(scope="module")
def int8_baseline(eng_int8, prompts):
    res = _run(eng_int8, prompts)
    assert all(r.error is None for r in res)
    return res


# ------------------------------------------------------------ value layout


def test_kvquant_roundtrip_and_pack():
    from tpu_voice_agent.ops.kvquant import (
        dequantize_kv,
        pack_int4,
        quantize_kv,
        unpack_int4,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 2, 32))
    for tier, tol in (("int8", 2.5e-2), ("int4", 3.5e-1)):
        q, s = quantize_kv(x, tier)
        assert q.dtype == jnp.int8 and s.dtype == jnp.bfloat16
        assert s.shape == x.shape[:-1]
        xd = dequantize_kv(q, s, tier)
        assert float(jnp.max(jnp.abs(xd.astype(jnp.float32) - x))) < tol
        # determinism: the same fp rows always produce the same stored
        # bytes (what makes prefill-written and decode-written KV bitwise
        # comparable at the differential suites' level)
        q2, s2 = quantize_kv(x, tier)
        assert bool((q2 == q).all()) and bool((s2 == s).all())
    # int4 packing: low nibble dims [0, hd/2), high nibble [hd/2, hd),
    # arithmetic-shift decode sign-extends exactly
    q8 = jnp.clip(jax.random.randint(jax.random.PRNGKey(1), (4, 8), -7, 8),
                  -7, 7).astype(jnp.int8)
    assert (unpack_int4(pack_int4(q8)) == q8).all()
    # all-zero rows quantize through the guarded scale, not a NaN
    q, s = quantize_kv(jnp.zeros((2, 4)), "int8")
    assert bool((q == 0).all()) and bool(jnp.isfinite(s.astype(jnp.float32)).all())


def test_kv_block_bytes_capacity_ratios():
    """The tentpole's capacity claim as pure accounting: at serving head
    dims a fixed HBM budget holds >= 1.9x the blocks under int8 and
    >= 3.5x under int4 (scale overhead included — the ratio is NOT a clean
    2x/4x and the ledger must use the honest number)."""
    from tpu_voice_agent.ops.kvquant import kv_block_bytes, kv_quant_bits

    assert (kv_quant_bits(None), kv_quant_bits("int8"),
            kv_quant_bits("int4")) == (16, 8, 4)
    for hd in (64, 128):
        off = kv_block_bytes(22, 128, 4, hd, None)
        i8 = kv_block_bytes(22, 128, 4, hd, "int8")
        i4 = kv_block_bytes(22, 128, 4, hd, "int4")
        assert off == 2 * 22 * 128 * 4 * hd * 2
        budget = 512 * off  # a 512-block bf16 budget
        assert (budget // i8) / (budget // off) >= 1.9
        assert (budget // i4) / (budget // off) >= 3.5


def test_decode_step_bytes_cpu_harness_proxy():
    """The decode-stage wall proxy (decode is HBM-bound, wall ∝ bytes
    moved): at the swarm shape — batched decode, ~2k context — int8 KV
    moves >= 1.5x fewer total bytes per step, int4 >= 2x. This is the
    acceptance scoreboard's CPU-harness stand-in for `engine.step.*`."""
    cfg = DecodeEngine(preset="test-tiny", max_len=128, prefill_buckets=(64,),
                       init_weights=False).cfg
    # the bench config's serving dims (docs/PERF.md "What the floor is")
    cfg = cfg.__class__(**{**cfg.__dict__, "dim": 2048, "ffn_dim": 5632,
                           "n_layers": 22, "n_heads": 32, "n_kv_heads": 4})
    off = decode_step_bytes(cfg, batch=64, context_tokens=2048)
    i8 = decode_step_bytes(cfg, batch=64, context_tokens=2048,
                           kv_quant="int8")
    i4 = decode_step_bytes(cfg, batch=64, context_tokens=2048,
                           kv_quant="int4")
    assert off["weights_bytes"] == i8["weights_bytes"]  # weights untouched
    assert off["total_bytes"] / i8["total_bytes"] >= 1.5
    assert off["total_bytes"] / i4["total_bytes"] >= 2.0
    # KV-only ratio matches the block-bytes accounting (~1.94x / ~3.8x)
    assert off["kv_read_bytes"] / i8["kv_read_bytes"] == pytest.approx(
        128 / 66, rel=1e-6)


# ------------------------------------------------------------ fused kernels


@pytest.mark.parametrize("bits", [8, 4])
def test_paged_attention_quant_kernel_parity(bits):
    """The fused-dequant decode kernel == dequantize-then-reference, int8
    and packed int4, ragged kv_len, both layers."""
    from tpu_voice_agent.ops import paged_attention_quant
    from tpu_voice_agent.ops.kvquant import quantize_kv
    from tpu_voice_agent.ops.paged_attention import (
        paged_attention_quant_reference,
    )

    tier = "int8" if bits == 8 else "int4"
    L, N, bs, B, nq, nkv, hd = 2, 8, 16, 3, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    kf = jax.random.normal(ks[1], (L, N, bs, nkv, hd), jnp.float32)
    vf = jax.random.normal(ks[2], (L, N, bs, nkv, hd), jnp.float32)
    k_pool, k_scale = quantize_kv(kf, tier)
    v_pool, v_scale = quantize_kv(vf, tier)
    tables = jnp.asarray([[3, 7, 1], [5, 2, 6], [4, 0, 2]], jnp.int32)
    kv_len = jnp.asarray([5, 33, 48], jnp.int32)
    for layer in (0, 1):
        ref = paged_attention_quant_reference(
            q, k_pool, v_pool, k_scale, v_scale, tables, kv_len, layer,
            bits=bits)
        out = paged_attention_quant(
            q, k_pool, v_pool, k_scale, v_scale, tables, kv_len,
            jnp.int32(layer), bits=bits)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bits", [8, 4])
def test_paged_block_attention_quant_kernel_parity(bits):
    from tpu_voice_agent.ops import paged_block_attention_quant
    from tpu_voice_agent.ops.kvquant import quantize_kv
    from tpu_voice_agent.ops.paged_attention import (
        paged_block_attention_quant_reference,
    )

    tier = "int8" if bits == 8 else "int4"
    L, N, bs, B, T, nq, nkv, hd = 1, 6, 16, 2, 3, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, T, nq, hd), jnp.float32)
    kf = jax.random.normal(ks[1], (L, N, bs, nkv, hd), jnp.float32)
    vf = jax.random.normal(ks[2], (L, N, bs, nkv, hd), jnp.float32)
    k_pool, k_scale = quantize_kv(kf, tier)
    v_pool, v_scale = quantize_kv(vf, tier)
    tables = jnp.asarray([[3, 1, 5], [2, 4, 0]], jnp.int32)
    positions = jnp.asarray([[17, 18, 19], [30, 31, 32]], jnp.int32)
    ref = paged_block_attention_quant_reference(
        q, k_pool, v_pool, k_scale, v_scale, tables, positions,
        jnp.int32(0), bits=bits)
    out = paged_block_attention_quant(
        q, k_pool, v_pool, k_scale, v_scale, tables, positions,
        jnp.int32(0), bits=bits)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bits", [8, 4])
def test_decode_attention_quant_kernel_parity(bits):
    """The dense-cache fused-dequant twin (same _qk_dot/_pv_dot packed
    arithmetic as the paged kernels — one copy, both proven here)."""
    from tpu_voice_agent.ops import decode_attention_quant
    from tpu_voice_agent.ops.decode_attention import (
        decode_attention_quant_reference,
    )
    from tpu_voice_agent.ops.kvquant import quantize_kv

    tier = "int8" if bits == 8 else "int4"
    B, S, nq, nkv, hd = 3, 256, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    kf = jax.random.normal(ks[1], (B, S, nkv, hd), jnp.float32)
    vf = jax.random.normal(ks[2], (B, S, nkv, hd), jnp.float32)
    k_cache, k_scale = quantize_kv(kf, tier)
    v_cache, v_scale = quantize_kv(vf, tier)
    kv_len = jnp.asarray([5, 133, 256], jnp.int32)
    ref = decode_attention_quant_reference(
        q, k_cache, v_cache, k_scale, v_scale, kv_len, bits=bits)
    out = decode_attention_quant(
        q, k_cache, v_cache, k_scale, v_scale, kv_len, bits=bits,
        block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.fixture(scope="module")
def tiny_tables():
    eng = DecodeEngine(preset="test-tiny", max_len=128, prefill_buckets=(64,),
                       init_weights=False)
    return eng.tables, eng.cfg.vocab_size


def test_masked_argmax_advance_fuses_mask_argmax_and_fsm(tiny_tables):
    """ONE kernel == the three-op chain it replaces (mask -> argmax ->
    fsm_advance) on the engine's real grammar tables, including the
    clamped dead-state contract the poison gate relies on."""
    from tpu_voice_agent.ops import (
        masked_argmax,
        masked_argmax_advance,
        masked_argmax_advance_reference,
    )

    tables, V = tiny_tables
    assert tables.dense_mask is not None
    S = tables.dense_mask.shape[0]
    B = 8
    logits = jax.random.normal(jax.random.PRNGKey(11), (B, V), jnp.float32)
    states = jnp.asarray([0, 1, S - 1, 2, 0, 5 % S, -1, 3 % S], jnp.int32)
    tok, nxt = masked_argmax_advance(
        logits, states, tables.dense_mask, tables.table, tables.col_id)
    rtok, rnxt = masked_argmax_advance_reference(
        logits, states, tables.dense_mask, tables.table, tables.col_id)
    assert (np.asarray(tok) == np.asarray(rtok)).all()
    assert (np.asarray(nxt) == np.asarray(rnxt)).all()
    # live rows: exactly the unfused chain
    live = np.asarray(states) >= 0
    chain_tok = masked_argmax(logits, jnp.maximum(states, 0),
                              tables.dense_mask)
    chain_nxt = fsm_advance(tables, jnp.maximum(states, 0), chain_tok)
    assert (np.asarray(tok)[live] == np.asarray(chain_tok)[live]).all()
    assert (np.asarray(nxt)[live] == np.asarray(chain_nxt)[live]).all()


def test_masked_argmax_block_per_position_states(tiny_tables):
    """The spec verify tail: every (row, position) masked at its OWN state
    in one call == the sequential per-position reference loop."""
    from tpu_voice_agent.ops import masked_argmax_block, masked_argmax_reference

    tables, V = tiny_tables
    S = tables.dense_mask.shape[0]
    B, T = 3, 5
    logits = jax.random.normal(jax.random.PRNGKey(13), (B, T, V), jnp.float32)
    states = jax.random.randint(jax.random.PRNGKey(14), (B, T), 0, S)
    states = states.at[1, 3].set(-1)  # dead positions clamp to state 0
    out = masked_argmax_block(logits, states, tables.dense_mask)
    for i in range(T):
        ref = masked_argmax_reference(
            logits[:, i, :], jnp.maximum(states[:, i], 0), tables.dense_mask)
        assert (np.asarray(out[:, i]) == np.asarray(ref)).all()


# ------------------------------------------------------------ engine gating


def test_kv_quant_unset_keeps_bf16_pool(monkeypatch):
    """KV_QUANT unset: bf16 pool, no scale planes, no quant branches —
    the byte-identical contract's structural half (the behavioral half is
    every pre-existing paged test running on this default path)."""
    monkeypatch.delenv("KV_QUANT", raising=False)
    eng = PagedDecodeEngine(preset="test-tiny", max_len=512,
                            prefill_buckets=(64,), init_weights=False)
    assert eng.kv_quant is None and eng.kv_quant_bits == 16
    assert eng.k_pool.dtype == jnp.bfloat16
    assert eng.k_scale is None and eng.v_scale is None


def test_kv_quant_env_knob_and_validation(monkeypatch):
    monkeypatch.setenv("KV_QUANT", "int8")
    eng = PagedDecodeEngine(preset="test-tiny", max_len=512,
                            prefill_buckets=(64,), init_weights=False)
    assert eng.kv_quant == "int8" and eng.k_pool.dtype == jnp.int8
    assert eng.k_scale is not None and eng.k_scale.dtype == jnp.bfloat16
    # stored last axis: full head_dim int8, half packed int4
    hd = eng.cfg.head_dim
    assert eng.k_pool.shape[-1] == hd
    monkeypatch.setenv("KV_QUANT", "int4")
    eng4 = PagedDecodeEngine(preset="test-tiny", max_len=512,
                             prefill_buckets=(64,), init_weights=False)
    assert eng4.k_pool.shape[-1] == hd // 2
    monkeypatch.setenv("KV_QUANT", "fp8")
    with pytest.raises(ValueError, match="KV_QUANT"):
        PagedDecodeEngine(preset="test-tiny", max_len=512,
                          prefill_buckets=(64,), init_weights=False)


# ------------------------------------------------------ int8 differentials

TURNS = [
    ("search for wireless headphones", {}),
    ("open the second result", {"last_query": "wireless headphones"}),
    ("sort these by price from low to high", {"last_query": "wireless headphones"}),
]


def _play_session(eng, turns=TURNS, max_new=MAXTOK):
    tok = eng.tokenizer
    st = SessionTranscripts(tok)
    results = []
    for text, ctx in turns:
        prompt = st.prompt_for("sess", text, ctx)
        ids = (tok.encode(prompt, bos=True) if isinstance(prompt, str)
               else list(prompt))
        r = _run(eng, [ids], max_new=max_new)[0]
        assert r.error is None, r.error
        results.append(r)
        st.record("sess", ids, r.token_ids)
    return results


def test_int8_radix_warm_cold_identity(eng_int8):
    """Radix chains share QUANTIZED blocks (scales travel with the block):
    warm admissions served from int8 cached chains are token-identical to
    int8 cold admissions — decode-written and prefill-written quantized KV
    are bitwise equal, same contract as the bf16 pool."""
    warm_eng = _paged("int8", radix=True)
    cold = _play_session(eng_int8)
    warm = _play_session(warm_eng)
    P = len(warm_eng.prefix_ids)
    for c, w in zip(cold, warm):
        assert c.token_ids == w.token_ids
        assert warm_eng.fsm.walk(w.token_ids) >= 0
    assert warm[0].cached_tokens == P       # turn 1: static prefix only
    assert warm[1].cached_tokens > P        # turn 2+: quantized chain hit
    # full replay FROM the cached quantized chains: still identical
    warm2 = _play_session(warm_eng)
    for c, w in zip(cold, warm2):
        assert c.token_ids == w.token_ids


def test_int8_spec_paged_identity(eng_int8, prompts, int8_baseline):
    """Spec verify/rollback is block-granular over the quantized pool
    unchanged: int8+spec == int8 plain, with drafts actually landing."""
    eng = _paged("int8", spec=SpecConfig(k=4, drafter="fsm,prompt"))
    res = _run(eng, prompts)
    for ref, r in zip(int8_baseline, res):
        assert r.error is None
        assert r.token_ids == ref.token_ids
        assert r.forwards > 0
    assert eng.spec.stats()["accepted"] > 0


def test_int8_chaos_nan_quarantines_alone(eng_int8, prompts, int8_baseline):
    """The chaos quarantine drill on the quantized plane: a NaN-poisoned
    row evicts alone, its batch-mate token-identical, zero leaked blocks."""
    counters = get_metrics().snapshot()["counters"]
    before = counters.get("scheduler.slots_quarantined", 0)
    eng = _paged("int8")
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=MAXTOK)
    chaos.configure("nan_logits@2")
    try:
        res = b.generate_many(prompts)
    finally:
        chaos.reset()
    assert res[1].error is not None and \
        res[1].error.startswith("poisoned: non-finite"), res[1].error
    assert res[0].error is None
    assert res[0].token_ids == int8_baseline[0].token_ids
    after = get_metrics().snapshot()["counters"]["scheduler.slots_quarantined"]
    assert after == before + 1
    assert eng.allocator.blocks_in_use == len(eng._prefix_blocks[0])


def test_int8_warm_restart_readopts_quantized_prefix(eng_int8, prompts,
                                                     int8_baseline):
    """warm_restart keeps the quantized pool arrays AND scale planes;
    reserve() re-adopts the static-prefix blocks whose scales are pool-
    indexed — post-restart output identical, prefix still served from
    cache, sentinel quiet contract covered by test_steplog elsewhere."""
    from tpu_voice_agent.utils.compilewatch import get_compile_watcher

    eng = _paged("int8")
    first = _run(eng, prompts)
    for ref, r in zip(int8_baseline, first):
        assert r.error is None and r.token_ids == ref.token_ids
    eng.warm_restart()  # arms the recompile-sentinel fence
    fence_before = get_compile_watcher().state()["post_fence_compiles"]
    again = _run(eng, prompts)
    for ref, r in zip(int8_baseline, again):
        assert r.error is None and r.token_ids == ref.token_ids
        assert r.cached_tokens == len(eng.prefix_ids)
    # the acceptance bar's sentinel half: the quantized plane's jitted
    # entry points (scatter twin, quant forward, fused tail) all come back
    # at their warmed shapes — zero compiles past the fence
    assert get_compile_watcher().state()["post_fence_compiles"] == \
        fence_before


# ------------------------------------------------------------ accounting


@pytest.mark.parametrize("tier", [None, "int8", "int4"])
def test_hbm_plan_matches_measured_kv(tier):
    """hbm.plan_drift ~ 0 under every tier: the static plan's KV bytes
    equal the measured pool + scale planes exactly (the satellite that
    kills the phantom 2-4x drift a bf16-assumed plan would flag)."""
    eng = PagedDecodeEngine(preset="test-tiny", max_len=512, batch_slots=2,
                            prefill_buckets=(64,), kv_quant=tier,
                            init_weights=False)
    plan = engine_hbm_plan(eng)
    measured = measure_hbm(eng)
    assert plan["kv_pool_bytes"] == measured["kv_pool_bytes"]
    assert eng.kv_bytes_per_block * eng.allocator.n_blocks == \
        plan["kv_pool_bytes"]


def test_pool_gauges_bytes_view(eng_int8):
    """record_pool_gauges with the engine exports the bytes-denominated
    view (satellite: block counts stopped being a unit of HBM) and the
    fused-tail dispatch gauge landed from the batcher runs above."""
    from tpu_voice_agent.serve.paged import record_pool_gauges

    record_pool_gauges(eng_int8.allocator, engine=eng_int8)
    g = get_metrics().snapshot()["gauges"]
    assert g["paged.kv_quant_bits"] == 8.0
    assert g["paged.kv_bytes_per_block"] == float(eng_int8.kv_bytes_per_block)
    assert g["paged.kv_bytes_total"] == pytest.approx(
        g["paged.kv_blocks_total"] * eng_int8.kv_bytes_per_block)
    assert g["paged.kv_bytes_used"] == pytest.approx(
        g["paged.kv_blocks_used"] * eng_int8.kv_bytes_per_block)
    # paged.kv_utilization stays a FRACTION of one uniform-block pool —
    # invariant under bytes-per-block, so the degradation ladder's
    # measured-thrash trigger (PoolExhausted -> RADIX_PRESSURE_S window)
    # needs no re-expression; the bytes gauges are the dashboard unit
    assert 0.0 <= g["paged.kv_utilization"] <= 1.0
    assert "engine.step.fused_mask_sample_ms" in g
    assert get_metrics().collisions() == []


# ------------------------------------------------------------ golden floors


def test_golden_kv_quant_differential_distilled_floors():
    """The pinned lossy-tier accuracy budget on the TRAINED tiny
    checkpoint (random-weight margins are razor-thin and would pin noise):
    int8 token-identical AND intent-type-identical on the golden subset;
    int4 holds the type-agreement floor with every output grammar-valid."""
    from tpu_voice_agent.evals.golden import (
        GOLDEN_INTENT_CASES,
        kv_quant_differential,
    )
    from tpu_voice_agent.models.llama import LlamaConfig
    from tpu_voice_agent.train import distill

    cfg, params = distill.load_ckpt("checkpoints", distill.INTENT_CKPT,
                                    LlamaConfig)
    device_params = jax.device_put(params)

    def make_engine(tier):
        eng = PagedDecodeEngine(cfg=cfg, max_len=2048, batch_slots=2,
                                prefill_buckets=(256, 512, 1024),
                                kv_quant=tier, init_weights=False)
        eng.load_params(device_params)
        install_prompt_prefix(eng)
        return eng

    out = kv_quant_differential(make_engine, GOLDEN_INTENT_CASES[:6])
    assert out["cases"] == 6
    i8, i4 = out["tiers"]["int8"], out["tiers"]["int4"]
    assert i8["token_identical"] == 1.0
    assert i8["type_agreement"] == 1.0
    assert i8["grammar_valid"] == 1.0
    assert i4["grammar_valid"] == 1.0
    assert i4["type_agreement"] >= 0.5
