"""TSAN-style concurrency stress for the serving scheduler queue.

SURVEY.md §5 (race detection): "add a TSAN-style test for the serving
scheduler's queue". Python has no TSAN, so this is the moral equivalent:
many threads hammer the thread-safe surface (ColocatedServing.submit_parse /
abandon_parse) against a live worker thread, and the invariants that a data
race would break are asserted at the end:

- exactly-once: every non-abandoned request resolves exactly one Future
  with a result; none hang, none double-complete
- no cross-talk: every finished result is grammar-valid (a slot-state race
  would interleave two requests' tokens and leave the FSM)
- clean quiescence: queue empty, no slot owned, no orphaned results
- paged engine: every pool block returns to the allocator (a refcount race
  leaks blocks or double-frees)
"""

import json
import threading

import pytest

from tpu_voice_agent.serve import ContinuousBatcher, PagedDecodeEngine
from tpu_voice_agent.serve.colocate import ColocatedServing


def _prompt(utterance: str) -> str:
    user = json.dumps({"text": utterance, "context": {}}, separators=(",", ":"))
    return f"<|user|>\n{user}\n<|assistant|>\n"


UTTERANCES = [
    "search for usb hubs", "scroll down", "go back", "take a screenshot",
    "sort by price", "filter under 50 dollars",
]


def _stress(co: ColocatedServing, n_threads: int, per_thread: int,
            abandon_every: int = 0):
    """Fire n_threads * per_thread submits through a barrier; return
    (results, n_abandoned). Raises on any hung future."""
    barrier = threading.Barrier(n_threads)
    results, errors = [], []
    abandoned = [0]
    lock = threading.Lock()

    def worker(t: int):
        try:
            barrier.wait(timeout=30)
            futs = []
            for i in range(per_thread):
                fut = co.submit_parse(_prompt(UTTERANCES[(t + i) % len(UTTERANCES)]))
                if abandon_every and (t * per_thread + i) % abandon_every == 1:
                    co.abandon_parse(fut)
                    with lock:
                        abandoned[0] += 1
                else:
                    futs.append(fut)
            for fut in futs:
                res = fut.result(timeout=300)  # a hang == a lost wakeup race
                with lock:
                    results.append(res)
        except Exception as e:  # pragma: no cover - failure reporting
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=320)
        assert not th.is_alive(), "stress worker hung"
    assert not errors, f"stress worker raised: {errors[0]!r}"
    return results, abandoned[0]


def _assert_quiescent(co: ColocatedServing):
    b = co.batcher
    assert not b.pending, "queue must drain"
    assert not b.results, "orphaned results must be purged"
    assert all(sl.request_id < 0 for sl in b.slots), "slot leaked an owner"
    assert not co._parse_futs, "future registry leaked"


@pytest.fixture()
def dense_runtime(tiny_batch_engine):
    co = ColocatedServing(None, ContinuousBatcher(
        tiny_batch_engine, chunk_steps=4, max_new_tokens=16))
    co.start()
    yield co
    co.stop()


def test_concurrent_submits_exactly_once(dense_runtime):
    co = dense_runtime
    n, m = 6, 4
    results, _ = _stress(co, n, m)
    assert len(results) == n * m
    assert co.stats.parse_jobs == n * m
    eng = co.batcher.engine
    for res in results:
        assert res.error is None
        assert eng.fsm.walk(res.token_ids) >= 0, "token cross-talk between slots"
    _assert_quiescent(co)


def test_abandon_races_completion(dense_runtime):
    co = dense_runtime
    n, m = 6, 4
    results, n_abandoned = _stress(co, n, m, abandon_every=3)
    assert n_abandoned > 0
    assert len(results) == n * m - n_abandoned
    for res in results:
        assert res.error is None
    co.drain(timeout_s=120)
    _assert_quiescent(co)


def test_paged_allocator_survives_stress():
    eng = PagedDecodeEngine(preset="test-tiny", max_len=1024, batch_slots=3,
                            prefill_buckets=(64, 128, 256, 512),
                            block_size=64)
    co = ColocatedServing(None, ContinuousBatcher(eng, chunk_steps=4,
                                                  max_new_tokens=16))
    co.start()
    try:
        results, _ = _stress(co, 5, 4)
    finally:
        co.stop()
    for res in results:
        # pool exhaustion is legal under stress (isolated per request);
        # anything else is a real fault
        assert res.error is None or "exhausted" in res.error
        if res.error is None:
            assert eng.fsm.walk(res.token_ids) >= 0
    # every block returned: a refcount race leaks or double-frees
    assert eng.allocator.blocks_in_use == 0
    assert not eng.allocator._refs
