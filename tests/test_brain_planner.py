"""Planner-backed brain service: long-session transcripts behind /parse.

The PlannerParser keeps each session_id's full transcript (utterances AND
plans) as model context, extends warm turns with cached prefill, and
re-anchors via SP ring-attention prefill when a session outgrows its
bucket — served through the same /parse contract as every other backend.
"""

import httpx
import pytest

from tpu_voice_agent.parallel.ring import sp_mesh
from tpu_voice_agent.serve.planner import LongSessionPlanner
from tpu_voice_agent.services.brain import PlannerParser, build_app
from tests.http_helper import AppServer


@pytest.fixture(scope="module")
def planner_server():
    planner = LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(2048, 4096),
        extend_buckets=(64, 128), max_new_tokens=300,
    )
    with AppServer(build_app(PlannerParser(planner, max_new_tokens=300))) as srv:
        yield srv


def _parse(srv, text, session_id=None, timeout=300.0):
    body = {"text": text, "context": {}}
    if session_id is not None:
        body["session_id"] = session_id
    return httpx.post(f"http://127.0.0.1:{srv.port}/parse", json=body,
                      timeout=timeout)


def test_planner_parse_contract(planner_server):
    r = _parse(planner_server, "search for usb hubs", session_id="s1")
    assert r.status_code in (200, 422)  # 422 = truncation, the one legal failure
    if r.status_code == 200:
        data = r.json()
        assert data["version"] == "1.0"
        assert isinstance(data["intents"], list) and data["intents"]


def test_planner_session_accumulates(planner_server):
    r1 = _parse(planner_server, "search for laptops", session_id="s2")
    r2 = _parse(planner_server, "sort by price", session_id="s2")
    assert r1.status_code in (200, 422) and r2.status_code in (200, 422)


_PLAN_OK = (
    '{"version":"1.0","intents":[{"type":"scroll","target":null,"args":{},'
    '"priority":1,"requires_confirmation":false,"timeout_ms":15000,'
    '"retries":0}],"context_updates":{},"confidence":0.9,"tts_summary":null,'
    '"follow_up_question":null}'
)


class _StubPlanner:
    """Deterministic planner stub (random tiny models cannot guarantee EOS,
    so bookkeeping tests use the same fake-backend seam as the engine
    tests); transcript growth mimics the real start/extend/plan contract."""

    max_new_tokens = 64

    def __init__(self, plan_text: str = _PLAN_OK, bytes_per_session: int = 0):
        from types import SimpleNamespace

        self._mk = lambda: SimpleNamespace(ids=list(range(5)), pos=5,
                                           anchors=1, last_logits=object(),
                                           cache=None)
        self.plan_text = plan_text
        self.bytes_per_session = bytes_per_session

    def start(self, text):
        return self._mk()

    def extend(self, sess, text):
        sess.ids.extend([7] * 3)

    def plan(self, sess, max_new_tokens=None):
        sess.ids.extend([9] * 4)
        return self.plan_text, [9] * 4

    def plan_many(self, sessions, max_new_tokens=None, **kw):
        return [self.plan(s, max_new_tokens) for s in sessions]

    def session_bytes(self, sess):
        return 0 if getattr(sess, "parked", False) else self.bytes_per_session

    def park(self, sess):
        sess.parked = True

    def unpark(self, sess):
        sess.parked = False

    def parked_bytes(self, sess):
        return self.bytes_per_session if getattr(sess, "parked", False) else 0


def test_planner_sessions_isolated_and_evicted():
    parser = PlannerParser(_StubPlanner())
    parser.max_sessions = 2

    def turn(sid):
        parser.parse("scroll down", {}, session_id=sid)

    turn("a")
    turn("b")
    assert parser.session_count() == 2
    turn("c")  # evicts LRU ("a")
    assert parser.session_count() == 2
    assert "a" not in parser._sessions and "c" in parser._sessions
    # a second turn on an existing session extends, not restarts
    sess_b = parser._sessions["b"]
    n_before = len(sess_b.ids)
    turn("b")
    assert parser._sessions["b"] is sess_b
    assert len(sess_b.ids) > n_before


def test_planner_truncated_plan_drops_session():
    """A plan that fails JSON validation must NOT keep the session — its
    transcript ends in malformed half-JSON that would poison later turns."""
    import pytest as _pytest

    from tpu_voice_agent.services.brain import ParserError

    parser = PlannerParser(_StubPlanner(plan_text='{"version":"1.0","int'))
    with _pytest.raises(ParserError) as ei:
        parser.parse("scroll down", {}, session_id="s")
    assert ei.value.kind == "schema_validation_failed"
    assert parser.session_count() == 0


def test_planner_no_session_id_is_one_shot():
    """session_id=None must never share state across callers (no hidden
    default key — that would bleed one client's transcript into another)."""
    parser = PlannerParser(_StubPlanner())
    parser.parse("scroll down", {}, session_id=None)
    assert parser.session_count() == 0


def test_planner_byte_aware_eviction():
    """Eviction is driven by KV-cache bytes, not only session count
    (round-2 advisor: 32 sessions of dense caches can OOM a chip long
    before the count cap binds)."""
    parser = PlannerParser(_StubPlanner(bytes_per_session=1 << 20),
                           hbm_budget_bytes=int(2.5 * (1 << 20)))
    for sid in ("a", "b", "c", "d"):
        parser.parse("scroll down", {}, session_id=sid)
    # 4 turns done, but only 2 sessions (2 MiB) fit the 2.5 MiB budget
    assert parser.session_count() == 2
    assert parser.session_hbm_bytes() <= int(2.5 * (1 << 20))
    assert "d" in parser._sessions and "c" in parser._sessions  # LRU kept


def test_planner_concurrent_sessions_share_batched_decode():
    """Round-2 VERDICT weak #2: sessions must not serialize behind one
    lock. 8 sessions parse concurrently; the gather worker batches their
    plan decodes into shared chunk_decode_loop dispatches."""
    import threading

    from tpu_voice_agent.utils import get_metrics

    planner = LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(2048,),
        extend_buckets=(64,), max_new_tokens=200,
    )
    parser = PlannerParser(planner, max_new_tokens=200)
    before = get_metrics().snapshot()["counters"].get("planner.batched_plans", 0)
    results: dict[str, object] = {}

    def turn(sid):
        try:
            results[sid] = parser.parse(f"search for {sid} gadgets", {}, session_id=sid)
        except Exception as e:  # truncation (422-class) is legal for random weights
            results[sid] = e

    threads = [threading.Thread(target=turn, args=(f"s{i}",)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert len(results) == 8
    from tpu_voice_agent.schemas import ParseResponse
    from tpu_voice_agent.services.brain import ParserError

    for sid, r in results.items():
        assert isinstance(r, (ParseResponse, ParserError)), f"{sid}: {r!r}"
    after = get_metrics().snapshot()["counters"].get("planner.batched_plans", 0)
    assert after > before, "concurrent plans never shared a batched dispatch"


def test_plan_many_matches_sequential_plan():
    """Batched plan decode must be token-identical to one-by-one plan()
    (greedy): the batching is a throughput optimization, never a
    distribution change."""
    mk = lambda: LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(1024,),
        extend_buckets=(32,), max_new_tokens=120,
    )
    texts = ["search for red shoes", "scroll down two pages", "go back now"]
    p1, p2 = mk(), mk()
    seq = [p1.plan(p1.start(t)) for t in texts]
    sessions = [p2.start(t) for t in texts]
    batched = p2.plan_many(sessions)
    for (st, si), (bt, bi) in zip(seq, batched):
        assert si == bi
        assert st == bt


def test_evicted_session_parks_to_host_and_resumes():
    """Eviction parks the session's cache to host RAM instead of dropping
    it (round-2 advisor offload option): a later turn on the evicted id
    RESUMES the transcript (extend path), never cold-starts."""
    parser = PlannerParser(_StubPlanner(bytes_per_session=1 << 20))
    parser.max_sessions = 2

    parser.parse("scroll down", {}, session_id="a")
    sess_a = parser._sessions["a"]
    n_before = len(sess_a.ids)
    parser.parse("scroll down", {}, session_id="b")
    parser.parse("scroll down", {}, session_id="c")  # evicts "a" -> parked
    assert "a" not in parser._sessions and "a" in parser._parked
    assert getattr(sess_a, "parked", False) is True
    parser.parse("go back", {}, session_id="a")  # resumes the SAME session
    assert parser._sessions["a"] is sess_a
    assert sess_a.parked is False  # unparked on checkout
    assert len(sess_a.ids) > n_before  # extended, not restarted


def test_park_budget_zero_disables_offload():
    parser = PlannerParser(_StubPlanner(bytes_per_session=1 << 20))
    parser.max_sessions = 1
    parser.park_budget_bytes = 0
    parser.parse("scroll down", {}, session_id="a")
    parser.parse("scroll down", {}, session_id="b")  # evicts "a" for real
    assert "a" not in parser._sessions and not parser._parked


def test_parked_overflow_drops_oldest():
    parser = PlannerParser(_StubPlanner(bytes_per_session=1 << 20))
    parser.max_sessions = 1
    parser.park_budget_bytes = 2 << 20  # room for two parked sessions
    for sid in ("a", "b", "c", "d"):
        parser.parse("scroll down", {}, session_id=sid)
    # d live; c, b parked; a dropped (oldest parked beyond budget)
    assert list(parser._sessions) == ["d"]
    assert list(parser._parked) == ["b", "c"]


def test_real_planner_park_roundtrip_preserves_decode():
    """park/unpark on the real planner: cache round-trips through host
    numpy and the next plan is token-identical to a never-parked twin."""
    import numpy as np

    mk = lambda: LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(1024,),
        extend_buckets=(32,), max_new_tokens=100,
    )
    p1, p2 = mk(), mk()
    s1 = p1.start("search for red shoes")
    s2 = p2.start("search for red shoes")
    p1.plan(s1)
    p2.plan(s2)
    p2.park(s2)
    assert isinstance(s2.cache["k"], np.ndarray)
    assert p2.session_bytes(s2) == 0 and p2.parked_bytes(s2) > 0
    p2.unpark(s2)
    p1.extend(s1, "\n<|user|>\nsort by price\n<|assistant|>\n")
    p2.extend(s2, "\n<|user|>\nsort by price\n<|assistant|>\n")
    (t1, ids1) = p1.plan(s1)
    (t2, ids2) = p2.plan(s2)
    assert ids1 == ids2 and t1 == t2


def test_planner_fast_forward_stays_in_grammar():
    """fast_forward>0 (opt-in) routes single-session plans through the
    forced-chain decode; the emitted token stream must still walk the
    intent grammar and carry its forced scaffolding. (Byte-identity with
    ff=0 is NOT a contract: retokenized chains change the model-visible
    history, so later free choices may legitimately diverge — which is why
    ff defaults OFF in the planner.)"""
    p8 = LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(1024,),
        extend_buckets=(32,), max_new_tokens=120, fast_forward=8,
    )
    t8, ids8 = p8.plan(p8.start("search for red shoes"))
    assert p8.fsm.walk(ids8) >= 0, "ff plan left the grammar"
    assert t8.startswith('{"version":"1.0","intents":[')
    # the ff twin shares the base tables' device arrays (no re-upload)
    assert p8.tables_ff.table is p8.tables.table
    assert p8.tables_ff.col_id is p8.tables.col_id


def test_checkin_survives_park_failure_without_leaking_lock():
    """Round-3 advisor (medium): park() is a blocking D2H copy that can
    raise (e.g. TPU backend failure) AFTER _busy is cleared; the per-session
    lock must still be released or every later turn on that session_id
    deadlocks in _checkout. The failing victim is simply dropped (it was
    already evicted) and the request whose plan succeeded still succeeds."""
    planner = _StubPlanner(bytes_per_session=1 << 20)

    def bad_park(sess):
        raise RuntimeError("injected TPU backend failure")

    planner.park = bad_park
    parser = PlannerParser(planner, hbm_budget_bytes=1)  # evict on every checkin

    parser.parse("scroll down", {}, session_id="a")
    # checkin of "b" evicts "a" -> park raises; the parse must still succeed
    r = parser.parse("scroll down", {}, session_id="b")
    assert r.intents
    # "a" was dropped, not parked
    assert "a" not in parser._parked and "a" not in parser._sessions
    # the critical bit: b's lock was released -- another turn on "b" must
    # not deadlock (run it in a thread with a timeout so a regression fails
    # fast instead of hanging the suite)
    import threading

    done = threading.Event()
    err: list = []

    def turn():
        try:
            parser.parse("scroll up", {}, session_id="b")
        except Exception as e:  # pragma: no cover - diagnostic only
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=turn, daemon=True)
    t.start()
    assert done.wait(timeout=10.0), "second turn deadlocked: lock leaked by _checkin"
    assert not err


def test_plan_gather_groups_heterogeneous_budgets():
    """Round-3 advisor: co-batched requests with different max_new_tokens
    must NOT be clipped to min() -- the gatherer groups by budget."""
    import threading
    import time as _time
    from tpu_voice_agent.services.brain import _PlanGather

    calls: list = []
    first_entered = threading.Event()
    release = threading.Event()

    class _RecordingPlanner:
        def plan_many(self, sessions, max_new_tokens=None, **kw):
            calls.append((len(sessions), max_new_tokens))
            if len(calls) == 1:  # block the loop so later submissions co-queue
                first_entered.set()
                release.wait(timeout=10.0)
            return [("{}", [1]) for _ in sessions]

    g = _PlanGather(_RecordingPlanner(), max_batch=8)
    results = {}

    def submit(name, budget):
        results[name] = g.plan(object(), budget)

    t0 = threading.Thread(target=submit, args=("first", 5), daemon=True)
    t0.start()
    assert first_entered.wait(timeout=10.0)  # loop is blocked inside plan_many
    ts = [threading.Thread(target=submit, args=(f"r{i}", b), daemon=True)
          for i, b in enumerate([10, 20, 10])]
    for t in ts:
        t.start()
    # deterministic rendezvous: all three must be IN the queue before the
    # loop wakes, or it would drain a partial batch (no fixed sleeps — a
    # loaded machine would make those flaky)
    deadline = _time.monotonic() + 10.0
    while g._q.qsize() < 3:
        assert _time.monotonic() < deadline, "submissions never queued"
        _time.sleep(0.005)
    release.set()
    for t in [t0] + ts:
        t.join(timeout=10.0)
    assert len(results) == 4
    # first ran alone; the co-queued three split into budget groups
    # {10: 2 sessions, 20: 1 session} -- nobody decoded under min(10, 20)
    grouped = sorted(calls[1:])
    assert grouped == [(1, 20), (2, 10)], calls


def test_plan_many_preserves_slot0_kv_of_early_finishers():
    """A session that stops decoding before its batchmates goes idle in
    chunk_decode_loop, which parks its per-step writes at slot 0 of its own
    cache line. The engines' per-request caches are throwaway, but the
    planner PERSISTS this cache — plan_many must restore each row's real
    slot-0 K/V so the first transcript token survives co-batching."""
    import numpy as np

    planner = LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(1024,),
        extend_buckets=(32,), max_new_tokens=120,
    )
    texts = ["search for red shoes", "scroll down two pages", "go back now"]
    sessions = [planner.start(t) for t in texts]
    before = [(np.asarray(s.cache["k"][:, 0, 0]).copy(),
               np.asarray(s.cache["v"][:, 0, 0]).copy()) for s in sessions]
    outs = planner.plan_many(sessions)
    counts = [len(ids) for _, ids in outs]
    # precondition for the regression to bite: rows finish at different
    # steps (greedy + fixed seed on CPU -> deterministic); if this ever
    # collapses to all-equal, change a prompt so the scenario is real again
    assert len(set(counts)) > 1, f"all rows finished together: {counts}"
    for sess, (k0, v0) in zip(sessions, before):
        np.testing.assert_array_equal(np.asarray(sess.cache["k"][:, 0, 0]), k0)
        np.testing.assert_array_equal(np.asarray(sess.cache["v"][:, 0, 0]), v0)


class _CountingPlanner(_StubPlanner):
    """Stub that counts plan decodes (speculation must not double-decode)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.plans = 0

    def plan_many(self, sessions, max_new_tokens=None, **kw):
        self.plans += len(sessions)
        return super().plan_many(sessions, max_new_tokens, **kw)


def test_planner_speculative_commit_is_one_decode():
    """spec(text) then final(text): the provisional turn IS the turn —
    the final must deliver the cached response with ZERO extra decode and
    the transcript must hold the turn exactly once."""
    planner = _CountingPlanner()
    parser = PlannerParser(planner)
    r1 = parser.parse("scroll down", {}, session_id="s", speculative=True)
    n_after_spec = len(parser._sessions["s"].ids)
    r2 = parser.parse("scroll down", {}, session_id="s")
    assert planner.plans == 1
    assert r2.model_dump() == r1.model_dump()
    assert len(parser._sessions["s"].ids) == n_after_spec  # no double record
    assert getattr(parser._sessions["s"], "pending_spec", None) is None


def test_planner_speculative_mismatch_rolls_back():
    """spec("sort...") then final("scroll...") on a WARM session: the
    provisional turn is undone before the real turn — the transcript must
    equal a twin session that never speculated."""
    parser = PlannerParser(_CountingPlanner())
    parser.parse("first turn", {}, session_id="a")  # warm the session
    twin = list(parser._sessions["a"].ids)
    parser.parse("sort by price", {}, session_id="a", speculative=True)
    parser.parse("scroll down", {}, session_id="a")  # DIFFERENT final

    ref = PlannerParser(_CountingPlanner())
    ref.parse("first turn", {}, session_id="a")
    assert list(ref._sessions["a"].ids) == twin
    ref.parse("scroll down", {}, session_id="a")
    assert list(parser._sessions["a"].ids) == list(ref._sessions["a"].ids)


def test_planner_speculative_fresh_session_mismatch_drops_provisional():
    """A session that only exists speculatively must vanish on mismatch —
    the final's turn is the session's FIRST turn."""
    parser = PlannerParser(_CountingPlanner())
    parser.parse("sort by price", {}, session_id="n", speculative=True)
    parser.parse("scroll down", {}, session_id="n")
    ref = PlannerParser(_CountingPlanner())
    ref.parse("scroll down", {}, session_id="n")
    assert list(parser._sessions["n"].ids) == list(ref._sessions["n"].ids)


def test_planner_eviction_rolls_back_pending_speculation():
    """Evicting a session mid-speculation must undo the provisional turn:
    the commit marker cannot survive, so a matching final re-parses from
    the CLEAN transcript (never double-records)."""
    parser = PlannerParser(_CountingPlanner(bytes_per_session=1 << 20),
                           hbm_budget_bytes=1)  # evict aggressively
    parser.max_sessions = 1
    parser.parse("first turn", {}, session_id="a")
    parser.parse("sort by price", {}, session_id="a", speculative=True)
    parser.parse("other session", {}, session_id="b")  # evicts "a" (parked)
    parser.parse("sort by price", {}, session_id="a")  # matching final
    ref = PlannerParser(_CountingPlanner())
    ref.parse("first turn", {}, session_id="a")
    ref.parse("sort by price", {}, session_id="a")
    assert list(parser._sessions["a"].ids) == list(ref._sessions["a"].ids)


def test_real_planner_speculative_commit_matches_plain_turns():
    """Integration on the REAL planner: [spec A, commit A, turn B] must
    leave the session token-identical to a twin that ran [A, B] plainly,
    and the committed response must equal the plain response."""
    mk = lambda: LongSessionPlanner(
        preset="test-tiny", mesh=sp_mesh(4), ctx_buckets=(2048,),
        extend_buckets=(64,), max_new_tokens=200,
    )
    p1 = PlannerParser(mk(), max_new_tokens=200)
    p2 = PlannerParser(mk(), max_new_tokens=200)

    def turn(parser, text, **kw):
        try:
            return parser.parse(text, {}, session_id="s", **kw)
        except Exception as e:  # truncation is legal for random weights
            return e

    ra_spec = turn(p1, "search for usb hubs", speculative=True)
    ra_fin = turn(p1, "search for usb hubs")
    rb1 = turn(p1, "scroll down")
    ra_plain = turn(p2, "search for usb hubs")
    rb2 = turn(p2, "scroll down")
    if not isinstance(ra_spec, Exception):
        assert ra_fin.model_dump() == ra_spec.model_dump()
        assert ra_plain.model_dump() == ra_spec.model_dump()
    if "s" in p1._sessions and "s" in p2._sessions:
        assert list(p1._sessions["s"].ids) == list(p2._sessions["s"].ids)
    if not isinstance(rb1, Exception) and not isinstance(rb2, Exception):
        assert rb1.model_dump() == rb2.model_dump()


def test_planner_http_speculative_now_200(planner_server):
    """The /parse route accepts speculative requests for the planner
    backend (two-phase turns replaced the round-4-early 409)."""
    r = _parse_spec(planner_server, "search for usb hubs", "sp1", True)
    assert r.status_code in (200, 422)
    r2 = _parse_spec(planner_server, "search for usb hubs", "sp1", False)
    assert r2.status_code in (200, 422)
    if r.status_code == 200 and r2.status_code == 200:
        assert r.json() == r2.json()


def _parse_spec(srv, text, session_id, speculative):
    return httpx.post(f"http://127.0.0.1:{srv.port}/parse",
                      json={"text": text, "session_id": session_id,
                            "context": {}, "speculative": speculative},
                      timeout=300.0)


def test_planner_speculative_commit_requires_same_context():
    """A context_update between spec and final changes what the parse
    should see: same TEXT with different CONTEXT must not deliver the
    stale old-context plan — it rolls back and re-parses."""
    planner = _CountingPlanner()
    parser = PlannerParser(planner)
    parser.parse("sort by price", {"page": 1}, session_id="c", speculative=True)
    parser.parse("sort by price", {"page": 2}, session_id="c")
    assert planner.plans == 2  # no stale commit
    ref = PlannerParser(_CountingPlanner())
    ref.parse("sort by price", {"page": 2}, session_id="c")
    assert list(parser._sessions["c"].ids) == list(ref._sessions["c"].ids)


def test_planner_failed_speculation_preserves_committed_history():
    """A speculative turn that truncates (the likeliest failure: the
    provisional transcript is a half-finished utterance) must NOT destroy
    the session's committed turns — the snapshot restores and the matching
    final re-parses from the clean transcript."""
    import pytest as _pytest

    from tpu_voice_agent.services.brain import ParserError

    planner = _CountingPlanner()
    parser = PlannerParser(planner)
    parser.parse("first turn", {}, session_id="h")  # committed history
    clean = list(parser._sessions["h"].ids)
    planner.plan_text = '{"version":"1.0","int'  # truncation
    with _pytest.raises(ParserError):
        parser.parse("sort by price", {}, session_id="h", speculative=True)
    # the session SURVIVED with its committed transcript intact
    assert "h" in parser._sessions
    assert list(parser._sessions["h"].ids) == clean
    planner.plan_text = _PLAN_OK
    r = parser.parse("sort by price", {}, session_id="h")
    assert r.intents
