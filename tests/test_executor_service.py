"""Session manager + executor HTTP service tests (real socket, fake page)."""

import io

import httpx
import pytest

from tpu_voice_agent.services.executor import FakePage, SessionManager, build_app
from tpu_voice_agent.services.executor.page import FakeElement
from tests.http_helper import AppServer


def fake_factory():
    return FakePage(
        elements=[
            FakeElement("#search", tag="input", etype="search", placeholder="Search"),
            FakeElement("#fileinput", tag="input", etype="file"),
            FakeElement(".results", tag="div", text="ok"),
        ]
    )


# ---------------------------------------------------------------- sessions


def test_session_reuse_and_close(tmp_path):
    m = SessionManager(page_factory=fake_factory, artifacts_root=str(tmp_path / "a"),
                      uploads_dir=str(tmp_path / "u"))
    s1 = m.open()
    s2 = m.open(s1.id)
    assert s1 is s2
    assert m.close(s1.id) and not m.close(s1.id)


def test_dead_session_recreated_on_reuse(tmp_path):
    m = SessionManager(page_factory=fake_factory, artifacts_root=str(tmp_path / "a"),
                      uploads_dir=str(tmp_path / "u"))
    s1 = m.open("sess1")
    s1.page.closed = True  # browser died
    s2 = m.open("sess1")
    assert s2.page is not s1.page and s2.id == "sess1"


def test_idle_sessions_evicted(tmp_path):
    m = SessionManager(page_factory=fake_factory, artifacts_root=str(tmp_path / "a"),
                      uploads_dir=str(tmp_path / "u"), idle_ttl_s=0.0)
    m.open("old")
    assert m.evict_idle() == 1
    assert "old" not in m.sessions


# ---------------------------------------------------------------- http


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("exec")
    manager = SessionManager(
        page_factory=fake_factory,
        artifacts_root=str(tmp / "artifacts"),
        uploads_dir=str(tmp / "uploads"),
    )
    with AppServer(build_app(manager)) as srv:
        yield srv


def test_health(server):
    r = httpx.get(server.url + "/health")
    assert r.status_code == 200 and r.json()["service"] == "executor"


def test_execute_search_and_session_reuse(server):
    r = httpx.post(
        server.url + "/execute",
        json={"intents": [{"type": "search", "args": {"query": "tvs"}}]},
    )
    assert r.status_code == 200
    body = r.json()
    sid = body["session_id"]
    assert body["results"][0]["ok"] and body["artifacts"]["dir"]

    r2 = httpx.post(
        server.url + "/execute",
        json={"session_id": sid, "intents": [{"type": "screenshot"}]},
    )
    assert r2.json()["session_id"] == sid


def test_upload_then_execute_upload_intent(server):
    """The full confirm-flow seam (reference SURVEY.md §3.5): multipart upload
    returns a resume:// ref, which the upload intent resolves and applies."""
    files = {"file": ("resume.pdf", io.BytesIO(b"%PDF fake resume"), "application/pdf")}
    up = httpx.post(server.url + "/uploads", files=files)
    assert up.status_code == 200
    ref = up.json()["fileRef"]
    assert ref.startswith("resume://")

    r = httpx.post(
        server.url + "/execute",
        json={"intents": [{"type": "upload", "args": {"fileRef": ref}}]},
    )
    res = r.json()["results"][0]
    assert res["ok"], res["error"]
    assert res["data"]["path"].endswith(".pdf")


def test_execute_invalid_request_400(server):
    r = httpx.post(server.url + "/execute", json={"intents": []})
    assert r.status_code == 400 and r.json()["error"] == "invalid_request"


def test_close_session(server):
    r = httpx.post(
        server.url + "/execute", json={"intents": [{"type": "screenshot"}]}
    )
    sid = r.json()["session_id"]
    assert httpx.post(server.url + "/close", json={"session_id": sid}).json()["ok"]
    assert not httpx.post(server.url + "/close", json={"session_id": sid}).json()["ok"]


def test_step_error_isolated_in_http_response(server):
    r = httpx.post(
        server.url + "/execute",
        json={"intents": [
            {"type": "click", "target": {"strategy": "css", "value": "#missing"}},
            {"type": "screenshot"},
        ]},
    )
    results = r.json()["results"]
    assert not results[0]["ok"] and results[1]["ok"]


# ---------------------------------------------------------------- grounding


def test_service_grounded_click_fallback(tmp_path):
    """Service-level VL grounding (VERDICT round-1 missing #3): an
    unmatchable auto click routes through the injected grounder and snaps
    onto the analyzed element under the grounded point."""
    manager = SessionManager(
        page_factory=lambda: FakePage(
            elements=[
                FakeElement("#buy", tag="button", text="Buy now", role="button",
                            name="Buy now", bbox=(100, 200, 80, 30)),
            ],
            url="https://demo.local/item",
        ),
        artifacts_root=str(tmp_path / "a"),
        uploads_dir=str(tmp_path / "u"),
    )
    calls = []

    def grounder(image, instruction):
        calls.append(instruction)
        return 120.0, 210.0, "buy button"

    with AppServer(build_app(manager, grounder=grounder)) as srv:
        r = httpx.post(
            srv.url + "/execute",
            json={"intents": [{"type": "click", "args": {"text": "purchase this item"}}]},
        )
    assert r.status_code == 200
    step = r.json()["results"][0]
    assert step["ok"], step["error"]
    assert step["data"]["by"] == "grounded_selector"
    assert step["data"]["selector"] == "#buy"
    assert calls == ["purchase this item"]


def test_make_grounder_from_env(monkeypatch):
    from tpu_voice_agent.services.executor.grounding import TPUGrounder
    from tpu_voice_agent.services.executor.server import make_grounder_from_env

    monkeypatch.delenv("EXECUTOR_GROUNDING", raising=False)
    assert make_grounder_from_env() is None
    monkeypatch.setenv("EXECUTOR_GROUNDING", "qwen2vl:qwen2vl-test")
    g = make_grounder_from_env()
    assert isinstance(g, TPUGrounder) and g.preset == "qwen2vl-test"
    monkeypatch.setenv("EXECUTOR_GROUNDING", "clipseg")
    with pytest.raises(ValueError):
        make_grounder_from_env()
