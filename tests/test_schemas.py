"""Schema contract tests.

Mirrors the reference's packages/schemas/test/intent.test.ts:1-54 (accepts
navigate, filter+sort params, rejects confidence>1, extract+csv) against the
unified schema.
"""

import pytest
from pydantic import ValidationError

from tpu_voice_agent.schemas import (
    INTENT_TYPES,
    Intent,
    ParseRequest,
    ParseResponse,
    ExecuteRequest,
    parse_response_from_json,
)


def test_intent_vocabulary_is_19_types():
    assert len(INTENT_TYPES) == 19
    assert "extract_table" in INTENT_TYPES and "unknown" in INTENT_TYPES


def test_accepts_navigate():
    it = Intent(type="navigate", args={"url": "https://example.com"})
    assert it.timeout_ms == 15_000 and it.retries == 0 and not it.is_risky()


def test_accepts_filter_and_sort_params():
    resp = ParseResponse(
        intents=[
            Intent(type="filter", args={"field": "price", "op": "lte", "value": 100}),
            Intent(type="sort", args={"field": "price", "direction": "asc"}),
        ],
        confidence=0.92,
    )
    assert resp.intents[1].args["direction"] == "asc"


def test_rejects_confidence_above_one():
    with pytest.raises(ValidationError):
        ParseResponse(intents=[], confidence=1.2)


def test_rejects_retries_above_three():
    with pytest.raises(ValidationError):
        Intent(type="click", retries=4)


def test_upload_is_risky_even_without_flag():
    assert Intent(type="upload", args={"fileRef": "resume://abc"}).is_risky()


def test_execute_request_requires_intents():
    with pytest.raises(ValidationError):
        ExecuteRequest(intents=[])


def test_parse_request_context_roundtrip():
    req = ParseRequest(text="open the second result", context={"last_query": "laptops"})
    assert req.context["last_query"] == "laptops"


def test_parse_response_from_json_error_envelope():
    model, err = parse_response_from_json("{not json")
    assert model is None and err.startswith("invalid_json")
    model, err = parse_response_from_json(
        '{"version":"1.0","intents":[{"type":"search","args":{"query":"4k tv"}}],'
        '"context_updates":{},"confidence":0.9}'
    )
    assert err is None and model.intents[0].type == "search"
