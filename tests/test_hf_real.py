"""Real-checkpoint serving path: HF tokenizer.json (true BPE merges),
vocab-sized compressed FSM, config.json-driven engine construction, and
safetensors weight loading — VERDICT round-1 missing #1.

Fixtures build a small but structurally real HF checkpoint directory:
byte-level BPE tokenizer.json with trained merges + added specials,
config.json in HF Llama naming, and random weights saved as safetensors in
HF tensor naming. No network; everything offline (the graft environment has
zero egress).
"""

import json
from collections import Counter

import numpy as np
import pytest

from tpu_voice_agent.grammar.fsm import TokenFSM
from tpu_voice_agent.grammar.hf_tokenizer import (
    HFTokenizer,
    _byte_to_unicode,
    _PRETOK,
    load_hf_tokenizer,
)
from tpu_voice_agent.grammar.intent_grammar import build_fsm_for, intent_dfa
from tpu_voice_agent.schemas import parse_response_from_json
from tpu_voice_agent.services.prompts import render_prompt


def _train_merges(texts: list[str], n: int) -> list[tuple[str, str]]:
    """Reference BPE trainer over byte-unicode symbols (test-side twin of
    what HF tokenizers ship in tokenizer.json's merges section)."""
    b2u = _byte_to_unicode()
    words: Counter = Counter()
    for t in texts:
        for m in _PRETOK.finditer(t):
            words[tuple(b2u[b] for b in m.group(0).encode())] += 1
    merges: list[tuple[str, str]] = []
    work = dict(words)
    for _ in range(n):
        pairs: Counter = Counter()
        for w, c in work.items():
            for a, b in zip(w, w[1:]):
                pairs[(a, b)] += c
        if not pairs:
            break
        (a, b), cnt = pairs.most_common(1)[0]
        if cnt < 2:
            break
        merges.append((a, b))
        new = {}
        for w, c in work.items():
            out, i = [], 0
            while i < len(w):
                if i + 1 < len(w) and w[i] == a and w[i + 1] == b:
                    out.append(a + b)
                    i += 2
                else:
                    out.append(w[i])
                    i += 1
            key = tuple(out)
            new[key] = new.get(key, 0) + c
        work = new
    return merges


@pytest.fixture(scope="module")
def bytelevel_tokenizer_json(tmp_path_factory):
    """A GPT-2-family tokenizer.json: 256 byte symbols, merges trained on
    the brain prompt corpus, added special bos/eos."""
    corpus = [
        render_prompt("search for wireless headphones", {}),
        render_prompt("open the second result and extract the table", {"last_query": "x"}),
        '{"version":"1.0","intents":[{"type":"search","target":null,"args":{"query":"q"},'
        '"priority":1,"requires_confirmation":false,"timeout_ms":15000,"retries":0}],'
        '"context_updates":{},"confidence":0.9,"tts_summary":null,"follow_up_question":null}',
    ]
    merges = _train_merges(corpus, 400)
    b2u = _byte_to_unicode()
    vocab: dict[str, int] = {}
    for b in range(256):
        vocab[b2u[b]] = len(vocab)
    for a, b in merges:
        tok = a + b
        if tok not in vocab:
            vocab[tok] = len(vocab)
    n = len(vocab)
    obj = {
        "model": {
            "type": "BPE",
            "vocab": vocab,
            "merges": [f"{a} {b}" for a, b in merges],
        },
        "pre_tokenizer": {"type": "ByteLevel"},
        "added_tokens": [
            {"id": n, "content": "<|begin_of_text|>", "special": True},
            {"id": n + 1, "content": "<|end_of_text|>", "special": True},
        ],
    }
    d = tmp_path_factory.mktemp("bl_tok")
    (d / "tokenizer.json").write_text(json.dumps(obj))
    return d / "tokenizer.json"


@pytest.fixture(scope="module")
def sp_tokenizer_json(tmp_path_factory):
    """A Llama-2/TinyLlama-family tokenizer.json: ▁ pieces, <0xNN> byte
    fallback, sentencepiece Prepend/Replace normalizer."""
    vocab: dict[str, int] = {"<unk>": 0, "<s>": 1, "</s>": 2}
    for b in range(256):
        vocab[f"<0x{b:02X}>"] = len(vocab)
    # char pieces + a few handcrafted merges
    for ch in "abcdefghijklmnopqrstuvwxyz▁{}\":,.[]0123456789":
        vocab.setdefault(ch, len(vocab))
    merges = [("t", "h"), ("th", "e"), ("▁", "the"), ("c", "a"), ("ca", "t"), ("▁", "cat")]
    for a, b in merges:
        vocab.setdefault(a + b, len(vocab))
    obj = {
        "model": {"type": "BPE", "vocab": vocab, "merges": [f"{a} {b}" for a, b in merges]},
        "normalizer": {
            "type": "Sequence",
            "normalizers": [
                {"type": "Prepend", "prepend": "▁"},
                {"type": "Replace", "pattern": {"String": " "}, "content": "▁"},
            ],
        },
        "added_tokens": [
            {"id": 0, "content": "<unk>", "special": True},
            {"id": 1, "content": "<s>", "special": True},
            {"id": 2, "content": "</s>", "special": True},
        ],
    }
    d = tmp_path_factory.mktemp("sp_tok")
    (d / "tokenizer.json").write_text(json.dumps(obj))
    return d / "tokenizer.json"


class TestHFTokenizer:
    def test_bytelevel_roundtrip(self, bytelevel_tokenizer_json):
        tok = load_hf_tokenizer(bytelevel_tokenizer_json)
        assert tok.kind == "byte_level"
        for text in (
            "search for wireless headphones",
            '{"version":"1.0","intents":[]}',
            "Hello, World! 123",
            "tabs\tand\nnewlines",
        ):
            assert tok.decode(tok.encode(text)) == text

    def test_bytelevel_merges_compress(self, bytelevel_tokenizer_json):
        tok = load_hf_tokenizer(bytelevel_tokenizer_json)
        text = render_prompt("search for shoes", {})
        ids = tok.encode(text)
        # trained merges must beat byte-per-token by a wide margin
        assert len(ids) < 0.6 * len(text.encode())

    def test_bytelevel_merge_order_is_rank_based(self):
        b2u = _byte_to_unicode()
        # vocab: a, b, c, ab, bc — with ("b","c") ranked before ("a","b"):
        # "abc" must become ["a", "bc"], never ["ab", "c"]
        vocab = {b2u[ord(ch)]: i for i, ch in enumerate("abc")}
        vocab[b2u[ord("a")] + b2u[ord("b")]] = 3
        vocab[b2u[ord("b")] + b2u[ord("c")]] = 4
        vocab["</s>"] = 5
        tok = HFTokenizer(
            vocab=vocab,
            merges=[(b2u[ord("b")], b2u[ord("c")]), (b2u[ord("a")], b2u[ord("b")])],
            kind="byte_level",
            added={"</s>": 5},
        )
        assert tok.encode("abc") == [0, 4]

    def test_bytelevel_specials(self, bytelevel_tokenizer_json):
        tok = load_hf_tokenizer(bytelevel_tokenizer_json)
        assert tok.id_of("<|begin_of_text|>") == tok.bos_id
        assert tok.id_of("<|end_of_text|>") == tok.eos_id
        assert tok.token_bytes(tok.eos_id) == b""
        ids = tok.encode("hi", bos=True, eos=True)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        # special strings embedded in text map to their single id
        ids = tok.encode("a<|end_of_text|>b")
        assert tok.eos_id in ids

    def test_sp_roundtrip_and_merges(self, sp_tokenizer_json):
        tok = load_hf_tokenizer(sp_tokenizer_json)
        assert tok.kind == "sentencepiece"
        assert tok.bos_id == 1 and tok.eos_id == 2
        ids = tok.encode("the cat")
        # "▁the" and "▁cat" exist as merged pieces
        assert ids == [tok.vocab["▁the"], tok.vocab["▁cat"]]
        assert tok.decode(ids) == "the cat"

    def test_sp_byte_fallback(self, sp_tokenizer_json):
        tok = load_hf_tokenizer(sp_tokenizer_json)
        ids = tok.encode("caté")  # é not in vocab -> <0xC3><0xA9>
        assert tok.decode(ids) == "caté"
        assert any(tok.id_to_tok[i].startswith("<0x") for i in ids)


class TestVocabSizedFSM:
    def test_fsm_over_hf_vocab_walks_grammar(self, bytelevel_tokenizer_json):
        tok = load_hf_tokenizer(bytelevel_tokenizer_json)
        fsm = build_fsm_for(tok)
        js = (
            '{"version":"1.0","intents":[{"type":"back","target":null,"args":{},'
            '"priority":1,"requires_confirmation":false,"timeout_ms":15000,'
            '"retries":0}],"context_updates":{},"confidence":0.9,'
            '"tts_summary":null,"follow_up_question":null}'
        )
        ids = tok.encode(js)
        state = fsm.walk(ids)
        assert state >= 0 and fsm.accepting[state]
        # EOS allowed exactly at accept
        assert fsm.step(state, tok.eos_id) >= 0
        assert fsm.step(fsm.start, tok.eos_id) < 0

    def test_padded_vocab_ids_are_dead(self, bytelevel_tokenizer_json):
        tok = load_hf_tokenizer(bytelevel_tokenizer_json)
        fsm = build_fsm_for(tok, vocab_size=tok.vocab_size + 64)
        assert fsm.vocab_size == tok.vocab_size + 64
        row = fsm.allowed(fsm.start)
        assert not row[tok.vocab_size:].any()

    def test_compressed_tables_match_dense(self):
        """Column compression must be lossless vs the dense (S, V) view."""
        from tpu_voice_agent.grammar.intent_grammar import build_intent_fsm

        tok, fsm = build_intent_fsm()
        dense = fsm.next_state  # (S, V) via compressed expansion
        rng = np.random.default_rng(0)
        for _ in range(200):
            s = int(rng.integers(0, fsm.num_states))
            t = int(rng.integers(0, fsm.vocab_size))
            assert fsm.step(s, t) == dense[s, t]
        # compression is real: far fewer classes than vocab entries
        assert fsm.num_classes < fsm.vocab_size

    def test_memory_at_llama3_scale_is_sane(self, bytelevel_tokenizer_json):
        """At V=128k the compressed layout must stay in the tens of MB
        (the round-1 dense layout was ~3 GB — VERDICT weak #4)."""
        tok = load_hf_tokenizer(bytelevel_tokenizer_json)
        fsm = TokenFSM(intent_dfa(), tok, vocab_size=128_256)
        nbytes = fsm.table.nbytes + fsm.col_id.nbytes
        assert nbytes < 64 * 1024 * 1024, f"{nbytes/1e6:.0f} MB"


@pytest.fixture(scope="module")
def hf_checkpoint_dir(tmp_path_factory, bytelevel_tokenizer_json):
    """A complete tiny HF Llama checkpoint: config.json + tokenizer.json +
    model.safetensors in HF tensor naming (random weights)."""
    from safetensors.numpy import save_file

    d = tmp_path_factory.mktemp("hf_ckpt")
    tok = load_hf_tokenizer(bytelevel_tokenizer_json)
    vocab_size = tok.vocab_size + 8  # padded embed table, like real ckpts
    cfg = {
        "vocab_size": vocab_size,
        "hidden_size": 64,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "intermediate_size": 128,
        "max_position_embeddings": 4096,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,
    }
    (d / "config.json").write_text(json.dumps(cfg))
    (d / "tokenizer.json").write_text(bytelevel_tokenizer_json.read_text())

    rng = np.random.default_rng(3)
    D, F, NQ, NKV = 64, 128, 4, 2
    hd = D // NQ
    state = {
        "model.embed_tokens.weight": rng.normal(0, 0.05, (vocab_size, D)),
        "model.norm.weight": np.ones((D,)),
    }
    for layer in range(2):
        p = f"model.layers.{layer}."
        state[p + "input_layernorm.weight"] = np.ones((D,))
        state[p + "post_attention_layernorm.weight"] = np.ones((D,))
        state[p + "self_attn.q_proj.weight"] = rng.normal(0, 0.05, (NQ * hd, D))
        state[p + "self_attn.k_proj.weight"] = rng.normal(0, 0.05, (NKV * hd, D))
        state[p + "self_attn.v_proj.weight"] = rng.normal(0, 0.05, (NKV * hd, D))
        state[p + "self_attn.o_proj.weight"] = rng.normal(0, 0.05, (D, NQ * hd))
        state[p + "mlp.gate_proj.weight"] = rng.normal(0, 0.05, (F, D))
        state[p + "mlp.up_proj.weight"] = rng.normal(0, 0.05, (F, D))
        state[p + "mlp.down_proj.weight"] = rng.normal(0, 0.05, (D, F))
    save_file({k: v.astype(np.float32) for k, v in state.items()},
              str(d / "model.safetensors"))
    return d


class TestFromHF:
    def test_engine_serves_real_checkpoint(self, hf_checkpoint_dir):
        """The headline round-2 capability: config.json decides the
        architecture, the checkpoint's own tokenizer drives the FSM, and a
        worst-case (random-weight) model still emits schema-valid JSON."""
        from tpu_voice_agent.serve import DecodeEngine

        eng = DecodeEngine.from_hf(
            str(hf_checkpoint_dir), max_len=4096,
            prefill_buckets=(512, 1024, 2048, 4096),
        )
        assert eng.cfg.vocab_size == eng.tokenizer.vocab_size + 8
        assert eng.eos_id == eng.tokenizer.id_of("<|end_of_text|>")
        res = eng.generate(
            render_prompt("search for mechanical keyboards", {}),
            max_new_tokens=1200, greedy=True,
        )
        assert res.finished, f"no EOS after {res.steps} steps: {res.text[:160]}"
        model, err = parse_response_from_json(res.text)
        assert model is not None, err

    def test_engine_parser_contract(self, hf_checkpoint_dir):
        """EngineParser (the /parse backend) over a real-checkpoint engine
        honors the reference's response contract."""
        from tpu_voice_agent.serve import DecodeEngine
        from tpu_voice_agent.services.brain import EngineParser

        eng = DecodeEngine.from_hf(
            str(hf_checkpoint_dir), max_len=4096,
            prefill_buckets=(512, 1024, 2048, 4096),
        )
        resp = EngineParser(eng, max_new_tokens=1200).parse("go back", {})
        assert resp.version == "1.0"
        assert isinstance(resp.intents, list)

    def test_tinyllama_shape_check(self):
        """hf_import's shape validation covers the real TinyLlama-1.1B
        layout (vocab 32000, GQA 32/4) without materializing 2 GB."""
        from dataclasses import replace

        from tpu_voice_agent.ckpt.hf_import import llama_hf_check
        from tpu_voice_agent.models.llama import PRESETS

        cfg = replace(PRESETS["tinyllama-1.1b"], vocab_size=32000)
        d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
        shapes = {
            "model.embed_tokens.weight": (32000, d),
            "model.norm.weight": (d,),
            "lm_head.weight": (32000, d),
        }
        for layer in range(cfg.n_layers):
            p = f"model.layers.{layer}."
            shapes[p + "input_layernorm.weight"] = (d,)
            shapes[p + "post_attention_layernorm.weight"] = (d,)
            shapes[p + "self_attn.q_proj.weight"] = (cfg.n_heads * hd, d)
            shapes[p + "self_attn.k_proj.weight"] = (cfg.n_kv_heads * hd, d)
            shapes[p + "self_attn.v_proj.weight"] = (cfg.n_kv_heads * hd, d)
            shapes[p + "self_attn.o_proj.weight"] = (d, cfg.n_heads * hd)
            shapes[p + "mlp.gate_proj.weight"] = (f, d)
            shapes[p + "mlp.up_proj.weight"] = (f, d)
            shapes[p + "mlp.down_proj.weight"] = (d, f)
        llama_hf_check(shapes, cfg)  # must not raise

        shapes["model.layers.3.mlp.up_proj.weight"] = (f, d + 1)
        with pytest.raises(ValueError, match="mlp.up_proj"):
            llama_hf_check(shapes, cfg)

    def test_whisper_from_hf_checkpoint(self, tmp_path, bytelevel_tokenizer_json):
        """SpeechEngine.from_hf: config-driven architecture, checkpoint
        tokenizer with whisper control tokens (sot sequence as the decoder
        prompt, specials suppressed in greedy decode)."""
        from safetensors.numpy import save_file

        from tpu_voice_agent.serve.stt import SpeechEngine

        base = json.loads(bytelevel_tokenizer_json.read_text())
        n0 = max(v for v in base["model"]["vocab"].values()) + 1
        specials = ["<|endoftext|>", "<|startoftranscript|>", "<|en|>",
                    "<|transcribe|>", "<|notimestamps|>", "<|0.00|>"]
        base["added_tokens"] = [
            {"id": n0 + i, "content": c, "special": True} for i, c in enumerate(specials)
        ]
        d = tmp_path / "whisper_ckpt"
        d.mkdir()
        (d / "tokenizer.json").write_text(json.dumps(base))
        V = n0 + len(specials)
        D, F, NH = 64, 256, 4
        cfg = {
            "vocab_size": V, "d_model": D, "encoder_attention_heads": NH,
            "decoder_attention_heads": NH, "encoder_layers": 2, "decoder_layers": 2,
            "encoder_ffn_dim": F, "decoder_ffn_dim": F, "num_mel_bins": 80,
            "max_source_positions": 100, "max_target_positions": 64,
        }
        (d / "config.json").write_text(json.dumps(cfg))

        rng = np.random.default_rng(5)
        w = lambda *s: rng.normal(0, 0.05, s).astype(np.float32)
        ones = lambda *s: np.ones(s, dtype=np.float32)
        zeros = lambda *s: np.zeros(s, dtype=np.float32)
        state = {
            "model.encoder.conv1.weight": w(D, 80, 3),
            "model.encoder.conv1.bias": zeros(D),
            "model.encoder.conv2.weight": w(D, D, 3),
            "model.encoder.conv2.bias": zeros(D),
            "model.encoder.layer_norm.weight": ones(D),
            "model.encoder.layer_norm.bias": zeros(D),
            "model.decoder.embed_tokens.weight": w(V, D),
            "model.decoder.embed_positions.weight": w(64, D),
            "model.decoder.layer_norm.weight": ones(D),
            "model.decoder.layer_norm.bias": zeros(D),
        }

        def attn(p):
            state[p + ".q_proj.weight"] = w(D, D)
            state[p + ".q_proj.bias"] = zeros(D)
            state[p + ".k_proj.weight"] = w(D, D)
            state[p + ".v_proj.weight"] = w(D, D)
            state[p + ".v_proj.bias"] = zeros(D)
            state[p + ".out_proj.weight"] = w(D, D)
            state[p + ".out_proj.bias"] = zeros(D)

        for n in range(2):
            p = f"model.encoder.layers.{n}"
            attn(p + ".self_attn")
            for ln in (".self_attn_layer_norm", ".final_layer_norm"):
                state[p + ln + ".weight"] = ones(D)
                state[p + ln + ".bias"] = zeros(D)
            state[p + ".fc1.weight"] = w(F, D)
            state[p + ".fc1.bias"] = zeros(F)
            state[p + ".fc2.weight"] = w(D, F)
            state[p + ".fc2.bias"] = zeros(D)
        for n in range(2):
            p = f"model.decoder.layers.{n}"
            attn(p + ".self_attn")
            attn(p + ".encoder_attn")
            for ln in (".self_attn_layer_norm", ".encoder_attn_layer_norm",
                       ".final_layer_norm"):
                state[p + ln + ".weight"] = ones(D)
                state[p + ln + ".bias"] = zeros(D)
            state[p + ".fc1.weight"] = w(F, D)
            state[p + ".fc1.bias"] = zeros(F)
            state[p + ".fc2.weight"] = w(D, F)
            state[p + ".fc2.bias"] = zeros(D)
        save_file(state, str(d / "model.safetensors"))

        eng = SpeechEngine.from_hf(str(d), frame_buckets=(100, 200), max_new_tokens=12)
        tok = eng.tokenizer
        assert eng.bos_ids == tuple(
            tok.id_of(c) for c in ("<|startoftranscript|>", "<|en|>", "<|transcribe|>",
                                   "<|notimestamps|>")
        )
        assert eng.eos_id == tok.id_of("<|endoftext|>")
        # all control tokens suppressed except EOS
        sup = np.asarray(eng.suppress)
        assert sup[tok.id_of("<|0.00|>")] and not sup[eng.eos_id]

        audio = rng.normal(0, 0.1, 16000).astype(np.float32)
        res = eng.transcribe(audio)
        assert "<|" not in res.text  # decode never emits control tokens

    def test_safetensors_header_shapes(self, hf_checkpoint_dir):
        from tpu_voice_agent.ckpt.hf_import import (
            llama_config_from_hf,
            llama_hf_check,
            safetensors_shapes,
        )

        shapes = safetensors_shapes(str(hf_checkpoint_dir))
        cfg = llama_config_from_hf(str(hf_checkpoint_dir))
        llama_hf_check(shapes, cfg)


def test_from_hf_on_mesh_pads_vocab_to_tp_multiple(hf_checkpoint_dir):
    """from_hf on a dp×tp mesh whose tp does NOT divide the checkpoint
    vocab: the engine pads the model vocab (and the checkpoint's embed and
    lm_head) to a tp multiple, and constrained decode still emits
    schema-valid JSON with the pallas kernels shard_map'd over the mesh."""
    import jax

    from tpu_voice_agent.parallel.mesh import make_mesh
    from tpu_voice_agent.serve import DecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher

    ckpt_vocab = json.loads((hf_checkpoint_dir / "config.json").read_text())["vocab_size"]
    # tp must divide heads/ffn of the tiny checkpoint (4 heads, 128 ffn) but
    # NOT the vocab, so the padding branch actually triggers
    tp = next((t for t in (4, 2) if ckpt_vocab % t), None)
    if tp is None:
        pytest.skip(f"checkpoint vocab {ckpt_vocab} divisible by 2 and 4")
    mesh = make_mesh(dp=2, tp=tp, devices=jax.devices()[: 2 * tp])
    eng = DecodeEngine.from_hf(
        str(hf_checkpoint_dir), mesh=mesh, batch_slots=2, max_len=4096,
        prefill_buckets=(1024, 2048, 4096), kernels="pallas",
    )
    assert eng.cfg.vocab_size % tp == 0
    assert eng.cfg.vocab_size > ckpt_vocab  # padding actually triggered
    assert eng.params["embed"].shape[0] == eng.cfg.vocab_size
    assert eng.params["lm_head"].shape[1] == eng.cfg.vocab_size

    b = ContinuousBatcher(eng, chunk_steps=16, max_new_tokens=1200)
    res = b.generate_many([render_prompt("go back", {})])[0]
    assert res.error is None, res.error
    assert eng.fsm.walk(res.token_ids) >= 0, "mesh decode left the grammar"
    if res.finished:
        model, err = parse_response_from_json(res.text)
        assert model is not None, err


@pytest.fixture(scope="module")
def qwen2vl_hf_checkpoint_dir(tmp_path_factory, bytelevel_tokenizer_json):
    """A complete tiny HF Qwen2-VL checkpoint (config.json with
    vision_config + rope_scaling.mrope_section, tokenizer.json, safetensors
    in Qwen2VLForConditionalGeneration naming) — the real-checkpoint
    grounding path (round-2 VERDICT missing #3)."""
    from safetensors.numpy import save_file

    d = tmp_path_factory.mktemp("hf_qwen2vl")
    tok = load_hf_tokenizer(bytelevel_tokenizer_json)
    vocab_size = tok.vocab_size + 8
    D, F, NQ, NKV, L = 64, 128, 4, 2, 2
    DV, LV, P = 32, 2, 14
    cfg = {
        "vocab_size": vocab_size,
        "hidden_size": D,
        "num_hidden_layers": L,
        "num_attention_heads": NQ,
        "num_key_value_heads": NKV,
        "intermediate_size": F,
        "max_position_embeddings": 4096,
        "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-6,
        "rope_scaling": {"type": "mrope", "mrope_section": [4, 2, 2]},
        "vision_config": {
            "img_size": 112, "patch_size": P, "spatial_merge_size": 2,
            "embed_dim": DV, "num_heads": 2, "depth": LV,
        },
    }
    (d / "config.json").write_text(json.dumps(cfg))
    (d / "tokenizer.json").write_text(bytelevel_tokenizer_json.read_text())

    rng = np.random.default_rng(5)
    hd = D // NQ
    n = lambda *s: rng.normal(0, 0.05, s)
    state = {
        "model.embed_tokens.weight": n(vocab_size, D),
        "model.norm.weight": np.ones((D,)),
        "visual.patch_embed.proj.weight": n(DV, 3, P, P),
        "visual.merger.ln_q.weight": np.ones((DV,)),
        "visual.merger.ln_q.bias": np.zeros((DV,)),
        "visual.merger.mlp.0.weight": n(4 * DV, 4 * DV),
        "visual.merger.mlp.0.bias": np.zeros((4 * DV,)),
        "visual.merger.mlp.2.weight": n(D, 4 * DV),
        "visual.merger.mlp.2.bias": np.zeros((D,)),
    }
    for i in range(LV):
        p = f"visual.blocks.{i}."
        state[p + "norm1.weight"] = np.ones((DV,))
        state[p + "norm1.bias"] = np.zeros((DV,))
        state[p + "norm2.weight"] = np.ones((DV,))
        state[p + "norm2.bias"] = np.zeros((DV,))
        state[p + "attn.qkv.weight"] = n(3 * DV, DV)
        state[p + "attn.qkv.bias"] = np.zeros((3 * DV,))
        state[p + "attn.proj.weight"] = n(DV, DV)
        state[p + "attn.proj.bias"] = np.zeros((DV,))
        state[p + "mlp.fc1.weight"] = n(4 * DV, DV)
        state[p + "mlp.fc1.bias"] = np.zeros((4 * DV,))
        state[p + "mlp.fc2.weight"] = n(DV, 4 * DV)
        state[p + "mlp.fc2.bias"] = np.zeros((DV,))
    for i in range(L):
        p = f"model.layers.{i}."
        state[p + "input_layernorm.weight"] = np.ones((D,))
        state[p + "post_attention_layernorm.weight"] = np.ones((D,))
        state[p + "self_attn.q_proj.weight"] = n(NQ * hd, D)
        state[p + "self_attn.q_proj.bias"] = np.zeros((NQ * hd,))
        state[p + "self_attn.k_proj.weight"] = n(NKV * hd, D)
        state[p + "self_attn.k_proj.bias"] = np.zeros((NKV * hd,))
        state[p + "self_attn.v_proj.weight"] = n(NKV * hd, D)
        state[p + "self_attn.v_proj.bias"] = np.zeros((NKV * hd,))
        state[p + "self_attn.o_proj.weight"] = n(D, NQ * hd)
        state[p + "mlp.gate_proj.weight"] = n(F, D)
        state[p + "mlp.up_proj.weight"] = n(F, D)
        state[p + "mlp.down_proj.weight"] = n(D, F)
    save_file({k: v.astype(np.float32) for k, v in state.items()},
              str(d / "model.safetensors"))
    return d


class TestGroundingFromHF:
    def test_grounds_screenshot_through_hf_checkpoint(self, qwen2vl_hf_checkpoint_dir):
        """Round-2 VERDICT missing #3 closed: a real-HF-format Qwen2-VL
        (true BPE tokenizer.json, padded vocab, safetensors) grounds a
        synthetic screenshot — the 512-vocab toy assertion is gone; the
        point grammar compiles over the checkpoint vocab."""
        from tpu_voice_agent.serve.grounding import GroundingEngine

        eng = GroundingEngine.from_hf(str(qwen2vl_hf_checkpoint_dir), max_len=256)
        assert eng.cfg.vocab_size == eng.tok.vocab_size + 8  # padded embed
        assert eng.fsm.vocab_size == eng.cfg.vocab_size
        img = np.zeros((90, 120, 3), np.uint8)
        img[20:40, 30:80] = 200  # a bright "button"
        res = eng.ground(img, "click the bright button", max_new_tokens=48)
        assert res.raw.startswith('{"point":[')
        if res.ok:
            import json as _json

            obj = _json.loads(res.raw)
            assert 0 <= res.x_norm <= 999 and 0 <= res.y_norm <= 999
            assert isinstance(obj["label"], str)

    def test_executor_grounder_accepts_hf_spec(self, qwen2vl_hf_checkpoint_dir, monkeypatch):
        from tpu_voice_agent.services.executor.server import make_grounder_from_env

        monkeypatch.setenv("EXECUTOR_GROUNDING",
                           f"qwen2vl-hf:{qwen2vl_hf_checkpoint_dir}")
        g = make_grounder_from_env()
        assert g is not None and g.model_dir == str(qwen2vl_hf_checkpoint_dir)


def test_paged_engine_serves_real_checkpoint(hf_checkpoint_dir):
    """Classmethod polymorphism: the paged engine loads HF checkpoints
    through the same from_hf loader (BRAIN_MODEL + BRAIN_PAGED=1 path),
    with subclass knobs (pool_blocks) passing through."""
    from tpu_voice_agent.serve import PagedDecodeEngine
    from tpu_voice_agent.serve.scheduler import ContinuousBatcher
    from tpu_voice_agent.services.prompts import render_prompt

    eng = PagedDecodeEngine.from_hf(str(hf_checkpoint_dir), max_len=2048,
                                    batch_slots=2, pool_blocks=40)
    assert eng.allocator.n_blocks == 40
    res = ContinuousBatcher(eng, chunk_steps=16, max_new_tokens=96).generate_many(
        [render_prompt("scroll down", {})])
    assert res[0].error is None
    assert eng.fsm.walk(res[0].token_ids) >= 0


def test_make_parser_env_routes_paged_checkpoint(hf_checkpoint_dir, monkeypatch):
    """BRAIN_MODEL + BRAIN_PAGED=1 must actually serve the checkpoint
    through the paged engine (the env contract README documents)."""
    from tpu_voice_agent.serve import PagedDecodeEngine
    from tpu_voice_agent.services.brain import make_parser_from_env

    monkeypatch.setenv("BRAIN_MODEL", str(hf_checkpoint_dir))
    monkeypatch.setenv("BRAIN_PAGED", "1")
    monkeypatch.setenv("BRAIN_BATCH", "2")
    monkeypatch.setenv("BRAIN_POOL_BLOCKS", "40")
    # ambient BRAIN_* knobs must not leak into the configuration under test
    for knob in ("BRAIN_QUANT", "BRAIN_MOE", "BRAIN_PREFIX", "BRAIN_CHUNK",
                 "BRAIN_FF", "BRAIN_BACKEND"):
        monkeypatch.delenv(knob, raising=False)
    parser = make_parser_from_env()
    try:
        assert isinstance(parser.engine, PagedDecodeEngine)
        assert parser.engine.allocator.n_blocks == 40
        resp = parser.parse("scroll down", {})
        assert resp.version == "1.0"
    finally:
        parser.close()
