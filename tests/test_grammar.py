"""Grammar engine tests: regex->DFA, schema->regex, tokenizer, token FSM.

The load-bearing property: every byte string the DFA accepts validates under
the pydantic schema (constrained decoding can then never produce invalid
JSON), and every few-shot exemplar in the prompt is representable (the model
is never asked to imitate something the grammar forbids).
"""

import json

import numpy as np
import pytest

from tpu_voice_agent.grammar import compile_regex, Tokenizer
from tpu_voice_agent.grammar.fsm import TokenFSM, sample_dfa
from tpu_voice_agent.grammar.intent_grammar import (
    build_intent_fsm,
    intent_dfa,
    intent_regex,
)
from tpu_voice_agent.grammar.tokenizer import EOS_ID, BOS_ID
from tpu_voice_agent.schemas import parse_response_from_json
from tpu_voice_agent.services.prompts import FEWSHOTS


# ---------------------------------------------------------------- regexlang


@pytest.mark.parametrize(
    "pattern,yes,no",
    [
        ("abc", ["abc"], ["ab", "abcd", ""]),
        ("a|bc", ["a", "bc"], ["b", "abc"]),
        ("a*", ["", "a", "aaaa"], ["b"]),
        ("a+b?", ["a", "ab", "aaab"], ["", "b", "abb"]),
        ("[a-c]{2,3}", ["ab", "abc", "ccc"], ["a", "abcd", "ad"]),
        (r"\d{1,2}", ["7", "42"], ["", "123", "x"]),
        (r"[^a-z]", ["A", "0", " "], ["a", "z", ""]),
        (r"(ab){2}", ["abab"], ["ab", "ababab"]),
        (r"a{2,}", ["aa", "aaaa"], ["a", ""]),
        (r"\[x\]", ["[x]"], ["x"]),
        # escaped char anchoring a range (the bug found during bring-up)
        (r"[\]-~]", ["]", "^", "t", "~"], ["[", " "]),
    ],
)
def test_regex_matches(pattern, yes, no):
    dfa = compile_regex(pattern)
    for s in yes:
        assert dfa.matches(s.encode()), f"{pattern} should match {s!r}"
    for s in no:
        assert not dfa.matches(s.encode()), f"{pattern} should reject {s!r}"


def test_inverted_ranges_raise():
    with pytest.raises(ValueError):
        compile_regex("[z-a]")
    with pytest.raises(ValueError):
        compile_regex("a{3,1}")


def test_numeric_bounds_are_exact():
    from tpu_voice_agent.grammar.jsonschema import _int_regex, _num_regex, int_range_regex

    d = compile_regex(_int_regex(10, 99))
    assert d.matches(b"10") and d.matches(b"57") and d.matches(b"99")
    assert not d.matches(b"0") and not d.matches(b"9") and not d.matches(b"100")

    d = compile_regex(_int_regex(-5, 5))
    assert d.matches(b"-5") and d.matches(b"0") and d.matches(b"5")
    assert not d.matches(b"-6") and not d.matches(b"6") and not d.matches(b"-999999999")

    d = compile_regex(_num_regex(0, 10.0))
    assert d.matches(b"9.999999") and d.matches(b"10.0") and d.matches(b"0.5")
    assert not d.matches(b"10.5") and not d.matches(b"999999999") and not d.matches(b"-1")

    d = compile_regex(int_range_regex(0, 120000))
    assert d.matches(b"120000") and d.matches(b"99999") and not d.matches(b"120001")


def test_min_items_enforced():
    from tpu_voice_agent.grammar.jsonschema import schema_to_regex

    rx = schema_to_regex({"type": "array", "items": {"type": "boolean"}, "minItems": 2, "maxItems": 4})
    d = compile_regex(rx)
    assert not d.matches(b"[true]")
    assert d.matches(b"[true,false]") and d.matches(b"[true,false,true,true]")
    assert not d.matches(b"[true,false,true,true,true]")


def test_json_string_pattern():
    from tpu_voice_agent.grammar.jsonschema import STRING

    dfa = compile_regex(STRING)
    assert dfa.matches(b'"hello world"')
    assert dfa.matches(b'""')
    assert dfa.matches(rb'"a\"b\\c\nd"')
    assert not dfa.matches(b'"unterminated')
    assert not dfa.matches(b'"raw"quote"')


# ---------------------------------------------------------------- intent grammar


def test_intent_dfa_accepts_every_fewshot():
    dfa = intent_dfa()
    for _, resp in FEWSHOTS:
        payload = json.dumps(resp, separators=(",", ":")).encode()
        assert dfa.matches(payload), f"grammar must accept fewshot: {payload[:80]}"


def test_intent_dfa_rejects_structural_garbage():
    dfa = intent_dfa()
    assert not dfa.matches(b"{}")
    assert not dfa.matches(b'{"version":"2.0","intents":[],"context_updates":{},"confidence":0.5,"tts_summary":null,"follow_up_question":null}')
    assert not dfa.matches(b'{"version":"1.0","intents":[{"type":"fly"}],"context_updates":{},"confidence":0.5,"tts_summary":null,"follow_up_question":null}')


def test_sampled_strings_always_validate():
    dfa = intent_dfa()
    rng = np.random.default_rng(1234)
    for _ in range(100):
        sample = sample_dfa(dfa, rng)
        model, err = parse_response_from_json(sample.decode())
        assert model is not None, f"DFA sample failed schema: {err} :: {sample[:120]}"


def test_intent_regex_is_compact_json():
    assert " " not in intent_regex().replace("[ ", "").replace(" !", "")


# ---------------------------------------------------------------- tokenizer


def test_tokenizer_roundtrip_ascii_and_unicode():
    tok = Tokenizer.build(corpus=["the quick brown fox"], literals=['"type":'])
    for text in ["hello world", '{"type":"search"}', "café ☕ non-ascii", ""]:
        assert tok.decode(tok.encode(text)) == text


def test_tokenizer_uses_schema_literals():
    tok, _ = build_intent_fsm()
    ids = tok.encode('{"version":"1.0","intents":[')
    # the whole prefix is one injected literal
    assert len(ids) == 1


def test_tokenizer_bos_eos():
    tok, _ = build_intent_fsm()
    ids = tok.encode("x", bos=True, eos=True)
    assert ids[0] == BOS_ID and ids[-1] == EOS_ID


# ---------------------------------------------------------------- token FSM


def test_fsm_walk_fewshots_to_accept():
    tok, fsm = build_intent_fsm()
    for _, resp in FEWSHOTS:
        payload = json.dumps(resp, separators=(",", ":"))
        state = fsm.walk(tok.encode(payload))
        assert state >= 0 and fsm.accepting[state]
        assert fsm.mask[state, EOS_ID], "EOS must be allowed at accept"


def test_fsm_masks_disallow_garbage_from_start():
    tok, fsm = build_intent_fsm()
    start_allowed = fsm.mask[fsm.start]
    # 'z' byte token can never start the JSON
    z_id = tok.encode("z")[0]
    assert not start_allowed[z_id]
    # the canonical opening literal must be allowed
    open_id = tok.encode('{"version":"1.0","intents":[')[0]
    assert start_allowed[open_id]
    assert not start_allowed[EOS_ID]


def test_fsm_every_live_state_has_a_move():
    _, fsm = build_intent_fsm()
    # no live state may be a dead end with EOS disallowed (decode would stall)
    stuck = ~fsm.mask.any(axis=1)
    assert not stuck.any(), f"{stuck.sum()} states have no allowed token"
