"""Paged KV cache (SURVEY §7 step 2): kernel, allocator, engine parity.

The paged engine must reproduce the dense engine's behavior through the
continuous batcher while holding only live tokens in HBM and sharing the
prompt-prefix blocks across slots.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.ops import paged_attention, paged_attention_reference
from tpu_voice_agent.serve import DecodeEngine, PagedDecodeEngine
from tpu_voice_agent.serve.paged import BlockAllocator
from tpu_voice_agent.serve.scheduler import ContinuousBatcher
from tpu_voice_agent.services.brain import install_prompt_prefix
from tpu_voice_agent.services.prompts import render_prompt


# ---------------------------------------------------------------- kernel


def test_paged_attention_matches_reference():
    L, N, bs, B, nq, nkv, hd = 2, 12, 16, 3, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (L, N, bs, nkv, hd), jnp.float32)
    v_pool = jax.random.normal(ks[2], (L, N, bs, nkv, hd), jnp.float32)
    # rows own disjoint, deliberately out-of-order blocks
    tables = jnp.asarray([[3, 7, 1, 0], [5, 2, 9, 4], [11, 6, 8, 10]], jnp.int32)
    kv_len = jnp.asarray([5, 40, 64], jnp.int32)
    for layer in (0, 1):
        out = paged_attention(q, k_pool, v_pool, tables, kv_len, jnp.int32(layer))
        ref = paged_attention_reference(q, k_pool, v_pool, tables, kv_len, layer)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------- allocator


def test_allocator_refcounts_and_exhaustion():
    a = BlockAllocator(6)  # block 0 reserved -> 5 usable
    x = a.alloc(3)
    assert 0 not in x and a.blocks_in_use == 3
    a.ref(x[:1])  # shared
    a.free(x)
    assert a.blocks_in_use == 1  # the ref'd block survives
    a.free(x[:1])
    assert a.blocks_in_use == 0
    a.alloc(5)
    with pytest.raises(RuntimeError, match="exhausted"):
        a.alloc(1)


# ---------------------------------------------------------------- engine


def _dense(slots):
    return DecodeEngine(preset="test-tiny", max_len=2048, batch_slots=slots,
                        prefill_buckets=(128, 256, 512, 1024))


def _paged(slots, **kw):
    return PagedDecodeEngine(preset="test-tiny", max_len=2048, batch_slots=slots,
                             prefill_buckets=(128, 256, 512, 1024), **kw)


PROMPTS = [
    render_prompt("search for laptops under 1000", {}),
    render_prompt("upload my resume and submit", {}),
    render_prompt("take a screenshot of this page", {}),
]


@pytest.mark.parametrize("with_prefix", [False, True])
def test_paged_batcher_matches_dense(with_prefix):
    dense = _dense(3)
    paged = _paged(3)
    if with_prefix:
        install_prompt_prefix(dense)
        install_prompt_prefix(paged)
    rd = ContinuousBatcher(dense, chunk_steps=16, max_new_tokens=200).generate_many(PROMPTS)
    rp = ContinuousBatcher(paged, chunk_steps=16, max_new_tokens=200).generate_many(PROMPTS)
    for d, p in zip(rd, rp):
        assert d.error is None and p.error is None
        assert paged.fsm.walk(p.token_ids) >= 0
        assert d.token_ids == p.token_ids, (d.text[:80], p.text[:80])


def test_prefix_blocks_are_shared_not_copied():
    eng = _paged(3)
    P = install_prompt_prefix(eng)
    bs = eng.block_size
    full = P // bs
    assert full >= 1
    base = eng.allocator.blocks_in_use  # the shared prefix blocks
    assert base == full
    b = ContinuousBatcher(eng, chunk_steps=16, max_new_tokens=48)
    for p in PROMPTS:
        b.submit(p)
    b.step()  # admits all three
    # three slots live, but the prefix full-blocks exist ONCE in the pool
    per_slot_owned = [len(o) for o in eng._slot_owned]
    assert all(o >= 1 for o in per_slot_owned)
    assert eng.allocator.blocks_in_use == base + sum(per_slot_owned)
    for s in eng._slot_shared:
        assert s == eng._prefix_blocks[0][:full]  # group 0 (no mesh -> one group)
    b.run_until_done()
    # completed requests returned their blocks; the shared prefix survives
    assert eng.allocator.blocks_in_use == base


def test_pool_memory_tracks_live_tokens_not_budgets():
    """The point of paging: with the prefix shared, a pool far smaller than
    slots*max_len (48 blocks vs the dense layout's equivalent of 3*16)
    serves three concurrent requests."""
    eng = _paged(3, pool_blocks=24)  # 23 usable blocks * 128 = 2944 positions
    install_prompt_prefix(eng)  # ~7 blocks, stored once for all slots
    b = ContinuousBatcher(eng, chunk_steps=16, max_new_tokens=64)
    res = b.generate_many(PROMPTS)
    for r in res:
        assert r.error is None
        assert eng.fsm.walk(r.token_ids) >= 0
    # per-request blocks returned; only the installed prefix stays resident
    assert eng.allocator.blocks_in_use == len(eng._prefix_blocks[0])


def test_pool_exhaustion_fails_the_request_not_the_engine():
    eng = _paged(2, pool_blocks=10)  # 9 usable: one admission fits, two don't
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=32)
    r1, r2 = b.generate_many([PROMPTS[0], PROMPTS[1]])
    # at least one completes; any failure is the clean pool-exhausted error
    ok = [r for r in (r1, r2) if r.error is None]
    bad = [r for r in (r1, r2) if r.error is not None]
    assert ok, "pool sized for one request must serve at least one"
    for r in bad:
        assert "exhausted" in r.error


def test_paged_generate_is_rejected():
    eng = _paged(1)
    with pytest.raises(ValueError, match="batcher"):
        eng.generate("x")


# ---------------------------------------------------------------- mesh


@pytest.fixture(scope="module")
def mesh():
    from tpu_voice_agent.parallel.mesh import make_mesh

    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_mesh(dp=2, tp=2)


def test_sharded_paged_attention_matches_single_device(mesh):
    """Pool blocks shard over dp, kv heads over tp; each row's table only
    references its own dp group's block range (the allocator invariant)."""
    from tpu_voice_agent.ops import sharded_paged_attention

    L, N, bs, B, nq, nkv, hd = 2, 16, 16, 4, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (L, N, bs, nkv, hd), jnp.float32)
    v_pool = jax.random.normal(ks[2], (L, N, bs, nkv, hd), jnp.float32)
    # rows 0-1 (dp group 0) use blocks 1..7; rows 2-3 (group 1) blocks 9..15
    tables = jnp.asarray(
        [[3, 7, 1, 2], [5, 2, 6, 4], [11, 14, 8, 10], [15, 9, 13, 12]], jnp.int32)
    kv_len = jnp.asarray([5, 40, 64, 17], jnp.int32)
    for layer in (0, 1):
        ref = paged_attention_reference(q, k_pool, v_pool, tables, kv_len, layer)
        out = sharded_paged_attention(
            mesh, q, k_pool, v_pool, tables, kv_len, jnp.int32(layer))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


MESH_PROMPTS = PROMPTS + [render_prompt("sort results by price low to high", {})]


@pytest.mark.parametrize("kernels", ["xla", "pallas"])
def test_paged_batcher_on_mesh_matches_dense_single_device(mesh, kernels):
    """The meshed paged engine (pool dp-sharded, kv heads tp-sharded, int8
    aside) must be token-identical to the single-device dense engine.

    Identical float32 weights go into both: the mesh engine pads its vocab
    to a tp multiple (changing any random init), and GSPMD's tp-split
    contractions reorder f32 partial sums enough to flip greedy argmax on
    random bf16 weights."""
    from tpu_voice_agent.models.llama import init_params

    dense = DecodeEngine(preset="test-tiny", max_len=2048, batch_slots=4,
                         prefill_buckets=(128, 256, 512, 1024),
                         init_weights=False)
    paged = PagedDecodeEngine(
        preset="test-tiny", max_len=2048, batch_slots=4,
        prefill_buckets=(128, 256, 512, 1024), mesh=mesh, kernels=kernels,
        init_weights=False)
    raw = init_params(dense.cfg, jax.random.PRNGKey(21), dtype=jnp.float32)
    dense.load_params(raw)
    pad = paged.cfg.vocab_size - dense.cfg.vocab_size
    padded = dict(raw)
    padded["embed"] = jnp.pad(raw["embed"], ((0, pad), (0, 0)))
    padded["lm_head"] = jnp.pad(raw["lm_head"], ((0, 0), (0, pad)))
    paged.load_params(padded)
    install_prompt_prefix(dense)
    install_prompt_prefix(paged)
    rd = ContinuousBatcher(dense, chunk_steps=16, max_new_tokens=160).generate_many(MESH_PROMPTS)
    rp = ContinuousBatcher(paged, chunk_steps=16, max_new_tokens=160).generate_many(MESH_PROMPTS)
    for d, p in zip(rd, rp):
        assert d.error is None and p.error is None
        assert paged.fsm.walk(p.token_ids) >= 0
        assert d.token_ids == p.token_ids, (d.text[:80], p.text[:80])
    # slots landed in their own dp group's block ranges
    bpg = paged.allocator.blocks_per_group
    for slot in range(4):
        g = paged._group(slot)
        for blk in paged._slot_owned[slot] + paged._slot_shared[slot]:
            assert g * bpg <= blk < (g + 1) * bpg


def test_sharded_paged_attention_rejects_dp_indivisible(mesh):
    """Round-3 advisor: with the pool physically sharded over dp, a silent
    fallback to replicated in_specs would make GSPMD all-gather the whole
    KV pool per layer. The public op must raise, not degrade."""
    from tpu_voice_agent.ops import sharded_paged_attention

    L, N, bs, B, nq, nkv, hd = 1, 16, 16, 3, 8, 4, 32  # B=3 % dp=2 != 0
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, nq, hd), jnp.float32)
    k_pool = jax.random.normal(ks[1], (L, N, bs, nkv, hd), jnp.float32)
    v_pool = jax.random.normal(ks[2], (L, N, bs, nkv, hd), jnp.float32)
    tables = jnp.zeros((B, 4), jnp.int32)
    kv_len = jnp.asarray([5, 6, 7], jnp.int32)
    with pytest.raises(ValueError, match="divisible by dp"):
        sharded_paged_attention(mesh, q, k_pool, v_pool, tables, kv_len,
                                jnp.int32(0))


def test_paged_block_attention_matches_contiguous_reference():
    """The batched-ff paged kernel: T queries against pool blocks must
    equal the dense block reference over the gathered contiguous cache
    (per-query causality, non-contiguous tables, multiple layers)."""
    from tpu_voice_agent.ops import paged_block_attention
    from tpu_voice_agent.ops.decode_attention import (
        decode_block_attention_reference,
    )

    L, N, bs, B, T, nq, nkv, hd = 2, 8, 16, 3, 4, 8, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, nq, hd), jnp.float32)
    kp = jax.random.normal(ks[1], (L, N, bs, nkv, hd), jnp.float32)
    vp = jax.random.normal(ks[2], (L, N, bs, nkv, hd), jnp.float32)
    tables = jnp.asarray([[1, 2, 0, 0], [3, 4, 5, 0], [6, 7, 1, 2]], jnp.int32)
    q_pos = jnp.asarray([[5, 6, 7, 8], [20, 21, 22, 22], [30, 31, 32, 33]],
                        jnp.int32)
    for li in range(L):
        kc = kp[li][tables].reshape(B, 4 * bs, nkv, hd)
        vc = vp[li][tables].reshape(B, 4 * bs, nkv, hd)
        ref = decode_block_attention_reference(q, kc, vc, q_pos)
        out = paged_block_attention(q, kp, vp, tables, q_pos, jnp.int32(li))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_ff_coverage_reconciles_to_actual_frontier():
    """decode_chunk claims the worst-case ff span before dispatch; the
    scheduler's reconcile hook must clamp the growth target back to the
    REAL frontier so the claim never compounds across chunks (a grammar
    that rarely forces chains would otherwise race every table to
    max_len), and a tight pool must still serve ff requests."""
    eng = _paged(3, pool_blocks=40, fast_forward=8)
    install_prompt_prefix(eng)
    b = ContinuousBatcher(eng, chunk_steps=8, max_new_tokens=96)
    res = b.generate_many(PROMPTS)
    for r in res:
        assert r.error is None
        assert eng.fsm.walk(r.token_ids) >= 0
    # direct contract: the hook clamps live slots only
    eng._slot_owned[0] = [5]
    eng._slot_owned[1] = []
    eng._next_pos[0] = 4000
    eng._next_pos[1] = 4000
    eng.reconcile_coverage(np.asarray([950, 123, 0]))
    assert eng._next_pos[0] == 950
    assert eng._next_pos[1] == 4000  # dead slot untouched (stale pos row)
