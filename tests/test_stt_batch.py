"""Multi-stream batched STT plane (serve/stt_batch.py): differential
token-identity vs the B=1 per-connection path for every work kind, batcher
priority/coalescing/shed units, the StreamingSTT-level event differential,
feed_async, and the stream-gauge aggregation fix.

Fast tier on purpose (unlike test_stt's compile-heavy module): the
batched-vs-single identity contract is the acceptance bar of the batched
plane and must gate every tier-1 run.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_voice_agent.audio.endpoint import EnergyEndpointer
from tpu_voice_agent.models.whisper import init_self_cache, pad_cross_kv
from tpu_voice_agent.serve.stt import SpeechEngine, StreamingSTT, _stt_decode_loop
from tpu_voice_agent.serve.stt_batch import BatchedStreamingSTT, STTBatcher


def tone(freq, dur_s, amp=0.3, sr=16_000):
    t = np.arange(int(dur_s * sr)) / sr
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


@pytest.fixture(scope="module")
def engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(50, 100, 200),
                        max_new_tokens=16)


@pytest.fixture()
def batcher(engine):
    b = STTBatcher(engine, slots=4)
    yield b
    b.stop()


def test_batched_finals_token_identical_ragged_buckets(engine, batcher):
    """Four finals spanning every bucket decoded in ONE batch must be
    token-identical to engine.transcribe per slot (ragged enc lengths)."""
    audios = [tone(300, 0.4), tone(440, 0.9), tone(520, 1.8), tone(260, 0.3)]
    singles = [engine.transcribe(a).text for a in audios]
    futs = [batcher.submit("final", 9000 + i, a) for i, a in enumerate(audios)]
    assert [f.result(timeout=60).text for f in futs] == singles


def test_batched_spec_final_token_identical(engine, batcher):
    a = tone(410, 0.7)
    res = batcher.submit("spec_final", 9100, a).result(timeout=60)
    assert res.text == engine.transcribe(a).text


def test_batched_partials_token_identical_and_slot_persistent(engine, batcher):
    """Partials decode the pool slot's incremental cross-KV; identity vs a
    per-connection IncrementalState fed the same audio, across TWO rounds
    (the slot persists between ticks)."""
    hop = engine.mel_cfg.hop
    b1, b2 = tone(330, 1.0), tone(400, 1.5)
    st1 = engine.incremental_feed(engine.incremental_init(len(b1) // hop), b1)
    st2 = engine.incremental_feed(engine.incremental_init(len(b2) // hop), b2)
    f1 = batcher.submit("partial", 9201, b1)
    f2 = batcher.submit("partial", 9202, b2)
    assert f1.result(timeout=60).text == engine.incremental_decode(st1).text
    assert f2.result(timeout=60).text == engine.incremental_decode(st2).text
    g1 = np.concatenate([b1, tone(350, 0.5)])
    st1 = engine.incremental_feed(st1, g1)
    assert (batcher.submit("partial", 9201, g1).result(timeout=60).text
            == engine.incremental_decode(st1).text)


def test_batched_partial_reanchor_matches_b1(engine, batcher):
    """An utterance outgrowing the cross-KV budget re-anchors in the pool
    slot exactly like the B=1 state (no silent freeze, same transcript)."""
    hop = engine.mel_cfg.hop
    b = tone(440, 1.0)
    st = engine.incremental_feed(engine.incremental_init(len(b) // hop), b)
    batcher.submit("partial", 9301, b).result(timeout=60)
    g = np.concatenate([b, tone(380, 2.0)])  # >> 2 s budget
    st = engine.incremental_feed(st, g)
    assert (batcher.submit("partial", 9301, g).result(timeout=60).text
            == engine.incremental_decode(st).text)


def test_decode_loop_mid_batch_eos_and_ragged_budgets(engine):
    """The batched loop with per-slot budgets: each row stops at its OWN
    limit (mid-batch termination) and emits exactly what a B=1 loop with
    the same budget emits."""
    P = engine.cfg.enc_positions
    audios = [tone(300, 0.4), tone(440, 0.9), tone(520, 1.2), tone(260, 0.6)]
    kvs, masks = [], []
    for a in audios:
        kv, _, n_frames = engine._encode_window(a)
        kvs.append(pad_cross_kv(kv, P))
        # P-shaped masks (the batched plane's layout; padding is masked)
        masks.append(jnp.arange(P)[None, :] < max(1, n_frames // 2))
    ck = {"k": jnp.concatenate([kv["k"] for kv in kvs], axis=1),
          "v": jnp.concatenate([kv["v"] for kv in kvs], axis=1)}
    mask_b = jnp.concatenate(masks, axis=0)
    budgets = np.array([3, 16, 1, 8], dtype=np.int32)
    bos = jnp.broadcast_to(
        jnp.asarray(list(engine.bos_ids), jnp.int32)[None, :], (4, 1))
    out_b, n_b, _, _ = _stt_decode_loop(
        engine.params, engine.cfg,
        init_self_cache(engine.cfg, 4, dtype=engine._param_dtype),
        ck, mask_b, bos, engine.suppress,
        live=jnp.ones((4,), bool), max_new_each=jnp.asarray(budgets),
        max_new=16, eos_id=engine.eos_id, pad_id=engine.pad_id,
    )
    out_b, n_b = np.asarray(out_b), np.asarray(n_b)
    assert (n_b <= budgets).all()
    assert n_b[2] <= 1 < n_b[1]  # ragged: row 2 parked while row 1 ran on
    for i in range(4):
        o1, n1, _, _ = _stt_decode_loop(
            engine.params, engine.cfg,
            init_self_cache(engine.cfg, 1, dtype=engine._param_dtype),
            kvs[i], masks[i], bos[:1], engine.suppress,
            max_new_each=jnp.asarray(budgets[i:i + 1]),
            max_new=16, eos_id=engine.eos_id, pad_id=engine.pad_id,
        )
        assert np.array_equal(out_b[i, : n_b[i]],
                              np.asarray(o1)[0, : int(np.asarray(n1)[0])])


def test_batcher_priority_and_coalescing(engine):
    """finals > spec_finals > partials; a newer partial for the same
    utterance supersedes the queued stale one (resolved None + counted)."""
    from tpu_voice_agent.utils import get_metrics

    b = STTBatcher(engine, slots=2, autostart=False)
    a = tone(300, 0.5)
    c0 = get_metrics().snapshot()["counters"].get("stt.partials_coalesced", 0)
    p1 = b.submit("partial", 1, a)
    p2 = b.submit("partial", 1, tone(300, 0.6))  # supersedes p1
    sp = b.submit("spec_final", 2, a)
    fi = b.submit("final", 3, a)
    assert p1.done() and p1.result() is None
    assert get_metrics().snapshot()["counters"]["stt.partials_coalesced"] == c0 + 1
    # width 2: the first tick takes [final, spec_final]; the partial waits
    b.tick()
    assert fi.done() and sp.done() and not p2.done()
    assert fi.result().text == engine.transcribe(a).text
    b.tick()
    assert p2.done() and p2.result() is not None


def test_batcher_sheds_partials_under_overload(engine):
    """Admission control at submit (resilience convention): partials beyond
    the slot pool or the bounded queue shed with stt.shed_overload; finals
    are always admitted."""
    from tpu_voice_agent.utils import get_metrics

    # slot-pool exhaustion: one slot, four concurrent utterances — only the
    # first partial gets a slot, the rest shed AT SUBMIT
    b = STTBatcher(engine, slots=1, autostart=False)
    a = tone(300, 0.3)
    s0 = get_metrics().snapshot()["counters"].get("stt.shed_overload", 0)
    futs = [b.submit("partial", 100 + i, a) for i in range(4)]
    shed = [f for f in futs if f.done() and f.result() is None]
    assert len(shed) == 3
    assert get_metrics().snapshot()["counters"]["stt.shed_overload"] == s0 + 3
    f = b.submit("final", 999, a)
    assert not f.done()  # admitted despite the exhausted pool
    while b.tick():
        pass
    assert f.result(timeout=5).text == engine.transcribe(a).text

    # bounded queue: plenty of slots, but the pending cap sheds the second
    # utterance's partial before it queues
    b2 = STTBatcher(engine, slots=4, max_pending=1, autostart=False)
    s1 = get_metrics().snapshot()["counters"].get("stt.shed_overload", 0)
    q1 = b2.submit("partial", 201, a)
    q2 = b2.submit("partial", 202, a)
    assert not q1.done() and q2.done() and q2.result() is None
    assert get_metrics().snapshot()["counters"]["stt.shed_overload"] == s1 + 1


def test_batcher_slot_exhaustion_sheds_partial_not_final(engine):
    """More concurrent utterances than pool slots: the un-slotted
    utterance's partial sheds, its final still transcribes."""
    b = STTBatcher(engine, slots=1, autostart=False)
    a1, a2 = tone(320, 0.8), tone(430, 0.8)
    f1 = b.submit("partial", 501, a1)
    b.tick()
    assert f1.result(timeout=5) is not None  # owns the only slot
    f2 = b.submit("partial", 502, a2)
    b.tick()
    assert f2.result(timeout=5) is None  # no slot left: shed
    fin = b.submit("final", 502, a2)
    b.tick()
    assert fin.result(timeout=5).text == engine.transcribe(a2).text
    # releasing the slotted utterance frees the slot for the next one
    b.release(501)
    f3 = b.submit("partial", 503, a2)
    b.tick()
    assert f3.result(timeout=5) is not None


def test_release_mid_flight_partial_never_leaks_the_slot(engine):
    """Regression: an utterance closing while its partial is already in the
    worker's batch must NOT re-acquire its slot (slots are reserved at
    submit and freed by release; a worker-side re-alloc for a closed
    utterance id could never be released again — a permanent leak)."""
    b = STTBatcher(engine, slots=1, autostart=False)
    a = tone(320, 0.8)
    f = b.submit("partial", 601, a)
    with b._wake:
        batch = b._take_batch_locked()  # in flight: popped, not yet processed
    b.release(601)  # endpoint closed the utterance meanwhile
    b._process(batch)
    assert f.result(timeout=5) is None  # dropped, not decoded
    assert b.slot_of == {} and b.slot_state == [None]  # slot stayed free
    f2 = b.submit("partial", 602, a)  # ...and is reusable
    b.tick()
    assert f2.result(timeout=5) is not None


def test_batched_streaming_matches_base_events(engine, batcher):
    """Differential e2e at the StreamingSTT level: the same chunk sequence
    through the base (inline) and batched planes yields the same events —
    async delivery may shift WHEN a partial/spec surfaces, but every text
    is identical and the final matches exactly."""

    def run(stt, batched):
        events = []
        chunks = [tone(300, 0.6)] + [np.zeros(16_000 * 60 // 1000, np.float32)] * 12
        for c in chunks:
            events += stt.feed(c)
            if batched:
                assert batcher.drain(timeout_s=30)  # deliveries land before the next feed
        return events

    base = StreamingSTT(
        engine, partial_interval_s=0.2,
        endpointer=EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100))
    bat = BatchedStreamingSTT(
        engine, batcher, partial_interval_s=0.2,
        endpointer=EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100))
    eb = run(base, batched=False)
    eB = run(bat, batched=True)
    assert sorted(eb) == sorted(eB)
    assert [t for k, t in eb if k == "final"] == [t for k, t in eB if k == "final"]


def test_batched_feed_async_delivers_identical_final(engine, batcher):
    """feed_async awaits the final's future instead of blocking a thread;
    the delivered final equals the base plane's."""
    import asyncio

    chunks = [tone(300, 0.6)] + [np.zeros(16_000 * 60 // 1000, np.float32)] * 12
    base = StreamingSTT(
        engine, partial_interval_s=60.0,
        endpointer=EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100))
    ref_finals = [t for c in chunks for k, t in base.feed(c) if k == "final"]

    stt = BatchedStreamingSTT(
        engine, batcher, partial_interval_s=60.0,
        endpointer=EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100))

    async def drive():
        evs = []
        for c in chunks:
            evs += await stt.feed_async(c)
        return evs

    evs = asyncio.run(drive())
    assert [t for k, t in evs if k == "final"] == ref_finals


def test_stream_gauges_aggregate_across_instances(engine):
    """The gauge-stomp fix: concurrent streams must not overwrite each
    other — buffered seconds SUM across live instances (and lag is a max,
    so one saturated stream keeps the alarm up)."""
    from tpu_voice_agent.utils import get_metrics

    s1 = StreamingSTT(engine, partial_interval_s=60.0)
    s2 = StreamingSTT(engine, partial_interval_s=60.0)
    s1.feed(tone(300, 0.5))
    g1 = get_metrics().snapshot()["gauges"]["stt.buffered_audio_s"]
    s2.feed(tone(400, 0.3))
    g2 = get_metrics().snapshot()["gauges"]["stt.buffered_audio_s"]
    # the second stream's feed ADDED its buffer to the aggregate instead of
    # replacing the first stream's 0.5 s with its own 0.3 s
    assert g2 >= g1 + 0.25
