"""STT engine: transcription pipeline runs end to end and is deterministic."""

import numpy as np
import pytest

from tpu_voice_agent.serve.stt import NullSTT, SpeechEngine, StreamingSTT
from tpu_voice_agent.audio.endpoint import EnergyEndpointer


def tone(freq, dur_s, amp=0.3, sr=16_000):
    t = np.arange(int(dur_s * sr)) / sr
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


@pytest.fixture(scope="module")
def engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(50, 100, 200), max_new_tokens=16)


def test_transcribe_runs_and_is_deterministic(engine):
    audio = tone(440, 1.0)
    a = engine.transcribe(audio)
    b = engine.transcribe(audio)
    assert a.text == b.text
    assert a.n_frames == 100 and a.encode_ms > 0


def test_transcribe_window_truncates_to_top_bucket(engine):
    long_audio = tone(440, 10.0)  # 1000 frames >> top bucket 200
    res = engine.transcribe(long_audio)
    assert res.n_frames == 200


def test_streaming_emits_final_on_endpoint(engine):
    stt = StreamingSTT(
        engine,
        partial_interval_s=0.2,
        endpointer=EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=100),
    )
    events = []
    events += stt.feed(tone(300, 0.6))
    events += stt.feed(np.zeros(16_000 // 2, dtype=np.float32))
    kinds = [k for k, _ in events]
    assert "final" in kinds or len(stt._buf) == 0  # final fired (empty-text finals are dropped)
    # buffer reset after the utterance closed
    assert len(stt._buf) == 0


def test_null_stt_scripted():
    stt = NullSTT(scripted=[("final", "search for shoes")])
    events = stt.feed(np.zeros(160, dtype=np.float32))
    assert events == [("final", "search for shoes")]
    assert stt.feed(np.zeros(160, dtype=np.float32)) == []
