"""STT engine: transcription pipeline runs end to end and is deterministic."""

import numpy as np
import pytest

from tpu_voice_agent.serve.stt import NullSTT, SpeechEngine, StreamingSTT
from tpu_voice_agent.audio.endpoint import EnergyEndpointer


def tone(freq, dur_s, amp=0.3, sr=16_000):
    t = np.arange(int(dur_s * sr)) / sr
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


@pytest.fixture(scope="module")
def engine():
    return SpeechEngine(preset="whisper-test", frame_buckets=(50, 100, 200), max_new_tokens=16)


def test_transcribe_runs_and_is_deterministic(engine):
    audio = tone(440, 1.0)
    a = engine.transcribe(audio)
    b = engine.transcribe(audio)
    assert a.text == b.text
    assert a.n_frames == 100 and a.encode_ms > 0


def test_transcribe_window_truncates_to_top_bucket(engine):
    long_audio = tone(440, 10.0)  # 1000 frames >> top bucket 200
    res = engine.transcribe(long_audio)
    assert res.n_frames == 200


def test_streaming_emits_final_on_endpoint(engine):
    stt = StreamingSTT(
        engine,
        partial_interval_s=0.2,
        endpointer=EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=100),
    )
    events = []
    events += stt.feed(tone(300, 0.6))
    events += stt.feed(np.zeros(16_000 // 2, dtype=np.float32))
    kinds = [k for k, _ in events]
    assert "final" in kinds or len(stt._buf) == 0  # final fired (empty-text finals are dropped)
    # buffer reset after the utterance closed
    assert len(stt._buf) == 0


def test_incremental_feed_accumulates_and_decodes(engine):
    """2 s of audio -> four 0.5 s blocks -> enc buffer full (whisper-test
    enc_positions=100 = 4 x 25); decode is deterministic over the buffer."""
    st = engine.incremental_init()
    buf = tone(440, 2.0)  # 200 mel frames
    st = engine.incremental_feed(st, buf)
    assert st.consumed_frames == 200
    assert st.enc_len == 100
    res = engine.incremental_decode(st)
    st2 = engine.incremental_feed(engine.incremental_init(), buf)
    assert engine.incremental_decode(st2).text == res.text


def test_incremental_split_feeds_match_single_feed(engine):
    """Feeding the stream in pieces must produce the same encoder state and
    transcript as feeding it at once (same blocks, same positions)."""
    buf = tone(440, 1.0)  # 100 mel frames -> 2 blocks
    st = engine.incremental_init()
    st = engine.incremental_feed(st, buf[:8000])
    st = engine.incremental_feed(st, buf)
    st_once = engine.incremental_feed(engine.incremental_init(), buf)
    assert st.enc_len == st_once.enc_len == 50
    assert engine.incremental_decode(st).text == engine.incremental_decode(st_once).text


def test_streaming_partials_ride_the_incremental_path(engine):
    stt = StreamingSTT(
        engine,
        partial_interval_s=0.2,
        endpointer=EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=100),
    )
    for i in range(4):
        stt.feed(tone(300 + 40 * i, 0.3))
    assert stt._inc is not None and stt._inc.enc_len > 0
    stt.feed(np.zeros(8_000, dtype=np.float32))  # endpoint closes the utterance
    assert len(stt._buf) == 0 and stt._inc is None


def test_null_stt_scripted():
    stt = NullSTT(scripted=[("final", "search for shoes")])
    events = stt.feed(np.zeros(160, dtype=np.float32))
    assert events == [("final", "search for shoes")]
    assert stt.feed(np.zeros(160, dtype=np.float32)) == []


def test_incremental_long_utterance_reanchors_instead_of_freezing(engine):
    """An utterance longer than the cross-KV budget must keep producing
    fresh partials: the state re-anchors on the most recent window (the
    round-1-review failure mode was a silent freeze at the budget)."""
    st = engine.incremental_init()
    st = engine.incremental_feed(st, tone(440, 4.0))  # 400 mel >> 2 s budget
    assert st.consumed_frames == 400  # consumption never stalled
    assert 0 < st.enc_len <= engine.cfg.enc_positions
    assert st.anchor_frames > 0
    assert engine.incremental_decode(st).n_frames == 400


def test_incremental_init_anchors_past_stale_silence(engine):
    """Pre-speech buffer content beyond one window is skipped at init, so
    buffered silence cannot spend the cross-KV budget."""
    total = 500  # mel frames already buffered
    st = engine.incremental_init(total)
    assert st.anchor_frames == max(0, total - engine.cfg.enc_positions)
    st = engine.incremental_feed(st, tone(440, 5.0))
    assert st.enc_len > 0 and st.consumed_frames == 500


def test_speculative_final_stays_exact_after_resumed_speech(engine):
    """A speculative final computed during a mid-utterance pause must be
    discarded when the speaker resumes — the delivered final must equal the
    direct transcription of the FULL utterance buffer."""
    ep = EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep)
    chunks = [
        tone(300, 0.5),
        np.zeros(int(16_000 * 0.16), dtype=np.float32),  # pause: spec fires
        tone(420, 0.4),  # resumed speech invalidates it
        np.zeros(16_000 // 2, dtype=np.float32),  # endpoint closes
    ]
    full = np.concatenate(chunks[:3])
    events = []
    for c in chunks:
        for ev in stt.feed(c):
            events.append(ev)
    finals = [t for k, t in events if k == "final"]
    assert finals, "endpoint must close the utterance"
    # deterministic engine: the delivered final must EQUAL the direct
    # transcription of the full utterance buffer (audio + the silence
    # consumed before the endpoint fired) — not the stale speculation
    sil = int(16_000 * 0.5)
    direct = engine.transcribe(np.concatenate([full, np.zeros(sil, np.float32)]))
    assert finals[0] == direct.text


def test_endpointer_short_blip_does_not_stick():
    """A sub-min_speech noise blip must not leave in_speech latched True
    forever (that blocked buffer trimming and fired wasted speculation)."""
    ep = EnergyEndpointer(trailing_silence_ms=200, min_speech_ms=200)
    ended = ep.feed(tone(440, 0.04))  # 40 ms blip
    assert ep.in_speech
    ended = ep.feed(np.zeros(16_000 // 2, dtype=np.float32))
    assert not ended  # too short to be an utterance
    assert not ep.in_speech  # ...and the state unlatched


def test_trailing_silence_property_needs_a_real_pause():
    ep = EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100)
    ep.feed(tone(300, 0.4))
    assert ep.in_speech and not ep.in_trailing_silence
    ep.feed(np.zeros(int(16_000 * 0.06), dtype=np.float32))  # 60 ms dip
    assert not ep.in_trailing_silence  # ordinary inter-word gap
    ep.feed(np.zeros(int(16_000 * 0.14), dtype=np.float32))  # 200 ms total
    assert ep.in_trailing_silence  # >= half the closing window


def test_spec_final_event_precedes_and_matches_confirmed_final(engine):
    """During an uninterrupted closing pause the stream emits
    ("spec_final", text) — the cue for downstream to start parsing inside
    the endpoint window — and the confirming final carries the SAME text
    (the speculation is reused, not recomputed)."""
    ep = EnergyEndpointer(trailing_silence_ms=300, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep)
    events = []
    events += stt.feed(tone(300, 0.5))
    # silence arrives in mic-sized (~60 ms) frames, as over the WS: the
    # speculation fires mid-pause (~150 ms) and the endpoint closes later
    # (300 ms) in a different feed call
    frame = 16_000 * 60 // 1000
    for j in range(0, 16_000, frame):
        events += stt.feed(np.zeros(frame, dtype=np.float32))
    kinds = [k for k, _ in events]
    specs = [t for k, t in events if k == "spec_final"]
    finals = [t for k, t in events if k == "final"]
    assert finals, "endpoint must close the utterance"
    assert specs, "a long closing pause must fire the speculation event"
    assert specs[-1] == finals[0]
    assert kinds.index("spec_final") < kinds.index("final")


def test_early_close_fires_before_the_window(engine):
    """VERDICT round-4 next #9: once the speculative parse is reported
    grammar-complete and the transcript stays stable, the utterance closes
    at early_close_ms instead of waiting out the full trailing window."""
    ep = EnergyEndpointer(trailing_silence_ms=600, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep,
                       early_close_ms=400.0)
    stt.feed(tone(300, 0.5))
    frame = 16_000 * 60 // 1000
    events, final_at = [], None
    for j in range(0, 1200, 60):
        for ev in stt.feed(np.zeros(frame, dtype=np.float32)):
            events.append(ev)
            if ev[0] == "spec_final":
                stt.parse_complete(ev[1])  # consumer: parse done, complete
        if any(k == "final" for k, _ in events):
            final_at = j + 60
            break
    specs = [t for k, t in events if k == "spec_final"]
    finals = [t for k, t in events if k == "final"]
    assert specs and finals
    assert finals[0] == specs[-1]  # the speculation is delivered, not redone
    # closed at ~420-480 ms of silence — far inside the 600 ms window
    assert final_at is not None and final_at < 540
    assert stt.early_closes == 1 and stt.window_closes == 0


def test_early_close_needs_the_parse_completion(engine):
    """No parse_complete notification -> the full window applies (the knob
    is armed but inert for consumers that never speculate)."""
    ep = EnergyEndpointer(trailing_silence_ms=600, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep,
                       early_close_ms=400.0)
    stt.feed(tone(300, 0.5))
    frame = 16_000 * 60 // 1000
    final_at = None
    for j in range(0, 1200, 60):
        if any(k == "final" for k, _ in stt.feed(np.zeros(frame, dtype=np.float32))):
            final_at = j + 60
            break
    assert final_at is not None and final_at >= 600
    assert stt.early_closes == 0 and stt.window_closes == 1


def test_early_close_stale_notification_is_inert(engine):
    """A parse_complete for some OTHER text (raced transcript revision)
    must never close the utterance early."""
    ep = EnergyEndpointer(trailing_silence_ms=600, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep,
                       early_close_ms=400.0)
    stt.feed(tone(300, 0.5))
    stt.parse_complete("completely different transcript")
    frame = 16_000 * 60 // 1000
    final_at = None
    for j in range(0, 1200, 60):
        if any(k == "final" for k, _ in stt.feed(np.zeros(frame, dtype=np.float32))):
            final_at = j + 60
            break
    assert final_at is not None and final_at >= 600
    assert stt.early_closes == 0 and stt.window_closes == 1


def test_early_close_resumed_speech_rearms(engine):
    """Speech resuming between the speculation and the early-close point
    invalidates everything: no early close, and the delivered final equals
    the direct transcription of the FULL buffer (same exactness contract as
    test_speculative_final_stays_exact_after_resumed_speech)."""
    ep = EnergyEndpointer(trailing_silence_ms=600, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep,
                       early_close_ms=400.0)
    frame = 16_000 * 60 // 1000
    events = []
    events += stt.feed(tone(300, 0.5))
    for _ in range(6):  # 360 ms pause: spec fires (300 ms), close (400) not yet
        for ev in stt.feed(np.zeros(frame, dtype=np.float32)):
            events.append(ev)
            if ev[0] == "spec_final":
                stt.parse_complete(ev[1])
    assert not any(k == "final" for k, _ in events)
    events += stt.feed(tone(420, 0.4))  # resume: speculation + notify stale
    silence_ms = 0
    for _ in range(20):
        new = stt.feed(np.zeros(frame, dtype=np.float32))
        silence_ms += 60
        # do NOT notify parse_complete for the new speculation: the final
        # must come from the full window
        events += new
        if any(k == "final" for k, _ in new):
            break
    finals = [t for k, t in events if k == "final"]
    assert finals and silence_ms >= 600
    assert stt.early_closes == 0 and stt.window_closes == 1


def test_early_close_disabled_with_none(engine):
    ep = EnergyEndpointer(trailing_silence_ms=600, min_speech_ms=100)
    stt = StreamingSTT(engine, partial_interval_s=60.0, endpointer=ep,
                       early_close_ms=None)
    stt.feed(tone(300, 0.5))
    frame = 16_000 * 60 // 1000
    final_at = None
    for j in range(0, 1200, 60):
        for ev in stt.feed(np.zeros(frame, dtype=np.float32)):
            if ev[0] == "spec_final":
                stt.parse_complete(ev[1])
            if ev[0] == "final":
                final_at = j + 60
        if final_at:
            break
    assert final_at is not None and final_at >= 600
    assert stt.early_closes == 0 and stt.window_closes == 1


def test_endpointer_force_end_respects_min_speech():
    ep = EnergyEndpointer(trailing_silence_ms=600, min_speech_ms=200)
    assert not ep.force_end()  # nothing to close
    ep.feed(tone(440, 0.08))  # 80 ms < min_speech 200 ms
    assert not ep.force_end()  # blip guard applies to early closes too
    assert ep.in_speech  # untouched
    ep.feed(tone(440, 0.3))
    assert ep.force_end()
    assert not ep.in_speech
